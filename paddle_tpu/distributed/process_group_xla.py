"""ProcessGroupXLA: collectives as compiled XLA programs over ICI/DCN
(the single most important native component per SURVEY §2.2 — the TPU
equivalent of fluid/distributed/collective/process_group_nccl.cc).

Design: each collective compiles (and caches, keyed by
(op, shape, dtype, group)) a one-collective jitted program over the global
device mesh spanning the group's processes, using shard_map + lax collective
primitives. Requires jax.distributed.initialize() (one process per host) —
done by init_parallel_env when launched multi-process.

Device residency: unlike the round-2 version, tensors stay jax arrays end
to end — `_get_local`/`_put_local` hand the raw device buffer to the
collective and accept the device result, and global arrays are assembled
with ``jax.make_array_from_single_device_arrays`` (zero host copies). This
is the XLA analog of NCCL's zero-copy comm-stream collectives
(process_group_nccl.cc:902-991).

P2P send/recv are compiled two-device ``collective_permute`` programs over
a pair mesh of the endpoints' devices (reference: process_group_nccl.cc
Send/Recv on comm streams; pp_utils/p2p_communication.py). Both endpoints
launch the same cached executable — the sender feeds the payload, the
receiver feeds a dummy and takes the permuted result. Steady-state PP
traffic therefore never touches the TCPStore.

Ordering: XLA programs on a TPU stream execute in issue order per device, so
the reference's comm-stream event chaining maps to plain issue order here;
Task.wait() is a no-op barrier on the jax async dispatch.

Coalescing (reference process_group.h:119-121): deferred all_reduces flush
as ONE compiled program over the tuple of buffers (one launch, one fusion
scope) via `_coalesced_all_reduce_impl`.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from .process_group import ProcessGroup, ReduceOp

__all__ = ["ProcessGroupXLA"]

_LAX_REDUCE = {
    ReduceOp.SUM: jax.lax.psum,
    ReduceOp.MAX: jax.lax.pmax,
    ReduceOp.MIN: jax.lax.pmin,
}


def _shard_map(fn, mesh, in_specs, out_specs):
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


def _P(*args):
    return jax.sharding.PartitionSpec(*args)


class ProcessGroupXLA(ProcessGroup):
    def __init__(self, store, rank: int, world_size: int, gid: int = 0,
                 group_ranks: Optional[List[int]] = None):
        super().__init__(rank, world_size, gid, group_ranks)
        self._store = store
        self._ranks = self._group_ranks
        # one process per host: the group's devices = one device per member
        # process (cross-host axis)
        self._mesh_cache = {}
        self._fn_cache = {}

    # ------------------------------------------------------ device plumbing
    def _device_of(self, process_rank: int):
        for d in jax.devices():
            if d.process_index == process_rank:
                return d
        raise RuntimeError(
            f"no devices for process {process_rank}; is jax.distributed "
            "initialized with one process per host?")

    def _global_mesh(self):
        """1-D mesh over one device per member process (cross-host axis)."""
        key = tuple(self._ranks)
        if key not in self._mesh_cache:
            devs = [self._device_of(r) for r in self._ranks]
            self._mesh_cache[key] = jax.sharding.Mesh(
                np.array(devs), axis_names=("x",))
        return self._mesh_cache[key]

    def _pair_mesh(self, a: int, b: int):
        """2-device mesh [sender, receiver] for p2p (group-local ranks)."""
        key = ("pair", a, b)
        if key not in self._mesh_cache:
            devs = [self._device_of(self._ranks[a]),
                    self._device_of(self._ranks[b])]
            self._mesh_cache[key] = jax.sharding.Mesh(
                np.array(devs), axis_names=("x",))
        return self._mesh_cache[key]

    def _wrap_global(self, arr, mesh):
        """Local shard (leading dim = per-process share) -> global array,
        staying on device (no host copy)."""
        sharding = jax.sharding.NamedSharding(mesh, _P("x"))
        dev = self._device_of(jax.process_index())
        shard = jax.device_put(jnp.asarray(arr), dev)
        n = mesh.devices.size
        gshape = (shard.shape[0] * n,) + shard.shape[1:]
        return jax.make_array_from_single_device_arrays(
            gshape, sharding, [shard])

    @staticmethod
    def _local_out(out):
        """This process's shard of a sharded result, still on device."""
        return out.addressable_shards[0].data

    def _run_collective(self, tag, arr, fn_builder):
        """Execute fn over the group mesh with the local array as this
        process's shard. arr and the result are device arrays."""
        mesh = self._global_mesh()
        arr = jnp.asarray(arr)
        cache_key = (tag, tuple(arr.shape), str(arr.dtype),
                     tuple(self._ranks))
        if cache_key not in self._fn_cache:
            self._fn_cache[cache_key] = fn_builder(mesh)
        fn = self._fn_cache[cache_key]
        out = fn(self._wrap_global(arr, mesh))
        return self._local_out(out)

    # ------------------------------------------------------------ reducers
    def _reduce_body(self, x, op):
        if op == ReduceOp.PROD:
            # no pprod primitive: gather contributions, reduce locally
            full = jax.lax.all_gather(x, "x", axis=0, tiled=True)
            return jnp.prod(full, axis=0, keepdims=True)
        red = _LAX_REDUCE.get(op, jax.lax.psum)
        r = red(x, "x")
        if op == ReduceOp.AVG:
            r = r / len(self._ranks)
        return r

    def _all_reduce_impl(self, arr, op):
        a = jnp.asarray(arr)[None]  # stack axis for the mesh dim

        def builder(mesh):
            @jax.jit
            @functools.partial(_shard_map, mesh=mesh,
                               in_specs=_P("x"), out_specs=_P("x"))
            def f(x):
                return self._reduce_body(x, op)

            return f

        return self._run_collective(f"allreduce{int(op)}", a, builder)[0]

    def _coalesced_all_reduce_impl(self, arrs, ops):
        """All deferred all_reduces in ONE compiled program (the XLA
        rendering of NCCL group-call coalescing)."""
        mesh = self._global_mesh()
        arrs = [jnp.asarray(a)[None] for a in arrs]
        key = ("coalesced",
               tuple((tuple(a.shape), str(a.dtype)) for a in arrs),
               tuple(int(op) for op in ops), tuple(self._ranks))
        if key not in self._fn_cache:
            specs = tuple(_P("x") for _ in arrs)
            ops_now = list(ops)

            @jax.jit
            @functools.partial(_shard_map, mesh=mesh,
                               in_specs=specs, out_specs=specs)
            def f(*xs):
                return tuple(self._reduce_body(x, op)
                             for x, op in zip(xs, ops_now))

            self._fn_cache[key] = f
        fn = self._fn_cache[key]
        outs = fn(*(self._wrap_global(a, mesh) for a in arrs))
        return [self._local_out(o)[0] for o in outs]

    def _broadcast_impl(self, arr, src):
        # src already translated to group-local by the base class
        src_idx = src
        a = jnp.asarray(arr)[None]

        def builder(mesh):
            @jax.jit
            @functools.partial(_shard_map, mesh=mesh,
                               in_specs=_P("x"), out_specs=_P("x"))
            def f(x):
                full = jax.lax.all_gather(x, "x", axis=0, tiled=True)
                return full[src_idx][None]

            return f

        return self._run_collective(f"broadcast{src_idx}", a, builder)[0]

    def _all_gather_impl(self, arr):
        a = jnp.asarray(arr)[None]
        n = len(self._ranks)

        def builder(mesh):
            @jax.jit
            @functools.partial(_shard_map, mesh=mesh,
                               in_specs=_P("x"), out_specs=_P("x"))
            def f(x):
                full = jax.lax.all_gather(x, "x", axis=0, tiled=True)
                return full[None]  # replicated result, shard dim 1

            return f

        out = self._run_collective("allgather", a, builder)
        return [out[0][i] for i in range(n)]

    def _reduce_impl(self, arr, dst, op):
        out = self._all_reduce_impl(arr, op)
        return out if self._rank == dst else arr

    def _reduce_scatter_impl(self, arrs, op):
        """True reduce_scatter: psum_scatter, not allreduce-then-slice
        (reference: process_group_nccl.cc ReduceScatter)."""
        stacked = jnp.stack([jnp.asarray(a) for a in arrs])  # [n, ...]
        if op in (ReduceOp.SUM, ReduceOp.AVG):
            nr = len(self._ranks)

            def builder(mesh):
                @jax.jit
                @functools.partial(_shard_map, mesh=mesh,
                                   in_specs=_P("x"), out_specs=_P("x"))
                def f(x):
                    # x: [n, ...] local contributions; each member ends up
                    # with the sum of everyone's slice [my_index]
                    r = jax.lax.psum_scatter(x, "x", scatter_dimension=0,
                                             tiled=False)
                    if op == ReduceOp.AVG:
                        r = r / nr
                    return r[None]

                return f

            return self._run_collective(f"reducescatter{int(op)}", stacked,
                                        builder)[0]
        # MAX/MIN/PROD: no scatter-reduce primitive; reduce then slice
        summed = self._all_reduce_impl(stacked, op)
        return summed[self._rank]

    def _scatter_impl(self, arrs, src, shape, dtype):
        """NCCL-style scatter: n-1 sends from root over the p2p path."""
        if self._rank == src:
            keep = None
            for r in range(len(self._ranks)):
                if r == src:
                    keep = jnp.asarray(arrs[r])
                else:
                    self._p2p_exec(jnp.asarray(arrs[r]), src, r)
            return keep
        return self._p2p_exec(jnp.zeros(tuple(shape), dtype), src,
                              self._rank, receiving=True)

    def _gather_impl(self, arr, dst):
        """NCCL-style gather: every member sends to dst over p2p."""
        arr = jnp.asarray(arr)
        if self._rank != dst:
            self._p2p_exec(arr, self._rank, dst)
            return []
        outs = []
        for r in range(len(self._ranks)):
            if r == dst:
                outs.append(arr)
            else:
                outs.append(self._p2p_exec(
                    jnp.zeros(arr.shape, arr.dtype), r, dst,
                    receiving=True))
        return outs

    def _all_to_all_impl(self, arrs):
        a = jnp.stack([jnp.asarray(x) for x in arrs])[None]  # [1, n, ...]

        def builder(mesh):
            @jax.jit
            @functools.partial(_shard_map, mesh=mesh,
                               in_specs=_P("x"), out_specs=_P("x"))
            def f(x):
                # x: [1, n, ...] per member; all_to_all over axis 1
                return jax.lax.all_to_all(x, "x", split_axis=1,
                                          concat_axis=1, tiled=False)

            return f

        out = self._run_collective("alltoall", a, builder)
        return [out[0][i] for i in range(len(self._ranks))]

    # ------------------------------------------------------------------ p2p
    def _p2p_exec(self, local, src, dst, receiving: bool = False):
        """Paired send/recv as one compiled collective_permute over the
        2-device [src, dst] mesh. BOTH endpoints launch the same cached
        executable (sender feeds payload, receiver a dummy); the permute
        moves the payload src->dst entirely over ICI/DCN. Zero store
        traffic (reference: process_group_nccl.cc Send/Recv; the r2
        store-pickle path this replaces was VERDICT missing #1)."""
        mesh = self._pair_mesh(src, dst)
        local = jnp.asarray(local)
        key = ("p2p", tuple(local.shape), str(local.dtype), src, dst,
               tuple(self._ranks))
        if key not in self._fn_cache:
            @jax.jit
            @functools.partial(_shard_map, mesh=mesh,
                               in_specs=_P("x"), out_specs=_P("x"))
            def f(x):
                return jax.lax.ppermute(x, "x", perm=[(0, 1)])

            self._fn_cache[key] = f
        fn = self._fn_cache[key]
        out = fn(self._wrap_global(local[None], mesh))
        res = self._local_out(out)[0]
        return res if receiving else None

    def _send_impl(self, arr, dst):
        self._p2p_exec(arr, self._rank, dst)

    def _recv_impl(self, src, shape, dtype):
        return self._p2p_exec(jnp.zeros(tuple(shape), dtype), src,
                              self._rank, receiving=True)

    def _sendrecv_impl(self, send_arr, peer, shape, dtype):
        """Bidirectional exchange with one peer as ONE compiled program
        (two opposing ppermutes over the pair mesh). This is the XLA
        rendering of batched isend/irecv: both endpoints launch the same
        executable, so the 1F1B steady state cannot order-deadlock the
        per-device program queues (reference: send_forward_recv_backward,
        pp_utils/p2p_communication.py:573)."""
        me = self._rank
        send_arr = jnp.asarray(send_arr)
        lo, hi = (me, peer) if me < peer else (peer, me)
        mesh = self._pair_mesh(lo, hi)
        i_am_lo = me == lo
        # canonical shapes: (lo->hi payload, hi->lo payload)
        if i_am_lo:
            s_lh, d_lh = tuple(send_arr.shape), send_arr.dtype
            s_hl, d_hl = tuple(shape), jnp.dtype(dtype)
        else:
            s_lh, d_lh = tuple(shape), jnp.dtype(dtype)
            s_hl, d_hl = tuple(send_arr.shape), send_arr.dtype
        key = ("sendrecv", s_lh, str(d_lh), s_hl, str(d_hl), lo, hi,
               tuple(self._ranks))
        if key not in self._fn_cache:
            @jax.jit
            @functools.partial(_shard_map, mesh=mesh,
                               in_specs=(_P("x"), _P("x")),
                               out_specs=(_P("x"), _P("x")))
            def f(x_lh, x_hl):
                return (jax.lax.ppermute(x_lh, "x", perm=[(0, 1)]),
                        jax.lax.ppermute(x_hl, "x", perm=[(1, 0)]))

            self._fn_cache[key] = f
        fn = self._fn_cache[key]
        if i_am_lo:
            a_lh, a_hl = send_arr, jnp.zeros(s_hl, d_hl)
        else:
            a_lh, a_hl = jnp.zeros(s_lh, d_lh), send_arr
        y_lh, y_hl = fn(self._wrap_global(a_lh[None], mesh),
                        self._wrap_global(a_hl[None], mesh))
        recv = y_hl if i_am_lo else y_lh
        return self._local_out(recv)[0]

    # ------------------------------------------------ buffered p2p fallback
    # Store-transport p2p for host-driven schedules whose per-pair op
    # order is NOT endpoint-symmetric (interleaved VPP: at matched edge
    # positions both endpoints can be senders, which would deadlock the
    # paired-program path). 1F1B/ZB use the compiled collective_permute
    # path; device-native VPP needs the 4-way combined op with
    # recv_prev/recv_next flags (Megatron
    # send_forward_backward_recv_forward_backward) — future work.
    def send_buffered(self, tensor, dst: int):
        import pickle

        dst = self._g2l(dst)
        key = self._p2p_buf_key(self._rank, dst)
        self._store.set(key, pickle.dumps(
            np.asarray(self._get_local(tensor)), protocol=4))

    def recv_buffered(self, tensor, src: int):
        import pickle

        src = self._g2l(src)
        key = self._p2p_buf_key(src, self._rank)
        self._put_local(tensor, pickle.loads(self._store.get(key)))

    def _p2p_buf_key(self, src, dst):
        if not hasattr(self, "_p2p_seq"):
            self._p2p_seq = {}
        k = (src, dst)
        self._p2p_seq[k] = self._p2p_seq.get(k, 0) + 1
        return f"pgx{self._gid}/p2pbuf/{src}->{dst}/{self._p2p_seq[k]}"

    # --------------------------------------------------- buffer residency
    def _get_local(self, tensor):
        return tensor._data  # device array, no host copy

    def _put_local(self, tensor, out):
        out = jnp.asarray(out)
        if out.dtype != tensor._data.dtype:
            out = out.astype(tensor._data.dtype)
        tensor._data = out

    def _barrier_impl(self):
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"pg{self._gid}_barrier")
