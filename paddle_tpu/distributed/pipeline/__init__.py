"""Device-native pipeline parallelism.

This package moves pipeline-stage boundary tensors between devices with
compiled collectives instead of the host store/rpc pickle path:

* :mod:`transport` — the p2p layer: ``ring_shift`` (a
  ``jax.lax.ppermute`` ring step inside ``shard_map``, with a Pallas
  ``make_async_remote_copy`` variant behind ``PADDLE_TPU_PP_RING=pallas``),
  the ``PADDLE_TPU_PP_TRANSPORT`` mode knob, and
  :class:`~paddle_tpu.distributed.pipeline.transport.FleetPayloadTransport`
  which carries FleetExecutor message payloads over ProcessGroup device
  p2p while DATA_IS_READY/STOP control stays on the rpc message bus.
* :mod:`schedule` — :class:`CompiledPipeline`: the whole 1F1B
  micro-batch schedule as ONE jit (fixed shapes, zero steady-state
  recompiles, trace-counter-asserted), plus the Engine bridge
  :class:`CompiledStagedTrainStep`.
* :mod:`overlap` — per-layer-bucket gradient synchronisation for
  comm/compute overlap (``PADDLE_TPU_PP_BUCKET_MB``): in-jit
  ``bucket_taps`` whose VJP issues one ``psum`` per bucket during the
  backward pass, and eager ``bucketed_allreduce`` issued per-bucket
  instead of one trailing barrier.
"""
from .transport import (  # noqa: F401
    FleetPayloadTransport,
    ensure_fleet_transport,
    get_fleet_transport,
    is_payload_descriptor,
    overlap_bucket_bytes,
    ring_impl,
    ring_shift,
    set_fleet_transport,
    transport_mode,
)
from .overlap import bucket_taps, bucketed_allreduce, make_buckets  # noqa: F401
from .schedule import CompiledPipeline, CompiledStagedTrainStep  # noqa: F401

__all__ = [
    "FleetPayloadTransport", "ensure_fleet_transport",
    "get_fleet_transport", "is_payload_descriptor",
    "overlap_bucket_bytes", "ring_impl", "ring_shift",
    "set_fleet_transport", "transport_mode",
    "bucket_taps", "bucketed_allreduce", "make_buckets",
    "CompiledPipeline", "CompiledStagedTrainStep",
]
