"""Comm/compute overlap: per-layer-bucket gradient synchronisation.

Two mechanisms, one knob (``PADDLE_TPU_PP_BUCKET_MB``):

* **In-jit bucket taps** (:func:`bucket_taps`) for the compiled pipeline
  step: identity in the forward pass, but each bucket's VJP issues one
  ``lax.psum`` over the data-parallel axis the moment that bucket's
  cotangents materialise — so gradient reduction is interleaved with the
  remaining backward compute by XLA's latency-hiding scheduler instead
  of trailing it. Only valid where no implicit reduction applies, i.e.
  when gradients are computed by AD *inside* the ``shard_map`` body:
  differentiating *through* ``shard_map`` already inserts the psum for
  replicated-in params (verified: taps there double-count by exactly the
  axis size).
* **Eager bucketed all-reduce** (:func:`bucketed_allreduce`) for the
  1F1B fleet path: issues one fused all-reduce per bucket (each dispatch
  is async, so earlier buckets overlap the remaining cooldown
  sends/recvs) instead of one whole-model trailing barrier.
"""
from __future__ import annotations

import functools
from typing import List, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ... import observability as _obs
from .transport import overlap_bucket_bytes

__all__ = ["make_buckets", "bucket_taps", "bucketed_allreduce"]

Axes = Union[str, Tuple[str, ...]]


def make_buckets(leaves: Sequence, bucket_bytes: int = None
                 ) -> List[List[int]]:
    """Group leaf indices into contiguous buckets of ~bucket_bytes.

    Leaves keep their order (bucket boundaries respect layer order, so a
    bucket's grads are complete as soon as backward passes its layers).
    """
    if bucket_bytes is None:
        bucket_bytes = overlap_bucket_bytes()
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    for i, leaf in enumerate(leaves):
        nbytes = int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        cur.append(i)
        cur_bytes += nbytes
        if cur_bytes >= bucket_bytes:
            buckets.append(cur)
            cur, cur_bytes = [], 0
    if cur:
        buckets.append(cur)
    return buckets


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _bucket_sync(axes: Axes, *xs):
    return xs


def _bucket_sync_fwd(axes: Axes, *xs):
    return xs, None


def _bucket_sync_bwd(axes: Axes, _, gs):
    return tuple(jax.lax.psum(g, axes) for g in gs)


_bucket_sync.defvjp(_bucket_sync_fwd, _bucket_sync_bwd)


def bucket_taps(leaves: Sequence, axes: Axes,
                bucket_bytes: int = None) -> List:
    """Thread param leaves through per-bucket psum taps (see module doc).

    Returns the leaves unchanged numerically; in the backward pass each
    bucket's gradients are ``psum``-reduced over ``axes`` as a group.
    Call inside a traced ``shard_map`` body on the params of a function
    whose gradients are computed by in-body AD.
    """
    buckets = make_buckets(leaves, bucket_bytes)
    from ...observability import profiler as _profiler

    if _profiler.profiling_enabled():  # ptlint: disable=jit-purity
        # trace-time geometry note for the DP overlap estimator: every
        # bucket's psum overlaps the remaining backward except the last
        total = sum(int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
                    for leaf in leaves)
        _profiler.note_bucket_overlap("dp", total, len(buckets))
    out = list(leaves)
    for idx in buckets:
        synced = _bucket_sync(axes, *[out[i] for i in idx])
        for j, i in enumerate(idx):
            out[i] = synced[j]
    return out


def bucketed_allreduce(params, group, bucket_bytes: int = None,
                       scale=None) -> None:
    """Eager per-bucket gradient all-reduce over ``group``.

    Thin entry point over the fleet fused reducer with the pipeline
    bucket knob applied; each bucket dispatch carries a
    ``pp.bucket_reduce`` span. Imported lazily to keep this package
    free of an eager-fleet import cycle.
    """
    from ..fleet.hybrid_parallel_util import \
        fused_allreduce_gradients_with_group

    if bucket_bytes is None:
        bucket_bytes = overlap_bucket_bytes()
    fused_allreduce_gradients_with_group(params, group, scale=scale,
                                         bucket_bytes=bucket_bytes)


def record_bucket_gauge(n: int) -> None:
    """Report how many overlap buckets a compiled step was built with."""
    if _obs.enabled():
        _obs.registry.gauge("pipeline.overlap_buckets").set(n)
