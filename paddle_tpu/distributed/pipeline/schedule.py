"""The whole 1F1B micro-batch pipeline schedule as ONE compiled program.

:class:`CompiledPipeline` runs every stage of a uniform pipeline on its
own device of a ``("pp"[, "dp"])`` mesh and executes the full
forward/backward/update for a batch of ``M`` micro-batches in a single
``jax.jit`` dispatch:

* Per-stage params are STACKED (leaves ``[S, ...]``) and sharded
  ``P("pp")`` so stage ``s``'s slice lives on device ``s``.
* A ``lax.scan`` over ``T = M + S - 1`` ticks drives the software
  pipeline: each tick every stage receives its upstream boundary tensor
  via :func:`~.transport.ring_shift` (XLA ``collective-permute`` — the
  payload never leaves device HBM), runs its stage function, and passes
  the result on. Stage 0 masks the ring's wrap-around edge with its own
  micro-batch input, which also zeroes cotangents through the wrap edge
  under AD.
* Gradients are computed by ``jax.value_and_grad`` INSIDE the
  ``shard_map`` body; the transpose of ``ring_shift`` is the reverse
  ring step, so the backward dependency DAG is exactly 1F1B's and XLA
  interleaves each stage's backward ticks with the remaining forward
  ticks of later micro-batches. Data-parallel gradient reduction is
  issued per layer bucket *during* backward by
  :func:`~.overlap.bucket_taps` (``PADDLE_TPU_PP_BUCKET_MB``), not as a
  trailing barrier.
* The optimizer update runs inside the same jit on the flat param list,
  so steady state is exactly one executable launch per train step:
  fixed shapes, zero recompiles (``trace_count`` asserts it, like the
  serving decode step).

:class:`CompiledStagedTrainStep` adapts a uniform
:class:`~..passes.pipeline_partition.StagedProgram` to this engine so
``Engine.fit`` can swap it in for the host-driven ``_StagedTrainStep``
when ``PADDLE_TPU_PP_TRANSPORT=device``.
"""
from __future__ import annotations

import logging
from typing import Callable, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ... import observability as _obs
from .overlap import bucket_taps, record_bucket_gauge, make_buckets
from .transport import ring_shift

logger = logging.getLogger("paddle_tpu.distributed.pipeline")

__all__ = ["CompiledPipeline", "CompiledStagedTrainStep"]


def _tree_flat(tree):
    return jax.tree_util.tree_flatten(tree)


class CompiledPipeline:
    """One-jit 1F1B pipeline over a ``("pp"[, "dp"])`` device mesh.

    Args:
        stage_fn: ``(stage_params, h) -> h`` — the per-stage compute; the
            SAME function for every stage (uniform pipeline), applied to
            stage ``s``'s slice of ``stacked_params``.
        stacked_params: pytree whose leaves are stacked per-stage arrays
            ``[S, ...]``.
        loss_fn: ``(extra_params, h_last, y_micro) -> scalar`` mean loss
            of one micro-batch (runs on the last stage; masked
            elsewhere).
        num_stages / num_micro: pipeline depth ``S`` and micro-batch
            count ``M`` (batch size must divide by ``M``).
        optimizer: functional optimizer (``init_state``/``update``) or
            None for loss/grad-only stepping.
        extra_params: pytree of params shared across stage boundaries
            (embeddings, head, final norm); replicated on every device.
        pre_fn: ``(extra_params, x_micro) -> h0`` input embedding to the
            stage-0 boundary tensor; identity when None.
        devices: flat device list (pp-major: ``pp * dp`` entries).
        dp: data-parallel degree (batch split across it; grads bucket-
            psummed over it during backward).
    """

    def __init__(self, stage_fn: Callable, stacked_params, loss_fn: Callable,
                 num_stages: int, num_micro: int, optimizer=None,
                 extra_params=None, pre_fn: Optional[Callable] = None,
                 devices: Optional[Sequence] = None, dp: int = 1,
                 bucket_bytes: Optional[int] = None):
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.pre_fn = pre_fn
        self.optimizer = optimizer
        self.S = int(num_stages)
        self.M = int(num_micro)
        self.dp = int(dp)
        self._bucket_bytes = bucket_bytes
        self.trace_count = 0

        if devices is None:
            devices = jax.devices()[: self.S * self.dp]
        devices = list(devices)
        if len(devices) < self.S * self.dp:
            raise ValueError(
                f"CompiledPipeline needs {self.S * self.dp} devices "
                f"(pp={self.S} x dp={self.dp}), got {len(devices)}")
        dev_grid = np.array(devices[: self.S * self.dp]).reshape(
            self.S, self.dp)
        if self.dp > 1:
            self.mesh = Mesh(dev_grid, ("pp", "dp"))
            self._x_spec = P(None, "dp")   # [M, mb, ...]: micro dim whole
            self._reduce_axes = ("pp", "dp")
        else:
            self.mesh = Mesh(dev_grid.reshape(self.S), ("pp",))
            self._x_spec = P()
            self._reduce_axes = ("pp",)

        stacked_sh = NamedSharding(self.mesh, P("pp"))
        repl_sh = NamedSharding(self.mesh, P())
        self.params = jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a), stacked_sh),
            stacked_params)
        self.extra = jax.tree_util.tree_map(
            lambda a: jax.device_put(jnp.asarray(a), repl_sh),
            extra_params if extra_params is not None else {})

        flat_p, _ = _tree_flat((self.params, self.extra))
        self.opt_state = optimizer.init_state(flat_p) \
            if optimizer is not None else {}
        self.n_buckets = len(make_buckets(flat_p, self._bucket_bytes))
        record_bucket_gauge(self.n_buckets)

        # one jit for the whole schedule; params/opt_state donated so
        # steady state updates in place (donation is a no-op on cpu)
        donate = (0, 1, 2) if jax.default_backend() != "cpu" else ()
        self._jit_step = jax.jit(self._step, donate_argnums=donate)

    # ------------------------------------------------------------- traced
    def _body(self, stacked, extra, xs, ys):
        """shard_map body: local 1F1B scan + in-body AD + bucketed psum."""
        S, M = self.S, self.M
        sidx = jax.lax.axis_index("pp")

        def objective(p):
            stacked_l, extra_l = p
            if self.dp > 1:
                leaves, tdef = _tree_flat(stacked_l)
                leaves = bucket_taps(leaves, "dp", self._bucket_bytes)
                stacked_l = jax.tree_util.tree_unflatten(tdef, leaves)
            e_leaves, e_def = _tree_flat(extra_l)
            if e_leaves:
                e_leaves = bucket_taps(e_leaves, self._reduce_axes,
                                       self._bucket_bytes)
                extra_l = jax.tree_util.tree_unflatten(e_def, e_leaves)
            stage_params = jax.tree_util.tree_map(lambda a: a[0], stacked_l)

            def embed(xm):
                return self.pre_fn(extra_l, xm) if self.pre_fn is not None \
                    else xm

            bspec = jax.eval_shape(
                embed, jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype))
            from ...observability import profiler as _profiler

            if _profiler.profiling_enabled():  # ptlint: disable=jit-purity
                # trace-time geometry note: one boundary activation hops
                # the ring per tick over M+S-1 ticks; the fill/drain
                # bubble's S-1 hops are the exposed ones
                hop = bspec.dtype.itemsize
                for d in bspec.shape:
                    hop *= int(d)
                _profiler.note_pipeline_overlap("pp", hop, M, S)

            def tick(carry, t):
                y_prev, acc = carry
                recv = ring_shift(y_prev, "pp", S)
                i_in = jnp.clip(t, 0, M - 1)
                xm = jax.lax.dynamic_index_in_dim(xs, i_in, 0,
                                                  keepdims=False)
                h_in = jnp.where(sidx == 0, embed(xm), recv)
                yv = self.stage_fn(stage_params, h_in)
                out_i = jnp.clip(t - (S - 1), 0, M - 1)
                old = jax.lax.dynamic_index_in_dim(acc, out_i, 0,
                                                   keepdims=False)
                acc = jax.lax.dynamic_update_index_in_dim(
                    acc, jnp.where(t >= S - 1, yv, old), out_i, 0)
                return (yv, acc), None

            y0 = jnp.zeros(bspec.shape, bspec.dtype)
            acc0 = jnp.zeros((M,) + tuple(bspec.shape), bspec.dtype)
            (_, acc), _ = jax.lax.scan(tick, (y0, acc0),
                                       jnp.arange(M + S - 1))
            losses = jax.vmap(
                lambda h, ym: self.loss_fn(extra_l, h, ym))(acc, ys)
            # local objective scaled so the per-bucket psums over dp give
            # exactly the global-mean gradient
            local = jnp.mean(losses) / self.dp
            return jnp.where(sidx == S - 1, local, 0.0)

        loss_local, grads = jax.value_and_grad(objective)(
            (stacked, extra))
        loss = jax.lax.psum(loss_local, self._reduce_axes)
        return loss, grads[0], grads[1]

    def _step(self, params, extra, opt_state, x, y):
        self.trace_count += 1  # ptlint: disable=jit-purity
        if _obs.enabled():  # ptlint: disable=jit-purity
            _obs.registry.counter("pipeline.compiles").inc()
        M = self.M
        xs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
        ys = y.reshape((M, y.shape[0] // M) + y.shape[1:])
        from jax.experimental.shard_map import shard_map
        pipe = shard_map(
            self._body, mesh=self.mesh,
            in_specs=(P("pp"), P(), self._x_spec, self._x_spec),
            out_specs=(P(), P("pp"), P()),
            check_rep=False)
        loss, g_stacked, g_extra = pipe(params, extra, xs, ys)
        flat_p, pdef = _tree_flat((params, extra))
        flat_g, _ = _tree_flat((g_stacked, g_extra))
        if self.optimizer is not None:
            new_flat, new_state = self.optimizer.update(
                flat_p, flat_g, opt_state)
            new_flat = [n.astype(p.dtype) for n, p in zip(new_flat, flat_p)]
        else:
            new_flat, new_state = flat_p, opt_state
        new_params, new_extra = jax.tree_util.tree_unflatten(pdef, new_flat)
        return loss, new_params, new_extra, new_state

    # -------------------------------------------------------------- eager
    def step(self, x, y):
        """Run one train step over the full batch; returns the loss array."""
        if _obs.enabled():
            _obs.registry.counter("pipeline.steps").inc()
        with _obs.span("pipeline.step", cat="pipeline",
                       args={"micro": self.M, "stages": self.S}):
            loss, self.params, self.extra, self.opt_state = self._jit_step(
                self.params, self.extra, self.opt_state,
                jnp.asarray(x), jnp.asarray(y))
        return loss

    def loss_and_grads(self, x, y):
        """Loss + grads without the optimizer update (parity testing)."""
        M = self.M
        xs = jnp.asarray(x).reshape(
            (M, x.shape[0] // M) + tuple(x.shape[1:]))
        ys = jnp.asarray(y).reshape(
            (M, y.shape[0] // M) + tuple(y.shape[1:]))
        from jax.experimental.shard_map import shard_map
        pipe = shard_map(
            self._body, mesh=self.mesh,
            in_specs=(P("pp"), P(), self._x_spec, self._x_spec),
            out_specs=(P(), P("pp"), P()),
            check_rep=False)
        return jax.jit(pipe)(self.params, self.extra, xs, ys)


class CompiledStagedTrainStep:
    """Engine bridge: a uniform ``StagedProgram`` on ``CompiledPipeline``.

    Drop-in for the host-driven ``_StagedTrainStep``: same
    ``__call__(*batch) -> Tensor(loss)`` contract including per-step
    writeback of updated params into the model's segment params. Raises
    ``ValueError`` at construction when the staged program is not
    uniform (differing per-stage param shapes) — callers fall back to
    the host path.
    """

    def __init__(self, staged, optimizer, micro: int,
                 devices: Optional[Sequence] = None):
        from ...core.tensor import Tensor  # noqa: F401  (writeback)

        self.staged = staged
        self.optimizer = optimizer
        self.micro = int(micro)
        stages = staged.stages
        seg_params = staged.segment_params
        n = len(stages)
        if n < 2:
            raise ValueError("compiled pipeline needs >= 2 stages")
        shapes0 = [(tuple(p.shape), str(p.dtype)) for p in seg_params[0]]
        for s in range(1, n):
            shapes_s = [(tuple(p.shape), str(p.dtype))
                        for p in seg_params[s]]
            if shapes_s != shapes0:
                raise ValueError(
                    "staged program is not uniform (stage %d params %s != "
                    "stage 0 %s); device-compiled pipeline requires "
                    "identical stages — use the host transport" %
                    (s, shapes_s, shapes0))
        stacked = [jnp.stack([jnp.asarray(seg_params[s][i]._data)
                              for s in range(n)])
                   for i in range(len(seg_params[0]))]
        stage0 = stages[0]

        def stage_fn(param_list, h):
            return stage0(param_list, h)

        def loss_fn(_extra, h, ym):
            return self.staged.loss_fn(h, ym)

        self.pipe = CompiledPipeline(
            stage_fn, stacked, loss_fn, num_stages=n, num_micro=self.micro,
            optimizer=optimizer, devices=devices)
        self._seg_params = seg_params
        self.trace_count = 0

    def __call__(self, *batch):
        from ...core.tensor import Tensor

        arrs = [b._data if isinstance(b, Tensor) else jnp.asarray(b)
                for b in batch]
        x, y = arrs[0], arrs[1]
        loss = self.pipe.step(x, y)
        self.trace_count = self.pipe.trace_count
        self._writeback()
        return Tensor(loss)

    def _writeback(self):
        for i, leaf in enumerate(self.pipe.params):
            for s, plist in enumerate(self._seg_params):
                plist[i]._data = leaf[s]
                self.staged.params[s][i] = leaf[s]

    def sync_params_to_model(self):
        self._writeback()

    def restore_state(self, opt_state=None):
        flat_p, _ = _tree_flat((self.pipe.params, self.pipe.extra))
        self.pipe.opt_state = opt_state if opt_state is not None else (
            self.optimizer.init_state(flat_p)
            if self.optimizer is not None else {})
