"""Pipeline p2p transport: compiled ring transfers + fleet payload channel.

Two distinct consumers share this module:

* The compiled 1F1B step (:mod:`.schedule`) calls :func:`ring_shift`
  INSIDE a traced ``shard_map`` body — a single ring step implemented as
  ``jax.lax.ppermute`` (lowered to XLA ``collective-permute``) or, behind
  ``PADDLE_TPU_PP_RING=pallas`` on TPU backends, a Pallas kernel that
  drives the inter-chip DMA directly via ``make_async_remote_copy``.
  Either way the boundary tensor never leaves device HBM.
* The eager FleetExecutor keeps its rpc message bus for CONTROL
  (DATA_IS_READY / DATA_IS_USELESS / STOP) but, when a
  :class:`FleetPayloadTransport` is registered, array payloads ride
  ProcessGroup device p2p instead of being pickled through the store/rpc
  path. The rpc message then carries only a small shape/dtype/seq
  descriptor (:func:`is_payload_descriptor`).

Transport selection (``PADDLE_TPU_PP_TRANSPORT``):

* ``auto`` (default) — device p2p when the process group supports
  compiled collectives (ProcessGroupXLA), host store/rpc otherwise.
* ``device`` — same as auto, and additionally opts the Engine into the
  fully-compiled pipeline step when the staged program is uniform.
* ``host``  — force the host store/rpc path everywhere (debug escape
  hatch; also what the parity tests compare against).
"""
from __future__ import annotations

import threading
from typing import Optional

import jax
import jax.numpy as jnp

from ...config import knobs
from ...core.tensor import Tensor
from ... import observability as _obs

__all__ = [
    "transport_mode", "ring_impl", "overlap_bucket_bytes", "ring_shift",
    "FleetPayloadTransport", "set_fleet_transport", "get_fleet_transport",
    "is_payload_descriptor",
]

_PAYLOAD_KEY = "__pp_payload__"


# ------------------------------------------------------------------ knobs
def transport_mode() -> str:
    """``PADDLE_TPU_PP_TRANSPORT``: ``auto`` | ``device`` | ``host``."""
    mode = knobs.get_str("PADDLE_TPU_PP_TRANSPORT").strip().lower()
    return mode if mode in ("auto", "device", "host") else "auto"


def ring_impl() -> str:
    """``PADDLE_TPU_PP_RING``: ``ppermute`` (default) | ``pallas``."""
    impl = knobs.get_str("PADDLE_TPU_PP_RING").strip().lower()
    return impl if impl in ("ppermute", "pallas") else "ppermute"


def overlap_bucket_bytes() -> int:
    """Gradient-sync bucket size from ``PADDLE_TPU_PP_BUCKET_MB`` (MB)."""
    mb = knobs.get_float("PADDLE_TPU_PP_BUCKET_MB")
    return max(1, int(mb * (1 << 20)))


# ------------------------------------------------- compiled ring transfers
def _ppermute_shift(x: jnp.ndarray, axis_name: str, size: int,
                    step: int = 1) -> jnp.ndarray:
    perm = [(i, (i + step) % size) for i in range(size)]
    return jax.lax.ppermute(x, axis_name, perm=perm)


def _pallas_shift_impl(x: jnp.ndarray, axis_name: str, size: int,
                       step: int) -> jnp.ndarray:
    """One ring step as a Pallas remote-DMA kernel (TPU only)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def kernel(src_ref, dst_ref, send_sem, recv_sem):
        my_id = jax.lax.axis_index(axis_name)
        neighbor = jax.lax.rem(my_id + step, size)
        rdma = pltpu.make_async_remote_copy(
            src_ref, dst_ref, send_sem, recv_sem,
            device_id=(neighbor,),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        rdma.wait()

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA],
        compiler_params=pltpu.TPUCompilerParams(collective_id=0),
    )(x)


def _make_pallas_shift(axis_name: str, size: int):
    """Differentiable forward ring step; VJP is the reverse ring step."""
    import functools

    @functools.partial(jax.custom_vjp)
    def shift(x):
        return _pallas_shift_impl(x, axis_name, size, 1)

    def fwd(x):
        return shift(x), None

    def bwd(_, g):
        # transpose of y_i = x_{i-1} is g_j -> position j-1: reverse step
        return (_pallas_shift_impl(g, axis_name, size, size - 1),)

    shift.defvjp(fwd, bwd)
    return shift


def ring_shift(x: jnp.ndarray, axis_name: str, size: int) -> jnp.ndarray:
    """Move ``x`` one step forward around the ``axis_name`` ring.

    Must be called inside a ``shard_map`` body mapped over ``axis_name``.
    Lowered to XLA ``collective-permute`` via ``lax.ppermute`` by
    default; with ``PADDLE_TPU_PP_RING=pallas`` on a TPU backend the
    transfer is a hand-rolled Pallas ``make_async_remote_copy`` ring
    kernel instead. Differentiable in both modes (``ppermute`` has a
    native transpose; the Pallas variant carries a custom VJP that runs
    the reverse ring step).
    """
    if ring_impl() == "pallas" and jax.default_backend() == "tpu":
        return _make_pallas_shift(axis_name, size)(x)
    return _ppermute_shift(x, axis_name, size, 1)


# ---------------------------------------------- fleet payload transport
class FleetPayloadTransport:
    """Carries FleetExecutor message payloads over device p2p.

    The rpc control message keeps its ordering/trace-ctx role but its
    payload becomes a descriptor; the tensor itself moves via
    ``ProcessGroup.send``/``recv`` (compiled pair-mesh collectives on
    ProcessGroupXLA) and stays in device memory end to end.

    Ordering contract: per (src, dst) direction, collectives are
    launched in ``seq`` order on the send side (seq assignment and
    launch are atomic under a per-destination lock) and the receiver
    serialises its recvs per source in the same ``seq`` order via a
    condition variable — rpc delivery order is irrelevant. Distinct
    (src, dst) pairs use distinct pair meshes and may interleave
    freely. Concurrent OPPOSING transfers between the same pair must
    use ``ProcessGroup.sendrecv`` (one fused program) — the fleet
    graph's payload edges are one-directional per pair, which is what
    this transport is specified for.
    """

    def __init__(self, pg, my_rank: int, timeout: float = 300.0):
        self._pg = pg
        self._rank = int(my_rank)
        self._timeout = timeout
        self._maps_lock = threading.Lock()
        self._send_locks = {}        # dst_rank -> Lock
        self._send_seq = {}          # dst_rank -> next seq to assign
        self._recv_cv = {}           # src_rank -> Condition
        self._recv_next = {}         # src_rank -> next seq to accept

    def _send_lock(self, dst: int) -> threading.Lock:
        with self._maps_lock:
            return self._send_locks.setdefault(dst, threading.Lock())

    def _cv(self, src: int) -> threading.Condition:
        with self._maps_lock:
            return self._recv_cv.setdefault(src, threading.Condition())

    def send(self, payload, dst_rank: int, post=None) -> dict:
        """Ship ``payload`` to ``dst_rank``; returns the rpc descriptor.

        ``post`` (descriptor -> None), when given, is invoked while the
        per-destination lock is still held, so the control-message post
        order matches the collective launch order exactly — the
        receiver's single rpc dispatcher then always sees descriptors
        in ``seq`` order and never parks on the ordering condition.
        """
        arr = payload._data if isinstance(payload, Tensor) \
            else jnp.asarray(payload)
        with self._send_lock(dst_rank):
            seq = self._send_seq.get(dst_rank, 0)
            self._send_seq[dst_rank] = seq + 1
            with _obs.span("pp.send", cat="pipeline",
                           args={"transport": "device", "dst": dst_rank,
                                 "seq": seq}):
                self._pg.send(Tensor(arr), dst_rank)
            desc = {_PAYLOAD_KEY: True,
                    "shape": tuple(int(d) for d in arr.shape),
                    "dtype": str(arr.dtype), "seq": seq,
                    "src": self._rank}
            if post is not None:
                post(desc)
        if _obs.enabled():
            nbytes = int(arr.size) * jnp.dtype(arr.dtype).itemsize
            _obs.registry.counter("pipeline.p2p_bytes",
                                  {"transport": "device"}).inc(nbytes)
            _obs.registry.counter("pipeline.p2p_messages",
                                  {"transport": "device"}).inc()
        return desc

    def recv(self, desc: dict):
        """Blocking ordered receive for a payload descriptor."""
        src, seq = int(desc["src"]), int(desc["seq"])
        cv = self._cv(src)
        with cv:
            deadline = self._timeout
            while self._recv_next.get(src, 0) != seq:
                if not cv.wait(timeout=deadline):
                    raise TimeoutError(
                        f"pipeline transport: seq {seq} from rank {src} "
                        f"never became current "
                        f"(next={self._recv_next.get(src, 0)})")
            buf = Tensor(jnp.zeros(desc["shape"], desc["dtype"]))
            with _obs.span("pp.recv", cat="pipeline",
                           args={"transport": "device", "src": src,
                                 "seq": seq}):
                self._pg.recv(buf, src)
            self._recv_next[src] = seq + 1
            cv.notify_all()
        if _obs.enabled():
            arr = buf._data
            nbytes = int(arr.size) * jnp.dtype(arr.dtype).itemsize
            _obs.registry.counter("pipeline.p2p_bytes",
                                  {"transport": "device"}).inc(nbytes)
        return buf._data


def is_payload_descriptor(obj) -> bool:
    return isinstance(obj, dict) and obj.get(_PAYLOAD_KEY) is True


_fleet_transport: Optional[FleetPayloadTransport] = None
_fleet_transport_lock = threading.Lock()


def set_fleet_transport(t: Optional[FleetPayloadTransport]) -> None:
    global _fleet_transport
    with _fleet_transport_lock:
        _fleet_transport = t


def get_fleet_transport() -> Optional[FleetPayloadTransport]:
    return _fleet_transport


def ensure_fleet_transport() -> Optional[FleetPayloadTransport]:
    """Register a :class:`FleetPayloadTransport` over the default
    collective process group, if one exists and the transport knob
    allows device payloads. Idempotent; returns the live transport (or
    None when the store/rpc path must carry payloads — no collective
    group, or ``PADDLE_TPU_PP_TRANSPORT=host``)."""
    global _fleet_transport
    mode = transport_mode()
    if mode == "host":
        return None
    with _fleet_transport_lock:
        if _fleet_transport is not None:
            return _fleet_transport
        try:
            from .. import collective as _coll

            group = _coll._default_group
        except Exception:
            return None
        if group is None:
            return None
        pg = getattr(group, "process_group", None)
        if pg is None or not (hasattr(pg, "send") and hasattr(pg, "recv")):
            return None
        size = pg.size() if callable(getattr(pg, "size", None)) else 0
        if size < 2:
            return None  # single-process group: nothing to ship p2p
        if mode == "auto" and pg.__class__.__name__ != "ProcessGroupXLA":
            # auto engages device payloads only where p2p compiles to
            # device collectives; PADDLE_TPU_PP_TRANSPORT=device opts
            # store-backed groups in explicitly (parity tests)
            return None
        rank = pg.rank() if callable(getattr(pg, "rank", None)) \
            else getattr(pg, "rank", 0)
        _fleet_transport = FleetPayloadTransport(pg, rank)
        return _fleet_transport
