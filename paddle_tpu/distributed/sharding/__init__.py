"""group_sharded_parallel entry (reference:
python/paddle/distributed/sharding/group_sharded.py)."""
from __future__ import annotations

from ..fleet.sharding_optimizer import (
    GroupShardedOptimizerStage2,
    GroupShardedStage2,
    GroupShardedStage3,
)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def group_sharded_parallel(model, optimizer, level, scaler=None, group=None,
                           offload=False, sync_buffers=False,
                           buffer_max_size=2 ** 23, segment_size=2 ** 20,
                           sync_comm=False, dp_group=None,
                           exclude_layer=None):
    """reference: distributed/sharding/group_sharded.py
    group_sharded_parallel. level: os | os_g | p_g_os."""
    if group is None:
        from ..collective import get_group

        group = get_group(0)
    if level == "os":
        from ..fleet.sharding_optimizer import DygraphShardingOptimizer

        opt = DygraphShardingOptimizer(optimizer, group=group)
        return model, opt, scaler
    if level == "os_g":
        opt = GroupShardedOptimizerStage2(
            list(model.parameters()), optimizer, group=group, offload=offload)
        wrapped = GroupShardedStage2(model, opt, group=group,
                                     sync_buffers=sync_buffers,
                                     buffer_max_size=buffer_max_size)
        return wrapped, opt, scaler
    if level == "p_g_os":
        wrapped = GroupShardedStage3(model, optimizer, group=group,
                                     sync_buffers=sync_buffers,
                                     segment_size=segment_size,
                                     sync_comm=sync_comm)
        return wrapped, optimizer, scaler
    raise ValueError(f"unknown sharding level {level!r}; use os | os_g | p_g_os")


def save_group_sharded_model(model, output, optimizer=None):
    from ...framework.io_utils import save

    sd = model.state_dict()
    save(sd, output + ".pdmodel" if not output.endswith(".pdmodel")
         else output)
    if optimizer is not None:
        save(optimizer.state_dict(), output + ".pdopt")
