"""paddle.signal parity: frame / overlap_add / stft / istft
(reference: python/paddle/signal.py — frame:42, overlap_add:167,
stft:272, istft:449). All pure jnp; the FFTs lower to XLA's native FFT.
"""
from __future__ import annotations

import jax.numpy as jnp

from .ops._helpers import as_tensor, run_op, unwrap

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_idx(n, frame_length, hop_length):
    """[num_frames, frame_length] gather indices — the single framing
    definition shared by frame() and stft()."""
    num = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num) * hop_length
    return starts[:, None] + jnp.arange(frame_length)[None, :]


def _frame_core(a, frame_length, hop_length, axis):
    """Frame along ``axis`` with the reference layout —
    [frame_length, num_frames, ...] for axis=0 and
    [..., frame_length, num_frames] for axis=-1 (frame_length always
    precedes num_frames)."""
    ax = axis % a.ndim
    idx = _frame_idx(a.shape[ax], frame_length, hop_length)
    fr = jnp.take(a, idx.reshape(-1), axis=ax)
    new_shape = (a.shape[:ax] + idx.shape + a.shape[ax + 1:])
    fr = fr.reshape(new_shape)      # [..., num, frame_length, ...]
    # reference layout puts frame_length first in both conventions
    return jnp.swapaxes(fr, ax, ax + 1)


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice ``x`` into overlapping frames along ``axis`` (reference:
    signal.py frame — [frame_length, num_frames, ...] for axis=0,
    [..., frame_length, num_frames] for axis=-1)."""

    def fn(a):
        return _frame_core(a, frame_length, hop_length, axis)

    return run_op(fn, [as_tensor(x)], name="frame")


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of frame: overlap-add [..., frame_length, num_frames]
    (axis=-1) back to a signal."""

    def fn(a):
        if axis in (-1, a.ndim - 1):
            frames = jnp.swapaxes(a, -1, -2)  # [..., num, fl]
        else:
            # reference axis=0 layout [fl, num, ...] -> [..., num, fl]
            frames = jnp.moveaxis(a, (1, 0), (-2, -1))
        out = _ola_core(frames, hop_length)
        if axis not in (-1, a.ndim - 1):
            out = jnp.moveaxis(out, -1, 0)
        return out

    return run_op(fn, [as_tensor(x)], name="overlap_add")


def _ola_core(frames, hop_length):
    """Overlap-add [..., num, fl] -> [..., out_len] — the single OLA
    definition shared by overlap_add() and istft()."""
    num, fl = frames.shape[-2], frames.shape[-1]
    out_len = (num - 1) * hop_length + fl
    lead = frames.shape[:-2]
    out = jnp.zeros(lead + (out_len,), frames.dtype)
    idx = (jnp.arange(num) * hop_length)[:, None] + \
        jnp.arange(fl)[None, :]
    return out.at[..., idx.reshape(-1)].add(
        frames.reshape(lead + (num * fl,)))


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    """Short-time Fourier transform (reference: signal.py:272). Returns
    [..., n_fft//2+1 (onesided) | n_fft, num_frames] complex."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    win = None if window is None else unwrap(as_tensor(window))

    def fn(a, *w):
        x = a
        if center:
            pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
            x = jnp.pad(x, pad, mode=pad_mode)
        idx = _frame_idx(x.shape[-1], n_fft, hop_length)
        frames = x[..., idx]                     # [..., num, n_fft]
        if w:
            wv = w[0]
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                wv = jnp.zeros((n_fft,), wv.dtype).at[
                    lp:lp + win_length].set(wv)
            frames = frames * wv
        spec = jnp.fft.rfft(frames, n=n_fft) if onesided \
            else jnp.fft.fft(frames, n=n_fft)
        if normalized:
            spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        return jnp.swapaxes(spec, -1, -2)        # [..., freq, num]

    ts = [as_tensor(x)] + ([as_tensor(window)] if win is not None else [])
    return run_op(fn, ts, name="stft")


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-square OLA normalization (reference:
    signal.py:449)."""
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft

    def fn(a, *w):
        spec = jnp.swapaxes(a, -1, -2)           # [..., num, freq]
        if normalized:
            spec = spec * jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
        frames = jnp.fft.irfft(spec, n=n_fft) if onesided \
            else jnp.fft.ifft(spec, n=n_fft)
        if not return_complex:
            frames = jnp.real(frames)
        if w:
            wv = w[0]
            if win_length < n_fft:
                lp = (n_fft - win_length) // 2
                wv = jnp.zeros((n_fft,), wv.dtype).at[
                    lp:lp + win_length].set(wv)
        else:
            wv = jnp.ones((n_fft,), frames.dtype)
        num = frames.shape[-2]
        out_len = (num - 1) * hop_length + n_fft
        sig = _ola_core(frames * wv, hop_length)
        den = _ola_core(jnp.broadcast_to(
            (wv * wv).astype(jnp.float32), (num, n_fft)), hop_length)
        sig = sig / jnp.maximum(den, 1e-10)
        if center:
            sig = sig[..., n_fft // 2:out_len - n_fft // 2]
        if length is not None:
            sig = sig[..., :length]
        return sig

    win = None if window is None else unwrap(as_tensor(window))
    ts = [as_tensor(x)] + ([as_tensor(window)] if win is not None else [])
    return run_op(fn, ts, name="istft")
