"""The typed registry of every ``PADDLE_TPU_*`` environment knob.

Every environment read of a ``PADDLE_TPU_*`` name anywhere in the tree
MUST go through this module — ptlint's ``env-knobs`` pass rejects raw
``os.environ`` reads and accessor calls on undeclared names, and the
README env-var tables are generated from this schema by
``tools/gen_env_docs.py`` (drift is a lint finding too).

Design constraints:

* **stdlib-only, no paddle_tpu imports.** Observability modules read
  knobs at import time, so this module must sit below everything; it is
  also loaded standalone (``importlib.util.spec_from_file_location``)
  by repo tools that must not import jax (``tools/perfdiff.py``,
  ``tools/gen_env_docs.py``, ptlint, ``__graft_entry__``).
* **Declared type + default, call-site default override.** The schema
  default is the documented one; a call site may pass its own default
  (e.g. ``PADDLE_TPU_SYNTH_SAMPLES`` defaults per dataset) without
  redeclaring the knob.
* **Lenient parsing.** An unset or empty value yields the default; a
  malformed numeric value ALSO yields the default (a typo'd knob must
  degrade to documented behavior, not crash a training job at import).
* **Bool semantics**: ``"", "0", "false", "off", "no"`` (any case)
  are False, anything else set is True.
"""
from __future__ import annotations

import os
from typing import Any, Dict, Iterable, NamedTuple, Optional

__all__ = ["Knob", "KNOBS", "get_str", "get_int", "get_float",
           "get_bool", "is_set", "get_raw", "iter_knobs", "validate"]

_TYPES = ("str", "int", "float", "bool")


class Knob(NamedTuple):
    name: str
    type: str           # str | int | float | bool
    default: Any        # documented default; None = unset/derived
    subsystem: str      # README table section
    doc: str            # one line


def _k(name: str, type: str, default: Any, subsystem: str,
       doc: str) -> Knob:
    assert type in _TYPES, type
    return Knob("PADDLE_TPU_" + name, type, default, subsystem, doc)


_ALL = (
    # ------------------------------------------------------- serving
    _k("SERVE_SLOTS", "int", 8, "serving",
       "Max concurrent decode slots per serving engine."),
    _k("SERVE_BLOCK_SIZE", "int", 16, "serving",
       "KV page size in token slots."),
    _k("SERVE_NUM_BLOCKS", "int", 512, "serving",
       "KV pool size in pages (shared across layers)."),
    _k("SERVE_PREFILL_CHUNK", "int", 32, "serving",
       "Prefill tokens admitted per engine step."),
    _k("SERVE_RAGGED", "str", "auto", "serving",
       "Single-dispatch ragged step: auto|on|off "
       "(off restores the two-program decode+prefill layout)."),
    _k("SERVE_TOKEN_BUDGET", "int", None, "serving",
       "Token axis of the ragged step "
       "(default: SERVE_SLOTS + SERVE_PREFILL_CHUNK)."),
    # ------------------------------------------------------- cluster
    _k("CLUSTER_REPLICAS", "int", 2, "cluster",
       "Replica count for bench --cluster runs."),
    _k("CLUSTER_MAX_QUEUE", "int", 32, "cluster",
       "Router admission queue depth before shedding."),
    _k("CLUSTER_BEAT", "float", 0.5, "cluster",
       "Cluster control-plane heartbeat interval (s)."),
    _k("CLUSTER_LEASE_TIMEOUT", "float", 2.0, "cluster",
       "Replica lease freshness timeout (s)."),
    _k("AUTOSCALE_MIN", "int", 1, "cluster",
       "Autoscaler floor (replicas)."),
    _k("AUTOSCALE_MAX", "int", 4, "cluster",
       "Autoscaler ceiling (replicas)."),
    _k("AUTOSCALE_UP_TICKS", "int", 3, "cluster",
       "Consecutive pressured ticks before scale-out."),
    _k("AUTOSCALE_IDLE_TICKS", "int", 10, "cluster",
       "Consecutive idle ticks before scale-in."),
    _k("AUTOSCALE_COOLDOWN_TICKS", "int", 10, "cluster",
       "Ticks to hold after any scaling action."),
    _k("AUTOSCALE_QUEUE_HWM", "int", 4, "cluster",
       "Queue depth counting as sustained pressure."),
    _k("AUTOSCALE_SHED_THRESHOLD", "float", 0.0, "cluster",
       "Shed-rate fraction counting as pressure (0 = any shed)."),
    # ------------------------------------------------------ kv_store
    _k("KV_TIER", "str", "host", "kv_store",
       "Cluster KV tier: off (index only) | host (adds host-RAM "
       "spill tier)."),
    _k("KV_HOST_MB", "float", 64.0, "kv_store",
       "Host-RAM tier capacity (MiB of int8 spills)."),
    _k("KV_PUMP_S", "float", 0.02, "kv_store",
       "Async promote/demote pump interval (s)."),
    # ------------------------------------------------- observability
    _k("TELEMETRY", "bool", False, "observability",
       "Master switch for the metrics registry."),
    _k("TRACE_CAPACITY", "int", 65536, "observability",
       "Finished-span ring capacity (oldest dropped first)."),
    _k("FLIGHT_CAPACITY", "int", 4096, "observability",
       "Flight-recorder event ring capacity."),
    _k("DUMP_DIR", "str", None, "observability",
       "Crash/debug bundle output directory."),
    _k("ACCESS_LOG", "str", None, "observability",
       "Serving access-log path (JSONL)."),
    _k("HEALTH", "str", "off", "observability",
       "Non-finite grad policy: off|warn|skip|raise."),
    _k("WINDOW_S", "float", 60.0, "observability",
       "Rolling telemetry window span (s)."),
    _k("WINDOW_BUCKETS", "int", 12, "observability",
       "Buckets per rolling window."),
    _k("SLO_TTFT_P99_MS", "float", 2000.0, "observability",
       "SLO objective: p99 time-to-first-token (ms)."),
    _k("SLO_TOKEN_GAP_P99_MS", "float", 500.0, "observability",
       "SLO objective: p99 inter-token gap (ms)."),
    _k("SLO_SHED_RATE", "float", 0.05, "observability",
       "SLO objective: max shed-rate fraction."),
    _k("SLO_FAST_S", "float", 10.0, "observability",
       "Fast burn-rate window (s)."),
    _k("SLO_WINDOW_S", "float", 0.0, "observability",
       "Slow burn-rate window (s); 0 = the windows' full span."),
    _k("SLO_PAGE_BURN", "float", 4.0, "observability",
       "Burn-rate multiple that pages (BURN state)."),
    _k("SLO_UTIL_LOW", "float", 0.25, "observability",
       "Utilization below which scale-in is suggested."),
    _k("PROFILE", "str", "off", "observability",
       "Step attribution profiler: off|on|sample:N."),
    _k("PROF_PEAK_FLOPS", "float", None, "observability",
       "Override peak FLOP/s for MFU math."),
    _k("PROF_LINK_GBPS", "float", None, "observability",
       "Override interconnect GB/s for overlap estimators."),
    _k("PROFILE_DIR", "str", "/tmp/paddle_tpu_profile", "observability",
       "Device-trace output directory (jax profiler)."),
    # --------------------------------------------------- distributed
    _k("PP_TRANSPORT", "str", "auto", "distributed",
       "Pipeline stage transport: auto|device|host."),
    _k("PP_RING", "str", "ppermute", "distributed",
       "Pipeline ring collective implementation."),
    _k("PP_BUCKET_MB", "float", 4.0, "distributed",
       "Overlap bucket size (MiB) for DP grad fusion / PP ring."),
    _k("COMM_TIMEOUT", "float", None, "distributed",
       "Collective watchdog timeout (s); unset disables."),
    _k("PURE_PY_STORE", "bool", False, "distributed",
       "Force the pure-Python TCPStore (skip the native daemon)."),
    _k("RPC_RETRIES", "int", 4, "distributed",
       "Max re-posts of a lost rpc request."),
    _k("RPC_RETRY_BASE_DELAY", "float", 0.25, "distributed",
       "Base backoff (s) of the rpc retransmit schedule."),
    _k("ELASTIC", "bool", False, "distributed",
       "Opt the auto-parallel engine into elastic membership."),
    _k("ELASTIC_BEAT", "float", 0.5, "elastic",
       "Elastic membership heartbeat interval (s)."),
    _k("ELASTIC_TIMEOUT", "float", 10.0, "elastic",
       "Elastic lease timeout (s) before a member is declared dead."),
    _k("ELASTIC_SNAP_FREQ", "int", 10, "elastic",
       "Steps between peer snapshots."),
    _k("ELASTIC_STRAGGLER_FACTOR", "float", 3.0, "elastic",
       "Step-time multiple over the median that flags a straggler."),
    _k("ELASTIC_STRAGGLER_POLICY", "str", "flag", "elastic",
       "Straggler handling: flag|demote."),
    _k("ELASTIC_MAX_NODES", "int", 16, "elastic",
       "Upper bound on elastic group size."),
    # ------------------------------------------------------------ ps
    _k("PS_TIMEOUT", "float", 30.0, "ps",
       "Whole-op deadline (s) for one sharded pull/push."),
    _k("PS_RPC_TIMEOUT", "float", 2.0, "ps",
       "Per-rpc timeout (s) inside a sharded op."),
    _k("PS_BEAT", "float", 0.15, "ps",
       "PS primary heartbeat interval (s)."),
    _k("PS_FAILOVER_TIMEOUT", "float", 5.0, "ps",
       "Lease silence (s) before a replica takes over a shard."),
    _k("PS_REPLICATION", "str", "auto", "ps",
       "Chain replication mode: auto|on|off."),
    # ---------------------------------------------------- resilience
    _k("FAULT_PLAN", "str", None, "resilience",
       "Fault injection plan: 'site:kind[=value]@spec,...'."),
    _k("FAULT_SEED", "int", 0, "resilience",
       "Seed for probabilistic fault plans."),
    _k("RETRY_MAX_ATTEMPTS", "int", 5, "resilience",
       "Default retry policy: max attempts."),
    _k("RETRY_BASE_DELAY", "float", 0.05, "resilience",
       "Default retry policy: base backoff (s)."),
    _k("RETRY_MAX_DELAY", "float", 2.0, "resilience",
       "Default retry policy: backoff cap (s)."),
    _k("RETRY_SEED", "int", 0, "resilience",
       "Seed for retry jitter rngs."),
    # -------------------------------------------------------- fusion
    _k("FUSION", "str", "auto", "fusion",
       "Fused-epilogue dispatch: auto|on|off."),
    _k("MM_QUANT", "str", "off", "fusion",
       "Quantized GEMM path: off|int8|fp8."),
    _k("TP_OVERLAP", "str", "auto", "fusion",
       "TP comm/compute overlap: auto|on|off|pallas."),
    _k("TP_OVERLAP_CHUNKS", "int", 2, "fusion",
       "Ring chunks per overlapped TP GEMM."),
    # ---------------------------------------------------------- data
    _k("DATA_HOME", "str", "~/.cache/paddle_tpu", "data",
       "Dataset cache root."),
    _k("SYNTH_SAMPLES", "int", 32, "data",
       "Synthetic-fallback dataset size (datasets override the "
       "default per split)."),
    # --------------------------------------------------------- tools
    _k("BENCH", "str", None, "tools",
       "Bench model-size preset override (e.g. '125m')."),
    _k("OPS_SNAPSHOT", "str", None, "tools",
       "Write/read op-coverage snapshots at this path."),
    _k("PERFDIFF_BASE", "str", None, "tools",
       "Baseline metrics file/dir for tools/perfdiff.py."),
    _k("PERFDIFF_NOISE", "float", 0.10, "tools",
       "Relative noise floor for perfdiff regressions."),
    _k("WRITE_MANIFEST", "bool", False, "tools",
       "Let test_op_coverage rewrite the op manifest."),
    _k("KEEP_BACKEND_LOGS", "bool", False, "tools",
       "Keep spawned-backend log files after a clean exit."),
)

KNOBS: Dict[str, Knob] = {k.name: k for k in _ALL}
assert len(KNOBS) == len(_ALL), "duplicate knob declaration"

_FALSE = ("", "0", "false", "off", "no")
_MISSING = object()


def _declared(name: str, want: str) -> Knob:
    k = KNOBS.get(name)
    if k is None:
        raise KeyError(
            "undeclared env knob %r — declare it in "
            "paddle_tpu/config/knobs.py" % (name,))
    if k.type != want:
        raise TypeError("knob %s is declared %s, read as %s"
                        % (name, k.type, want))
    return k


def get_raw(name: str) -> Optional[str]:
    """The raw env string (declared names only), or None when unset."""
    if name not in KNOBS:
        raise KeyError("undeclared env knob %r" % (name,))
    return os.environ.get(name)


def is_set(name: str) -> bool:
    """Whether the knob is present in the environment at all."""
    if name not in KNOBS:
        raise KeyError("undeclared env knob %r" % (name,))
    return name in os.environ


def get_str(name: str, default: Any = _MISSING) -> Optional[str]:
    k = _declared(name, "str")
    v = os.environ.get(name)
    if v is None or not v.strip():
        return k.default if default is _MISSING else default
    return v


def get_int(name: str, default: Any = _MISSING) -> Optional[int]:
    k = _declared(name, "int")
    v = os.environ.get(name)
    d = k.default if default is _MISSING else default
    if v is None or not v.strip():
        return d
    try:
        return int(v)
    except ValueError:
        return d


def get_float(name: str, default: Any = _MISSING) -> Optional[float]:
    k = _declared(name, "float")
    v = os.environ.get(name)
    d = k.default if default is _MISSING else default
    if v is None or not v.strip():
        return d
    try:
        return float(v)
    except ValueError:
        return d


def get_bool(name: str, default: Any = _MISSING) -> bool:
    k = _declared(name, "bool")
    v = os.environ.get(name)
    if v is None:
        return bool(k.default if default is _MISSING else default)
    return v.strip().lower() not in _FALSE


def iter_knobs() -> Iterable[Knob]:
    """Declared knobs in declaration (= README table) order."""
    return iter(_ALL)


def validate() -> None:
    """Schema self-check: unique names, known types, prefix, doc."""
    seen = set()
    for k in _ALL:
        assert k.name.startswith("PADDLE_TPU_"), k.name
        assert k.name not in seen, "duplicate knob %s" % k.name
        seen.add(k.name)
        assert k.type in _TYPES, (k.name, k.type)
        assert k.subsystem and k.doc, k.name
        if k.default is not None:
            want = {"str": str, "int": int, "float": float,
                    "bool": bool}[k.type]
            assert isinstance(k.default, want), (k.name, k.default)


validate()
