"""Typed configuration layer: the ``PADDLE_TPU_*`` env-knob registry.

``knobs`` is stdlib-only and import-cycle-free — every subsystem
(including observability modules that read knobs at import time) may
``from ..config import knobs`` safely.
"""
from . import knobs

__all__ = ["knobs"]
