"""Location-scale families with special tails: Gumbel, Cauchy, StudentT,
Chi2 (reference: python/paddle/distribution/{gumbel,cauchy,student_t,
chi2}.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from ..core.tensor import Tensor
from .beta import Gamma
from .distribution import Distribution, _as_t, _op

__all__ = ["Gumbel", "Cauchy", "StudentT", "Chi2"]

_EULER = 0.57721566490153286060  # Euler–Mascheroni


class Gumbel(Distribution):
    """Gumbel(loc, scale) (reference gumbel.py:30; the reference builds it
    as TransformedDistribution(Uniform) — here the closed forms are direct
    and rsample reparameterizes through -log(-log U))."""

    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        shape = jnp.broadcast_shapes(tuple(self.loc.shape),
                                     tuple(self.scale.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return _op(lambda l, s: l + _EULER * s, [self.loc, self.scale],
                   "mean")

    @property
    def variance(self):
        return _op(lambda s: (math.pi ** 2 / 6.0) * s ** 2, [self.scale],
                   "variance")

    @property
    def stddev(self):
        return _op(lambda s: (math.pi / math.sqrt(6.0)) * s, [self.scale],
                   "stddev")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        g = jax.random.gumbel(self._key(), out_shape)
        return _op(lambda l, s: l + s * g, [self.loc, self.scale],
                   "gumbel_rsample")

    def log_prob(self, value):
        return _op(
            lambda l, s, v: -((v - l) / s) - jnp.exp(-(v - l) / s)
            - jnp.log(s),
            [self.loc, self.scale, _as_t(value)], "gumbel_log_prob")

    def cdf(self, value):
        return _op(lambda l, s, v: jnp.exp(-jnp.exp(-(v - l) / s)),
                   [self.loc, self.scale, _as_t(value)], "gumbel_cdf")

    def entropy(self):
        bs = self.batch_shape
        return _op(lambda s: jnp.broadcast_to(jnp.log(s) + 1.0 + _EULER,
                                              bs),
                   [self.scale], "gumbel_entropy")


class Cauchy(Distribution):
    """Cauchy(loc, scale) (reference cauchy.py:26). mean/variance are
    undefined and raise, matching the reference."""

    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        shape = jnp.broadcast_shapes(tuple(self.loc.shape),
                                     tuple(self.scale.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean.")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance.")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev.")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        c = jax.random.cauchy(self._key(), out_shape)
        return _op(lambda l, s: l + s * c, [self.loc, self.scale],
                   "cauchy_rsample")

    def log_prob(self, value):
        return _op(
            lambda l, s, v: -math.log(math.pi) - jnp.log(s)
            - jnp.log1p(((v - l) / s) ** 2),
            [self.loc, self.scale, _as_t(value)], "cauchy_log_prob")

    def cdf(self, value):
        return _op(
            lambda l, s, v: jnp.arctan((v - l) / s) / math.pi + 0.5,
            [self.loc, self.scale, _as_t(value)], "cauchy_cdf")

    def entropy(self):
        bs = self.batch_shape
        return _op(lambda s: jnp.broadcast_to(
            jnp.log(4 * math.pi * s), bs), [self.scale], "cauchy_entropy")


class StudentT(Distribution):
    """StudentT(df, loc, scale) (reference student_t.py:29)."""

    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _as_t(df)
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        shape = jnp.broadcast_shapes(tuple(self.df.shape),
                                     tuple(self.loc.shape),
                                     tuple(self.scale.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return _op(lambda d, l: jnp.where(d > 1, l, jnp.nan),
                   [self.df, self.loc], "mean")

    @property
    def variance(self):
        return _op(
            lambda d, s: jnp.where(
                d > 2, s ** 2 * d / (d - 2),
                jnp.where(d > 1, jnp.inf, jnp.nan)),
            [self.df, self.scale], "variance")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        t = jax.random.t(self._key(), self.df._data, shape=out_shape)
        return Tensor(self.loc._data + self.scale._data * t)

    def log_prob(self, value):
        return _op(
            lambda d, l, s, v: (
                gammaln((d + 1) / 2) - gammaln(d / 2)
                - 0.5 * jnp.log(d * math.pi) - jnp.log(s)
                - (d + 1) / 2 * jnp.log1p(((v - l) / s) ** 2 / d)),
            [self.df, self.loc, self.scale, _as_t(value)],
            "student_t_log_prob")

    def entropy(self):
        from jax.scipy.special import digamma

        return _op(
            lambda d, s: (
                (d + 1) / 2 * (digamma((d + 1) / 2) - digamma(d / 2))
                + 0.5 * jnp.log(d) + jnp.log(s)
                + gammaln(d / 2) + 0.5 * math.log(math.pi)
                - gammaln((d + 1) / 2)),
            [self.df, self.scale], "student_t_entropy")


class Chi2(Gamma):
    """Chi2(df) = Gamma(df/2, rate=1/2) (reference chi2.py:22)."""

    def __init__(self, df):
        df_t = _as_t(df)
        half = _op(lambda d: d / 2.0, [df_t], "div")
        super().__init__(half, 0.5)
        self.df = df_t
