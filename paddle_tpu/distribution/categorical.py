"""Categorical (reference: python/paddle/distribution/categorical.py).
Parameterized by unnormalized weights or logits; log_prob/entropy are
differentiable w.r.t. the input Tensor."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import Distribution, _as_t, _op


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        t = _as_t(logits)
        arr = t._data
        # paddle semantics: non-negative weights OR logits; normalize as
        # weights if all non-negative else softmax over logits
        if bool(jnp.all(arr >= 0)) and bool(jnp.any(arr != 0)):
            self.logits_t = _op(
                lambda a: jnp.log(a / jnp.sum(a, -1, keepdims=True)), [t],
                "categorical_norm")
        else:
            self.logits_t = _op(lambda a: jax.nn.log_softmax(a, -1), [t],
                                "categorical_norm")
        super().__init__(batch_shape=tuple(self.logits_t.shape[:-1]))

    @property
    def logits(self):
        return self.logits_t._data

    @property
    def probs_array(self):
        return jnp.exp(self.logits_t._data)

    @property
    def num_events(self):
        return self.logits_t.shape[-1]

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.categorical(
            self._key(), self.logits_t._data, shape=out_shape))

    def log_prob(self, value):
        idx = (_as_t(value)._data).astype(jnp.int32)
        return _op(lambda lg: jnp.take_along_axis(
            lg, idx[..., None], axis=-1)[..., 0],
            [self.logits_t], "categorical_log_prob")

    def probs(self, value):
        return _op(jnp.exp, [self.log_prob(value)], "exp")

    def entropy(self):
        return _op(lambda lg: -jnp.sum(jnp.exp(lg) * lg, axis=-1),
                   [self.logits_t], "categorical_entropy")
