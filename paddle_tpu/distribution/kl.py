"""KL divergence registry (reference: python/paddle/distribution/kl.py).
Registered rules run through run_op, so Tensor/Parameter distribution
parameters receive gradients from KL losses (e.g. the VAE ELBO)."""
from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from ..core.tensor import Tensor
from .bernoulli import Bernoulli
from .beta import Beta, Dirichlet, Gamma
from .categorical import Categorical
from .distribution import Distribution, _op
from .exponential import Exponential
from .laplace import Laplace
from .normal import Normal
from .uniform import Uniform

_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    for (pc, qc), fn in _REGISTRY.items():
        if isinstance(p, pc) and isinstance(q, qc):
            return fn(p, q)
    raise NotImplementedError(
        f"kl_divergence not registered for ({type(p).__name__}, "
        f"{type(q).__name__})")


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    return _op(
        lambda pl, ps, ql, qs: 0.5 * ((ps / qs) ** 2
                                      + ((pl - ql) / qs) ** 2 - 1
                                      - 2 * jnp.log(ps / qs)),
        [p.loc, p.scale, q.loc, q.scale], "kl_normal")


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return _op(lambda pl, ql: jnp.sum(jnp.exp(pl) * (pl - ql), axis=-1),
               [p.logits_t, q.logits_t], "kl_categorical")


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return _op(
        lambda plo, phi, qlo, qhi: jnp.where(
            (qlo <= plo) & (phi <= qhi),
            jnp.log((qhi - qlo) / (phi - plo)), jnp.inf),
        [p.low, p.high, q.low, q.high], "kl_uniform")


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    return _op(
        lambda pp, qp: jnp.clip(pp, 1e-7, 1 - 1e-7)
        * jnp.log(jnp.clip(pp, 1e-7, 1 - 1e-7)
                  / jnp.clip(qp, 1e-7, 1 - 1e-7))
        + (1 - jnp.clip(pp, 1e-7, 1 - 1e-7))
        * jnp.log((1 - jnp.clip(pp, 1e-7, 1 - 1e-7))
                  / (1 - jnp.clip(qp, 1e-7, 1 - 1e-7))),
        [p.probs_t, q.probs_t], "kl_bernoulli")


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    return _op(lambda pr, qr: jnp.log(pr / qr) + qr / pr - 1.0,
               [p.rate, q.rate], "kl_exponential")


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    # log(s_q/s_p) + |mu_p-mu_q|/s_q + (s_p/s_q) exp(-|mu_p-mu_q|/s_p) - 1
    return _op(
        lambda pl, ps, ql, qs: jnp.log(qs / ps)
        + jnp.abs(pl - ql) / qs
        + (ps / qs) * jnp.exp(-jnp.abs(pl - ql) / ps) - 1.0,
        [p.loc, p.scale, q.loc, q.scale], "kl_laplace")


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def fn(pa, pb, qa, qb):
        def lbeta(a, b):
            return gammaln(a) + gammaln(b) - gammaln(a + b)

        return (lbeta(qa, qb) - lbeta(pa, pb)
                + (pa - qa) * digamma(pa) + (pb - qb) * digamma(pb)
                + (qa - pa + qb - pb) * digamma(pa + pb))

    return _op(fn, [p.alpha, p.beta, q.alpha, q.beta], "kl_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def fn(pa, qa):
        p0 = jnp.sum(pa, -1)
        return (gammaln(p0) - jnp.sum(gammaln(pa), -1)
                - gammaln(jnp.sum(qa, -1)) + jnp.sum(gammaln(qa), -1)
                + jnp.sum((pa - qa) * (digamma(pa)
                                       - digamma(p0[..., None])), -1))

    return _op(fn, [p.concentration, q.concentration], "kl_dirichlet")


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    return _op(
        lambda pc, pr, qc, qr: (pc - qc) * digamma(pc)
        - gammaln(pc) + gammaln(qc)
        + qc * (jnp.log(pr) - jnp.log(qr)) + pc * (qr / pr - 1.0),
        [p.concentration, p.rate, q.concentration, q.rate], "kl_gamma")
