"""KL divergence registry (reference: python/paddle/distribution/kl.py).
Registered rules run through run_op, so Tensor/Parameter distribution
parameters receive gradients from KL losses (e.g. the VAE ELBO)."""
from __future__ import annotations

import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from ..core.tensor import Tensor
from .bernoulli import Bernoulli
from .beta import Beta, Dirichlet, Gamma
from .categorical import Categorical
from .distribution import Distribution, _op
from .exponential import Exponential
from .laplace import Laplace
from .normal import Normal
from .uniform import Uniform

_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p: Distribution, q: Distribution) -> Tensor:
    # most-specific match wins (reference kl.py dispatch): the generic
    # (ExponentialFamily, ExponentialFamily) fallback must not shadow a
    # closed-form rule for a concrete pair
    matches = [(pc, qc, fn) for (pc, qc), fn in _REGISTRY.items()
               if isinstance(p, pc) and isinstance(q, qc)]
    if not matches:
        raise NotImplementedError(
            f"kl_divergence not registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    best = matches[0]
    for m in matches[1:]:
        if issubclass(m[0], best[0]) and issubclass(m[1], best[1]):
            best = m
    return best[2](p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    return _op(
        lambda pl, ps, ql, qs: 0.5 * ((ps / qs) ** 2
                                      + ((pl - ql) / qs) ** 2 - 1
                                      - 2 * jnp.log(ps / qs)),
        [p.loc, p.scale, q.loc, q.scale], "kl_normal")


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    return _op(lambda pl, ql: jnp.sum(jnp.exp(pl) * (pl - ql), axis=-1),
               [p.logits_t, q.logits_t], "kl_categorical")


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return _op(
        lambda plo, phi, qlo, qhi: jnp.where(
            (qlo <= plo) & (phi <= qhi),
            jnp.log((qhi - qlo) / (phi - plo)), jnp.inf),
        [p.low, p.high, q.low, q.high], "kl_uniform")


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    return _op(
        lambda pp, qp: jnp.clip(pp, 1e-7, 1 - 1e-7)
        * jnp.log(jnp.clip(pp, 1e-7, 1 - 1e-7)
                  / jnp.clip(qp, 1e-7, 1 - 1e-7))
        + (1 - jnp.clip(pp, 1e-7, 1 - 1e-7))
        * jnp.log((1 - jnp.clip(pp, 1e-7, 1 - 1e-7))
                  / (1 - jnp.clip(qp, 1e-7, 1 - 1e-7))),
        [p.probs_t, q.probs_t], "kl_bernoulli")


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    return _op(lambda pr, qr: jnp.log(pr / qr) + qr / pr - 1.0,
               [p.rate, q.rate], "kl_exponential")


@register_kl(Laplace, Laplace)
def _kl_laplace(p, q):
    # log(s_q/s_p) + |mu_p-mu_q|/s_q + (s_p/s_q) exp(-|mu_p-mu_q|/s_p) - 1
    return _op(
        lambda pl, ps, ql, qs: jnp.log(qs / ps)
        + jnp.abs(pl - ql) / qs
        + (ps / qs) * jnp.exp(-jnp.abs(pl - ql) / ps) - 1.0,
        [p.loc, p.scale, q.loc, q.scale], "kl_laplace")


@register_kl(Beta, Beta)
def _kl_beta(p, q):
    def fn(pa, pb, qa, qb):
        def lbeta(a, b):
            return gammaln(a) + gammaln(b) - gammaln(a + b)

        return (lbeta(qa, qb) - lbeta(pa, pb)
                + (pa - qa) * digamma(pa) + (pb - qb) * digamma(pb)
                + (qa - pa + qb - pb) * digamma(pa + pb))

    return _op(fn, [p.alpha, p.beta, q.alpha, q.beta], "kl_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet(p, q):
    def fn(pa, qa):
        p0 = jnp.sum(pa, -1)
        return (gammaln(p0) - jnp.sum(gammaln(pa), -1)
                - gammaln(jnp.sum(qa, -1)) + jnp.sum(gammaln(qa), -1)
                + jnp.sum((pa - qa) * (digamma(pa)
                                       - digamma(p0[..., None])), -1))

    return _op(fn, [p.concentration, q.concentration], "kl_dirichlet")


@register_kl(Gamma, Gamma)
def _kl_gamma(p, q):
    return _op(
        lambda pc, pr, qc, qr: (pc - qc) * digamma(pc)
        - gammaln(pc) + gammaln(qc)
        + qc * (jnp.log(pr) - jnp.log(qr)) + pc * (qr / pr - 1.0),
        [p.concentration, p.rate, q.concentration, q.rate], "kl_gamma")


# --- round-4 families (reference kl.py: binomial/cauchy/cb/mvn/geometric/
# lognormal/poisson pairs + the ExponentialFamily Bregman fallback) -------

from .continuous_bernoulli import ContinuousBernoulli  # noqa: E402
from .discrete import Binomial, Geometric, Poisson  # noqa: E402
from .exponential_family import ExponentialFamily, bregman_kl  # noqa: E402
from .heavy_tail import Cauchy  # noqa: E402
from .multivariate_normal import MultivariateNormal  # noqa: E402
from .normal import LogNormal  # noqa: E402


@register_kl(Poisson, Poisson)
def _kl_poisson(p, q):
    return _op(
        lambda pr, qr: pr * (jnp.log(pr) - jnp.log(qr)) - pr + qr,
        [p.rate, q.rate], "kl_poisson")


@register_kl(Geometric, Geometric)
def _kl_geometric(p, q):
    # KL = log(p_p/p_q) + E[k]·log((1-p_p)/(1-p_q)), E[k] = (1-p_p)/p_p
    return _op(
        lambda pp, qp: (jnp.log(pp) - jnp.log(qp))
        + (1.0 - pp) / pp * (jnp.log1p(-pp) - jnp.log1p(-qp)),
        [p.probs, q.probs], "kl_geometric")


@register_kl(Cauchy, Cauchy)
def _kl_cauchy(p, q):
    # closed form (Chyzak & Nielsen 2019)
    return _op(
        lambda pl, ps, ql, qs: jnp.log(
            ((ps + qs) ** 2 + (pl - ql) ** 2) / (4.0 * ps * qs)),
        [p.loc, p.scale, q.loc, q.scale], "kl_cauchy")


@register_kl(Binomial, Binomial)
def _kl_binomial(p, q):
    return _op(
        lambda n, pp, qn, qp: jnp.where(
            n == qn,
            n * (pp * (jnp.log(pp) - jnp.log(qp))
                 + (1.0 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp))),
            jnp.inf),
        [p.total_count, p.probs, q.total_count, q.probs], "kl_binomial")


@register_kl(LogNormal, LogNormal)
def _kl_lognormal(p, q):
    # KL between the underlying normals (the exp transform cancels)
    return _kl_normal(p, q)


@register_kl(ContinuousBernoulli, ContinuousBernoulli)
def _kl_cb(p, q):
    from .continuous_bernoulli import _log_norm

    def fn(pp, qp, pm):
        return (pm * (jnp.log(pp) - jnp.log(qp))
                + (1.0 - pm) * (jnp.log1p(-pp) - jnp.log1p(-qp))
                + _log_norm(pp, p.lims) - _log_norm(qp, q.lims))

    return _op(fn, [p.probs, q.probs, p.mean], "kl_cb")


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn(p, q):
    def fn(pl, pL, ql, qL):
        import jax

        d = pl.shape[-1]
        diff = ql - pl
        batch = jnp.broadcast_shapes(diff.shape[:-1], pL.shape[:-2],
                                     qL.shape[:-2])
        solve = lambda L, y: jax.scipy.linalg.solve_triangular(
            L, y, lower=True)
        qLb = jnp.broadcast_to(qL, batch + qL.shape[-2:])
        pLb = jnp.broadcast_to(pL, batch + pL.shape[-2:])
        diff = jnp.broadcast_to(diff, batch + diff.shape[-1:])
        m = solve(qLb, diff[..., None])[..., 0]
        a = solve(qLb, pLb)
        half_logdet_p = jnp.sum(
            jnp.log(jnp.diagonal(pLb, axis1=-2, axis2=-1)), -1)
        half_logdet_q = jnp.sum(
            jnp.log(jnp.diagonal(qLb, axis1=-2, axis2=-1)), -1)
        tr = jnp.sum(a ** 2, axis=(-2, -1))
        return (half_logdet_q - half_logdet_p
                + 0.5 * (tr + jnp.sum(m ** 2, -1) - d))

    return _op(fn, [p.loc, p.scale_tril, q.loc, q.scale_tril], "kl_mvn")


@register_kl(ExponentialFamily, ExponentialFamily)
def _kl_expfamily(p, q):
    return bregman_kl(p, q)
