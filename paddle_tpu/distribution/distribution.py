"""Distribution base (reference: python/paddle/distribution/distribution.py)."""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..core import random as _rng
from ..core.autograd import run_op
from ..core.tensor import Tensor


def _as_arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, dtype=jnp.float32)


def _as_t(x) -> Tensor:
    """Keep Tensors (preserving their tape linkage) — parameters given as
    Tensors/Parameters stay differentiable through log_prob/rsample."""
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(
        x, dtype=jnp.float32))


def _op(fn, args, name):
    """run_op wrapper: args may mix Tensors and raw values."""
    return run_op(fn, [a if isinstance(a, Tensor) else jnp.asarray(a)
                       for a in args], name=name)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape: Sequence[int] = ()):
        raise NotImplementedError

    def rsample(self, shape: Sequence[int] = ()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return run_op(jnp.exp, [self.log_prob(value)], name="exp")

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        from .kl import kl_divergence

        return kl_divergence(self, other)

    def _key(self):
        return _rng.next_key()
