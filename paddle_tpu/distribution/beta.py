"""Beta / Dirichlet / Gamma (reference: python/paddle/distribution/
{beta,dirichlet,gamma}.py). log_prob/entropy run through run_op so
Tensor/Parameter concentrations receive gradients."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from ..core.tensor import Tensor
from .distribution import Distribution, _as_t, _op


class Gamma(Distribution):
    def __init__(self, concentration, rate):
        self.concentration = _as_t(concentration)
        self.rate = _as_t(rate)
        shape = jnp.broadcast_shapes(tuple(self.concentration.shape),
                                     tuple(self.rate.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return _op(lambda a, b: a / b, [self.concentration, self.rate],
                   "mean")

    @property
    def variance(self):
        return _op(lambda a, b: a / b ** 2,
                   [self.concentration, self.rate], "variance")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        g = jax.random.gamma(self._key(), self.concentration._data,
                             shape=out_shape)
        return Tensor(g / self.rate._data)

    def log_prob(self, value):
        return _op(
            lambda a, b, v: a * jnp.log(b) + (a - 1) * jnp.log(v)
            - b * v - gammaln(a),
            [self.concentration, self.rate, _as_t(value)],
            "gamma_log_prob")

    def entropy(self):
        return _op(
            lambda a, b: a - jnp.log(b) + gammaln(a)
            + (1 - a) * digamma(a),
            [self.concentration, self.rate], "gamma_entropy")


class Beta(Distribution):
    def __init__(self, alpha, beta):
        self.alpha = _as_t(alpha)
        self.beta = _as_t(beta)
        shape = jnp.broadcast_shapes(tuple(self.alpha.shape),
                                     tuple(self.beta.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return _op(lambda a, b: a / (a + b), [self.alpha, self.beta],
                   "mean")

    @property
    def variance(self):
        return _op(lambda a, b: a * b / ((a + b) ** 2 * (a + b + 1)),
                   [self.alpha, self.beta], "variance")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.beta(
            self._key(), self.alpha._data, self.beta._data,
            shape=out_shape))

    def log_prob(self, value):
        return _op(
            lambda a, b, v: (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v)
            - (gammaln(a) + gammaln(b) - gammaln(a + b)),
            [self.alpha, self.beta, _as_t(value)], "beta_log_prob")

    def entropy(self):
        return _op(
            lambda a, b: (gammaln(a) + gammaln(b) - gammaln(a + b))
            - (a - 1) * digamma(a) - (b - 1) * digamma(b)
            + (a + b - 2) * digamma(a + b),
            [self.alpha, self.beta], "beta_entropy")


class Dirichlet(Distribution):
    def __init__(self, concentration):
        self.concentration = _as_t(concentration)
        shape = tuple(self.concentration.shape)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def mean(self):
        return _op(lambda a: a / jnp.sum(a, -1, keepdims=True),
                   [self.concentration], "mean")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(
            self._key(), self.concentration._data, shape=out_shape))

    def log_prob(self, value):
        return _op(
            lambda a, v: jnp.sum((a - 1) * jnp.log(v), -1)
            - (jnp.sum(gammaln(a), -1) - gammaln(jnp.sum(a, -1))),
            [self.concentration, _as_t(value)], "dirichlet_log_prob")

    def entropy(self):
        k = self.concentration.shape[-1]
        return _op(
            lambda a: (jnp.sum(gammaln(a), -1) - gammaln(jnp.sum(a, -1)))
            + (jnp.sum(a, -1) - k) * digamma(jnp.sum(a, -1))
            - jnp.sum((a - 1) * digamma(a), -1),
            [self.concentration], "dirichlet_entropy")
