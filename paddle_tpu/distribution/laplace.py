"""Laplace (reference: python/paddle/distribution/laplace.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _as_t, _op


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        shape = jnp.broadcast_shapes(tuple(self.loc.shape),
                                     tuple(self.scale.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        bs = self.batch_shape
        return _op(lambda l: jnp.broadcast_to(l, bs), [self.loc], "mean")

    @property
    def variance(self):
        bs = self.batch_shape
        return _op(lambda s: jnp.broadcast_to(2 * s ** 2, bs),
                   [self.scale], "variance")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        eps = jnp.finfo(jnp.float32).eps
        # keep u strictly inside (-0.5, 0.5): u = -0.5 would hit log(0)
        u = jnp.clip(jax.random.uniform(self._key(), out_shape,
                                        minval=-0.5, maxval=0.5),
                     -0.5 + eps, 0.5 - eps)
        return _op(lambda l, s: l - s * jnp.sign(u)
                   * jnp.log1p(-2 * jnp.abs(u)),
                   [self.loc, self.scale], "laplace_rsample")

    def log_prob(self, value):
        return _op(lambda l, s, v: -jnp.log(2 * s) - jnp.abs(v - l) / s,
                   [self.loc, self.scale, _as_t(value)],
                   "laplace_log_prob")

    def entropy(self):
        bs = self.batch_shape
        return _op(lambda s: jnp.broadcast_to(1 + jnp.log(2 * s), bs),
                   [self.scale], "entropy")
