"""MultivariateNormal (reference: python/paddle/distribution/
multivariate_normal.py).

TPU-native: everything is expressed through the Cholesky factor L of the
covariance (one `cholesky` at construction, then triangular solves) so
log_prob / rsample / entropy / KL are all batched matmul-shaped work that
XLA maps onto the MXU.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _as_t, _op

__all__ = ["MultivariateNormal"]


def _tril_solve(L, y):
    return jax.scipy.linalg.solve_triangular(L, y, lower=True)


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        self.loc = _as_t(loc)
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError(
                "Exactly one of covariance_matrix, precision_matrix, "
                "scale_tril must be specified.")
        if scale_tril is not None:
            self.scale_tril = _as_t(scale_tril)
        elif covariance_matrix is not None:
            self.covariance_matrix = _as_t(covariance_matrix)
            self.scale_tril = _op(jnp.linalg.cholesky,
                                  [self.covariance_matrix], "cholesky")
        else:
            self.precision_matrix = _as_t(precision_matrix)
            # cov = P^-1; chol(P^-1) via inverse of chol(P) transpose-flip
            self.scale_tril = _op(
                lambda p: jnp.linalg.cholesky(jnp.linalg.inv(p)),
                [self.precision_matrix], "cholesky_inv")
        d = self.loc.shape[-1]
        batch = jnp.broadcast_shapes(tuple(self.loc.shape[:-1]),
                                     tuple(self.scale_tril.shape[:-2]))
        super().__init__(batch_shape=batch, event_shape=(d,))

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        bs = self.batch_shape + self.event_shape
        return _op(lambda L: jnp.broadcast_to(
            jnp.sum(L ** 2, axis=-1), bs), [self.scale_tril], "variance")

    @property
    def stddev(self):
        return _op(lambda v: jnp.sqrt(v), [self.variance], "sqrt")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(self._key(), out_shape)
        return _op(
            lambda l, L: l + jnp.einsum("...ij,...j->...i", L, eps),
            [self.loc, self.scale_tril], "mvn_rsample")

    def log_prob(self, value):
        d = self.event_shape[0]

        def fn(l, L, v):
            diff = v - l
            batch = jnp.broadcast_shapes(diff.shape[:-1], L.shape[:-2])
            Lb = jnp.broadcast_to(L, batch + L.shape[-2:])
            diff = jnp.broadcast_to(diff, batch + diff.shape[-1:])
            z = _tril_solve(Lb, diff[..., None])[..., 0]
            half_logdet = jnp.sum(
                jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
            return (-0.5 * jnp.sum(z ** 2, axis=-1) - half_logdet
                    - 0.5 * d * math.log(2 * math.pi))

        return _op(fn, [self.loc, self.scale_tril, _as_t(value)],
                   "mvn_log_prob")

    def entropy(self):
        d = self.event_shape[0]
        bs = self.batch_shape

        def fn(L):
            half_logdet = jnp.sum(
                jnp.log(jnp.diagonal(L, axis1=-2, axis2=-1)), axis=-1)
            return jnp.broadcast_to(
                0.5 * d * (1 + math.log(2 * math.pi)) + half_logdet, bs)

        return _op(fn, [self.scale_tril], "mvn_entropy")
