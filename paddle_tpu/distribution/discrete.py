"""Discrete count distributions: Poisson, Geometric, Binomial
(reference: python/paddle/distribution/{poisson,geometric,binomial}.py).

Sampling uses jax.random's native samplers; entropies that the reference
computes by summing over the support do the same here with a concrete
(eager) support bound, which keeps shapes static per call.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln, xlog1py, xlogy

from ..core.tensor import Tensor
from .distribution import Distribution, _as_t, _op
from .exponential_family import ExponentialFamily

__all__ = ["Poisson", "Geometric", "Binomial"]


class Poisson(ExponentialFamily):
    """Poisson(rate): P(X=k) = e^-λ λ^k / k! (reference poisson.py:25)."""

    def __init__(self, rate):
        self.rate = _as_t(rate)
        super().__init__(batch_shape=tuple(self.rate.shape))

    @property
    def mean(self):
        return self.rate

    @property
    def variance(self):
        return self.rate

    @property
    def _natural_parameters(self):
        return (_op(jnp.log, [self.rate], "log"),)

    def _log_normalizer(self, eta):
        return jnp.exp(eta)

    _mean_carrier_measure = None  # E[-log k!] has no closed form

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.poisson(
            self._key(), self.rate._data, shape=out_shape).astype(
                jnp.float32))

    def log_prob(self, value):
        return _op(
            lambda r, v: xlogy(v, r) - r - gammaln(v + 1),
            [self.rate, _as_t(value)], "poisson_log_prob")

    def entropy(self):
        # truncated-support sum like the reference (poisson.py entropy):
        # bound is concrete in eager mode
        r = self.rate._data
        upper = int(jnp.max(r) + 10.0 * jnp.sqrt(jnp.max(r)) + 20.0)
        ks = jnp.arange(upper, dtype=jnp.float32)

        def fn(rate):
            lp = (xlogy(ks, rate[..., None]) - rate[..., None]
                  - gammaln(ks + 1))
            return -jnp.sum(jnp.exp(lp) * lp, axis=-1)

        return _op(fn, [self.rate], "poisson_entropy")


class Geometric(Distribution):
    """Geometric(probs) over k ∈ {0,1,2,…} failures before first success:
    P(X=k) = (1-p)^k p (reference geometric.py:30, mean = 1/p − 1)."""

    def __init__(self, probs):
        self.probs = _as_t(probs)
        super().__init__(batch_shape=tuple(self.probs.shape))

    @property
    def mean(self):
        return _op(lambda p: 1.0 / p - 1.0, [self.probs], "mean")

    @property
    def variance(self):
        return _op(lambda p: (1.0 - p) / p ** 2, [self.probs], "variance")

    @property
    def stddev(self):
        return _op(lambda p: jnp.sqrt((1.0 - p) / p ** 2), [self.probs],
                   "stddev")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), out_shape, minval=1e-7)
        return Tensor(jnp.floor(
            jnp.log(u) / jnp.log1p(-self.probs._data)))

    def log_prob(self, value):
        return _op(lambda p, v: xlog1py(v, -p) + jnp.log(p),
                   [self.probs, _as_t(value)], "geometric_log_prob")

    def pmf(self, value):
        return _op(jnp.exp, [self.log_prob(value)], "exp")

    def cdf(self, value):
        return _op(lambda p, v: 1.0 - jnp.power(1.0 - p, v + 1.0),
                   [self.probs, _as_t(value)], "geometric_cdf")

    def entropy(self):
        return _op(
            lambda p: (-(1.0 - p) * jnp.log1p(-p) - p * jnp.log(p)) / p,
            [self.probs], "geometric_entropy")


class Binomial(Distribution):
    """Binomial(total_count, probs) (reference binomial.py:26)."""

    def __init__(self, total_count, probs):
        self.total_count = _as_t(total_count)
        self.probs = _as_t(probs)
        shape = jnp.broadcast_shapes(tuple(self.total_count.shape),
                                     tuple(self.probs.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        return _op(lambda n, p: n * p, [self.total_count, self.probs],
                   "mean")

    @property
    def variance(self):
        return _op(lambda n, p: n * p * (1 - p),
                   [self.total_count, self.probs], "variance")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.binomial(
            self._key(), self.total_count._data, self.probs._data,
            shape=out_shape).astype(jnp.float32))

    def log_prob(self, value):
        return _op(
            lambda n, p, v: (gammaln(n + 1) - gammaln(v + 1)
                             - gammaln(n - v + 1)
                             + xlogy(v, p) + xlog1py(n - v, -p)),
            [self.total_count, self.probs, _as_t(value)],
            "binomial_log_prob")

    def entropy(self):
        # support sum with a concrete bound (reference binomial.py entropy)
        n_max = int(jnp.max(self.total_count._data))
        ks = jnp.arange(n_max + 1, dtype=jnp.float32)

        def fn(n, p):
            lp = (gammaln(n[..., None] + 1) - gammaln(ks + 1)
                  - gammaln(n[..., None] - ks + 1)
                  + xlogy(ks, p[..., None])
                  + xlog1py(n[..., None] - ks, -p[..., None]))
            lp = jnp.where(ks <= n[..., None], lp, -jnp.inf)
            return -jnp.sum(jnp.where(jnp.isfinite(lp),
                                      jnp.exp(lp) * lp, 0.0), axis=-1)

        return _op(fn, [self.total_count, self.probs], "binomial_entropy")
