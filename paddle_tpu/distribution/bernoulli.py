"""Bernoulli (reference: python/paddle/distribution/bernoulli.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import Distribution, _as_t, _op


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_t = _as_t(probs)
        super().__init__(batch_shape=tuple(self.probs_t.shape))

    # raw array view used by the kl registry
    @property
    def probs_(self):
        return self.probs_t._data

    @property
    def mean(self):
        return _op(lambda p: p, [self.probs_t], "mean")

    @property
    def variance(self):
        return _op(lambda p: p * (1 - p), [self.probs_t], "variance")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(
            self._key(), self.probs_t._data, out_shape)
            .astype(jnp.float32))

    def log_prob(self, value):
        return _op(
            lambda p, v: v * jnp.log(jnp.clip(p, 1e-7, 1 - 1e-7))
            + (1 - v) * jnp.log1p(-jnp.clip(p, 1e-7, 1 - 1e-7)),
            [self.probs_t, _as_t(value)], "bernoulli_log_prob")

    def entropy(self):
        return _op(
            lambda p: -(jnp.clip(p, 1e-7, 1 - 1e-7)
                        * jnp.log(jnp.clip(p, 1e-7, 1 - 1e-7))
                        + (1 - jnp.clip(p, 1e-7, 1 - 1e-7))
                        * jnp.log1p(-jnp.clip(p, 1e-7, 1 - 1e-7))),
            [self.probs_t], "bernoulli_entropy")
