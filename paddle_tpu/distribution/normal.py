"""Normal / LogNormal (reference: python/paddle/distribution/normal.py,
lognormal.py). All math runs through run_op, so Tensor/Parameter
loc/scale receive gradients via log_prob / rsample / entropy / kl
(reparameterization for VAE-style training)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import Distribution, _as_t, _op


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        shape = jnp.broadcast_shapes(tuple(self.loc.shape),
                                     tuple(self.scale.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        bs = self.batch_shape
        return _op(lambda l: jnp.broadcast_to(l, bs), [self.loc], "mean")

    @property
    def variance(self):
        bs = self.batch_shape
        return _op(lambda s: jnp.broadcast_to(s ** 2, bs), [self.scale],
                   "variance")

    @property
    def stddev(self):
        bs = self.batch_shape
        return _op(lambda s: jnp.broadcast_to(s, bs), [self.scale], "stddev")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        eps = jax.random.normal(self._key(), out_shape)
        return _op(lambda l, s: l + eps * s, [self.loc, self.scale],
                   "normal_rsample")

    def log_prob(self, value):
        return _op(
            lambda l, s, v: -((v - l) ** 2) / (2 * s ** 2) - jnp.log(s)
            - 0.5 * math.log(2 * math.pi),
            [self.loc, self.scale, _as_t(value)], "normal_log_prob")

    def entropy(self):
        bs = self.batch_shape
        return _op(lambda s: jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(s), bs),
            [self.scale], "normal_entropy")

    def cdf(self, value):
        return _op(lambda l, s, v: 0.5 * (1 + jax.lax.erf(
            (v - l) / (s * math.sqrt(2.0)))),
            [self.loc, self.scale, _as_t(value)], "normal_cdf")


class LogNormal(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)
        self._base = Normal(self.loc, self.scale)
        super().__init__(batch_shape=self._base.batch_shape)

    @property
    def mean(self):
        return _op(lambda l, s: jnp.exp(l + s ** 2 / 2),
                   [self.loc, self.scale], "mean")

    @property
    def variance(self):
        return _op(lambda l, s: (jnp.exp(s ** 2) - 1)
                   * jnp.exp(2 * l + s ** 2),
                   [self.loc, self.scale], "variance")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        return _op(jnp.exp, [self._base.rsample(shape)], "exp")

    def log_prob(self, value):
        v = _as_t(value)
        base_lp = self._base.log_prob(_op(jnp.log, [v], "log"))
        return _op(lambda lp, vv: lp - jnp.log(vv), [base_lp, v],
                   "lognormal_log_prob")

    def entropy(self):
        return _op(lambda e, l: e + l, [self._base.entropy(), self.loc],
                   "entropy")
