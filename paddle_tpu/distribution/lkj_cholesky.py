"""LKJCholesky (reference: python/paddle/distribution/lkj_cholesky.py;
Lewandowski, Kurowicka & Joe 2009).

Distribution over Cholesky factors L of correlation matrices with density
p(L|η) ∝ Π_i L_ii^{D - i - 1 + 2(η-1)} (row index i from 2..D). Both the
reference's sampling methods are provided: "onion" (default) and "cvine".
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from ..core.tensor import Tensor
from .distribution import Distribution, _as_t, _op

__all__ = ["LKJCholesky"]


def _mvlgamma(a, p):
    """Multivariate log-gamma log Γ_p(a)."""
    i = jnp.arange(p, dtype=jnp.float32)
    return (p * (p - 1) / 4.0 * math.log(math.pi)
            + jnp.sum(gammaln(a[..., None] - i / 2.0), axis=-1))


class LKJCholesky(Distribution):
    def __init__(self, dim=2, concentration=1.0, sample_method="onion"):
        if dim < 2:
            raise ValueError("dim must be >= 2")
        if sample_method not in ("onion", "cvine"):
            raise ValueError("sample_method must be 'onion' or 'cvine'")
        self.dim = int(dim)
        self.concentration = _as_t(concentration)
        self.sample_method = sample_method
        super().__init__(batch_shape=tuple(self.concentration.shape),
                         event_shape=(dim, dim))

    # ------------------------------------------------------------- sampling
    def _beta_sample(self, a, b, shape):
        ga = jax.random.gamma(self._key(), jnp.broadcast_to(a, shape))
        gb = jax.random.gamma(self._key(), jnp.broadcast_to(b, shape))
        return ga / (ga + gb)

    def _sample_onion(self, sample_shape):
        d = self.dim
        eta = self.concentration._data
        bs = tuple(sample_shape) + tuple(self.batch_shape)
        L = jnp.zeros(bs + (d, d), dtype=jnp.float32)
        L = L.at[..., 0, 0].set(1.0)
        beta = eta + (d - 2.0) / 2.0
        for i in range(1, d):
            # norm^2 of row i ~ Beta(i/2, beta), direction uniform on sphere
            y = self._beta_sample(i / 2.0, beta, bs)
            u = jax.random.normal(self._key(), bs + (i,))
            u = u / jnp.linalg.norm(u, axis=-1, keepdims=True)
            w = jnp.sqrt(y)[..., None] * u
            L = L.at[..., i, :i].set(w)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.clip(1.0 - y, 1e-12)))
            beta = beta - 0.5
        return L

    def _sample_cvine(self, sample_shape):
        d = self.dim
        eta = self.concentration._data
        bs = tuple(sample_shape) + tuple(self.batch_shape)
        # partial correlations: p_ij ~ 2*Beta(a_i, a_i)-1 per row
        P = jnp.zeros(bs + (d, d), dtype=jnp.float32)
        for i in range(1, d):
            a = eta + (d - 1.0 - i) / 2.0
            p_row = 2.0 * self._beta_sample(a, a, bs + (i,)) - 1.0
            P = P.at[..., i, :i].set(p_row)
        # convert partial correlations to cholesky rows
        L = jnp.zeros_like(P)
        L = L.at[..., 0, 0].set(1.0)
        for i in range(1, d):
            rem = jnp.ones(bs, dtype=jnp.float32)
            for j in range(i):
                L = L.at[..., i, j].set(P[..., i, j] * jnp.sqrt(rem))
                rem = rem * (1.0 - P[..., i, j] ** 2)
            L = L.at[..., i, i].set(jnp.sqrt(jnp.clip(rem, 1e-12)))
        return L

    def sample(self, sample_shape=()):
        if self.sample_method == "onion":
            return Tensor(self._sample_onion(sample_shape))
        return Tensor(self._sample_cvine(sample_shape))

    # ------------------------------------------------------------- density
    def log_prob(self, value):
        d = self.dim

        def fn(eta, L):
            diag = jnp.diagonal(L, axis1=-2, axis2=-1)[..., 1:]
            order = jnp.arange(2, d + 1, dtype=jnp.float32)
            order = 2.0 * (eta[..., None] - 1.0) + d - order
            unnorm = jnp.sum(order * jnp.log(diag), axis=-1)
            # normalizer (LKJ 2009, p.1999), as in the reference
            dm1 = d - 1
            alpha = eta + 0.5 * dm1
            logz = (0.5 * dm1 * math.log(math.pi)
                    + _mvlgamma(alpha - 0.5, dm1) - dm1 * gammaln(alpha))
            return unnorm - logz

        return _op(fn, [self.concentration, _as_t(value)],
                   "lkj_log_prob")
