"""Probability distributions (reference: python/paddle/distribution/ —
Distribution base distribution.py, Normal, Uniform, Categorical, Bernoulli,
Beta, Dirichlet, Exponential, Gamma, Laplace, Multinomial, LogNormal,
kl_divergence kl.py, transforms transform.py, TransformedDistribution,
Independent)."""
from .distribution import Distribution  # noqa: F401
from .normal import LogNormal, Normal  # noqa: F401
from .uniform import Uniform  # noqa: F401
from .categorical import Categorical  # noqa: F401
from .bernoulli import Bernoulli  # noqa: F401
from .exponential import Exponential  # noqa: F401
from .laplace import Laplace  # noqa: F401
from .beta import Beta, Dirichlet, Gamma  # noqa: F401
from .multinomial import Multinomial  # noqa: F401
from .exponential_family import ExponentialFamily  # noqa: F401
from .discrete import Binomial, Geometric, Poisson  # noqa: F401
from .heavy_tail import Cauchy, Chi2, Gumbel, StudentT  # noqa: F401
from .continuous_bernoulli import ContinuousBernoulli  # noqa: F401
from .multivariate_normal import MultivariateNormal  # noqa: F401
from .lkj_cholesky import LKJCholesky  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401
from .transform import (AbsTransform, AffineTransform,  # noqa: F401
                        ChainTransform, ExpTransform, IndependentTransform,
                        PowerTransform, ReshapeTransform, SigmoidTransform,
                        SoftmaxTransform, StackTransform,
                        StickBreakingTransform, TanhTransform, Transform)
from .transformed_distribution import (  # noqa: F401
    Independent, TransformedDistribution)

__all__ = ["Distribution", "Normal", "LogNormal", "Uniform", "Categorical",
           "Bernoulli", "Exponential", "Laplace", "Beta", "Dirichlet",
           "Gamma", "Multinomial", "ExponentialFamily", "Poisson",
           "Geometric", "Binomial", "Gumbel", "Cauchy", "StudentT", "Chi2",
           "ContinuousBernoulli", "MultivariateNormal", "LKJCholesky",
           "kl_divergence", "register_kl",
           "Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform", "AbsTransform", "ChainTransform",
           "PowerTransform", "TanhTransform", "SoftmaxTransform",
           "StickBreakingTransform", "ReshapeTransform",
           "IndependentTransform", "StackTransform",
           "TransformedDistribution", "Independent"]
