"""Probability distributions (reference: python/paddle/distribution/ —
Distribution base distribution.py, Normal, Uniform, Categorical, Bernoulli,
Beta, Dirichlet, Exponential, Gamma, Laplace, Multinomial, LogNormal,
kl_divergence kl.py, transforms transform.py, TransformedDistribution,
Independent)."""
from .distribution import Distribution  # noqa: F401
from .normal import LogNormal, Normal  # noqa: F401
from .uniform import Uniform  # noqa: F401
from .categorical import Categorical  # noqa: F401
from .bernoulli import Bernoulli  # noqa: F401
from .exponential import Exponential  # noqa: F401
from .laplace import Laplace  # noqa: F401
from .beta import Beta, Dirichlet, Gamma  # noqa: F401
from .multinomial import Multinomial  # noqa: F401
from .kl import kl_divergence, register_kl  # noqa: F401
from .transform import (AbsTransform, AffineTransform,  # noqa: F401
                        ChainTransform, ExpTransform, SigmoidTransform,
                        Transform)
from .transformed_distribution import (  # noqa: F401
    Independent, TransformedDistribution)

__all__ = ["Distribution", "Normal", "LogNormal", "Uniform", "Categorical",
           "Bernoulli", "Exponential", "Laplace", "Beta", "Dirichlet",
           "Gamma", "Multinomial", "kl_divergence", "register_kl",
           "Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform", "AbsTransform", "ChainTransform",
           "TransformedDistribution", "Independent"]
