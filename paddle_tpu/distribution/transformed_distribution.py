"""TransformedDistribution + Independent (reference:
python/paddle/distribution/{transformed_distribution,independent}.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, _op
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    def __init__(self, base: Distribution, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transform = ChainTransform(list(transforms))
        super().__init__(batch_shape=base.batch_shape,
                         event_shape=base.event_shape)

    def sample(self, shape=()):
        return self.transform.forward(self.base.sample(shape))

    def rsample(self, shape=()):
        return self.transform.forward(self.base.rsample(shape))

    def log_prob(self, value):
        x = self.transform.inverse(value)
        base_lp = self.base.log_prob(x)
        jac = self.transform.forward_log_det_jacobian(x)
        return _op(lambda a, b: a - b, [base_lp, jac], "td_log_prob")


class Independent(Distribution):
    """Reinterpret batch dims as event dims (reference: independent.py)."""

    def __init__(self, base: Distribution,
                 reinterpreted_batch_rank: int = 1):
        self.base = base
        self.rank = reinterpreted_batch_rank
        bs = base.batch_shape
        super().__init__(batch_shape=bs[: len(bs) - self.rank],
                         event_shape=bs[len(bs) - self.rank:]
                         + base.event_shape)

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        rank = self.rank
        return _op(lambda lp: jnp.sum(lp, axis=tuple(range(-rank, 0))),
                   [self.base.log_prob(value)], "independent_log_prob")

    def entropy(self):
        rank = self.rank
        return _op(lambda e: jnp.sum(e, axis=tuple(range(-rank, 0))),
                   [self.base.entropy()], "independent_entropy")
