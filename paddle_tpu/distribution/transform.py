"""Transforms (reference: python/paddle/distribution/transform.py —
Transform base, AffineTransform, ExpTransform, SigmoidTransform,
AbsTransform, ChainTransform). Differentiable through run_op."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import _as_t, _op


class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return _op(jnp.negative,
                   [self.forward_log_det_jacobian(self.inverse(y))], "neg")

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    def forward(self, x):
        return _op(lambda l, s, v: l + s * v,
                   [self.loc, self.scale, _as_t(x)], "affine_fwd")

    def inverse(self, y):
        return _op(lambda l, s, v: (v - l) / s,
                   [self.loc, self.scale, _as_t(y)], "affine_inv")

    def forward_log_det_jacobian(self, x):
        xv = _as_t(x)
        shape = tuple(xv.shape)
        return _op(lambda s: jnp.broadcast_to(jnp.log(jnp.abs(s)), shape),
                   [self.scale], "affine_ldj")


class ExpTransform(Transform):
    def forward(self, x):
        return _op(jnp.exp, [_as_t(x)], "exp")

    def inverse(self, y):
        return _op(jnp.log, [_as_t(y)], "log")

    def forward_log_det_jacobian(self, x):
        return _as_t(x)


class SigmoidTransform(Transform):
    def forward(self, x):
        return _op(jax.nn.sigmoid, [_as_t(x)], "sigmoid")

    def inverse(self, y):
        return _op(lambda v: jnp.log(v) - jnp.log1p(-v), [_as_t(y)],
                   "logit")

    def forward_log_det_jacobian(self, x):
        return _op(lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v),
                   [_as_t(x)], "sigmoid_ldj")


class AbsTransform(Transform):
    def forward(self, x):
        return _op(jnp.abs, [_as_t(x)], "abs")

    def inverse(self, y):
        return _as_t(y)  # principal branch


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)
            total = j if total is None else _op(
                lambda a, b: a + b, [total, j], "add")
            x = t.forward(x)
        return total


class PowerTransform(Transform):
    """y = x^a (reference transform.py PowerTransform)."""

    def __init__(self, power):
        self.power = _as_t(power)

    def forward(self, x):
        return _op(lambda a, v: jnp.power(v, a),
                   [self.power, _as_t(x)], "power_fwd")

    def inverse(self, y):
        return _op(lambda a, v: jnp.power(v, 1.0 / a),
                   [self.power, _as_t(y)], "power_inv")

    def forward_log_det_jacobian(self, x):
        return _op(lambda a, v: jnp.log(jnp.abs(a * jnp.power(v, a - 1))),
                   [self.power, _as_t(x)], "power_ldj")


class TanhTransform(Transform):
    """y = tanh(x) (reference transform.py TanhTransform)."""

    def forward(self, x):
        return _op(jnp.tanh, [_as_t(x)], "tanh")

    def inverse(self, y):
        return _op(jnp.arctanh, [_as_t(y)], "atanh")

    def forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2(log 2 - x - softplus(-2x))
        return _op(lambda v: 2.0 * (jnp.log(2.0) - v
                                    - jax.nn.softplus(-2.0 * v)),
                   [_as_t(x)], "tanh_ldj")


class SoftmaxTransform(Transform):
    """exp then normalize on the last axis (reference transform.py
    SoftmaxTransform; not bijective — inverse is log up to an additive
    constant, matching the reference contract)."""

    def forward(self, x):
        return _op(lambda v: jax.nn.softmax(v, axis=-1), [_as_t(x)],
                   "softmax_fwd")

    def inverse(self, y):
        return _op(jnp.log, [_as_t(y)], "softmax_inv")


class StickBreakingTransform(Transform):
    """Unconstrained R^k -> k+1 simplex via stick breaking (reference
    transform.py StickBreakingTransform)."""

    def forward(self, x):
        def fn(v):
            offset = v.shape[-1] - jnp.arange(v.shape[-1])
            z = jax.nn.sigmoid(v - jnp.log(offset.astype(v.dtype)))
            zpad = jnp.concatenate([z, jnp.ones(z.shape[:-1] + (1,),
                                                z.dtype)], -1)
            one_minus = jnp.concatenate(
                [jnp.ones(z.shape[:-1] + (1,), z.dtype),
                 jnp.cumprod(1 - z, -1)], -1)
            return zpad * one_minus

        return _op(fn, [_as_t(x)], "stickbreaking_fwd")

    def inverse(self, y):
        def fn(v):
            k = v.shape[-1] - 1
            cum = jnp.concatenate(
                [jnp.zeros(v.shape[:-1] + (1,), v.dtype),
                 jnp.cumsum(v[..., :-1], -1)], -1)[..., :k]
            rest = 1 - cum
            z = v[..., :k] / rest
            offset = k - jnp.arange(k)
            return jnp.log(z / (1 - z)) + jnp.log(
                offset.astype(v.dtype))

        return _op(fn, [_as_t(y)], "stickbreaking_inv")

    def forward_log_det_jacobian(self, x):
        def fn(v):
            offset = v.shape[-1] - jnp.arange(v.shape[-1])
            u = v - jnp.log(offset.astype(v.dtype))
            z = jax.nn.sigmoid(u)
            rest = jnp.concatenate(
                [jnp.ones(z.shape[:-1] + (1,), z.dtype),
                 jnp.cumprod(1 - z, -1)[..., :-1]], -1)
            return jnp.sum(jnp.log(z) + jnp.log1p(-z) + jnp.log(rest),
                           -1)

        return _op(fn, [_as_t(x)], "stickbreaking_ldj")


class ReshapeTransform(Transform):
    """reference transform.py ReshapeTransform."""

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)

    def forward(self, x):
        xv = _as_t(x)
        batch = tuple(xv.shape)[:len(tuple(xv.shape))
                                - len(self.in_event_shape)]
        return _op(lambda v: v.reshape(batch + self.out_event_shape),
                   [xv], "reshape_fwd")

    def inverse(self, y):
        yv = _as_t(y)
        batch = tuple(yv.shape)[:len(tuple(yv.shape))
                                - len(self.out_event_shape)]
        return _op(lambda v: v.reshape(batch + self.in_event_shape),
                   [yv], "reshape_inv")

    def forward_log_det_jacobian(self, x):
        xv = _as_t(x)
        batch = tuple(xv.shape)[:len(tuple(xv.shape))
                                - len(self.in_event_shape)]
        return Tensor(jnp.zeros(batch))


class IndependentTransform(Transform):
    """Reinterpret batch dims of a base transform as event dims
    (reference transform.py IndependentTransform): the log-det sums over
    the reinterpreted dims."""

    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self.rank = int(reinterpreted_batch_rank)

    def forward(self, x):
        return self.base.forward(x)

    def inverse(self, y):
        return self.base.inverse(y)

    def forward_log_det_jacobian(self, x):
        ldj = self.base.forward_log_det_jacobian(x)
        axes = tuple(range(-self.rank, 0))
        return _op(lambda v: jnp.sum(v, axis=axes), [ldj],
                   "independent_ldj")


class StackTransform(Transform):
    """Apply a list of transforms along an axis (reference transform.py
    StackTransform)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _split(self, x):
        from ..ops.manipulation import unstack

        return unstack(_as_t(x), axis=self.axis)

    def _stack(self, parts):
        from ..ops.manipulation import stack

        return stack(parts, axis=self.axis)

    def forward(self, x):
        parts = self._split(x)
        return self._stack([t.forward(p)
                            for t, p in zip(self.transforms, parts)])

    def inverse(self, y):
        parts = self._split(y)
        return self._stack([t.inverse(p)
                            for t, p in zip(self.transforms, parts)])

    def forward_log_det_jacobian(self, x):
        parts = self._split(x)
        return self._stack([t.forward_log_det_jacobian(p)
                            for t, p in zip(self.transforms, parts)])


__all__ = ["Transform", "AffineTransform", "ExpTransform",
           "SigmoidTransform", "AbsTransform", "ChainTransform",
           "PowerTransform", "TanhTransform", "SoftmaxTransform",
           "StickBreakingTransform", "ReshapeTransform",
           "IndependentTransform", "StackTransform"]
