"""Transforms (reference: python/paddle/distribution/transform.py —
Transform base, AffineTransform, ExpTransform, SigmoidTransform,
AbsTransform, ChainTransform). Differentiable through run_op."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import _as_t, _op


class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError

    def inverse_log_det_jacobian(self, y):
        return _op(jnp.negative,
                   [self.forward_log_det_jacobian(self.inverse(y))], "neg")

    def __call__(self, x):
        return self.forward(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_t(loc)
        self.scale = _as_t(scale)

    def forward(self, x):
        return _op(lambda l, s, v: l + s * v,
                   [self.loc, self.scale, _as_t(x)], "affine_fwd")

    def inverse(self, y):
        return _op(lambda l, s, v: (v - l) / s,
                   [self.loc, self.scale, _as_t(y)], "affine_inv")

    def forward_log_det_jacobian(self, x):
        xv = _as_t(x)
        shape = tuple(xv.shape)
        return _op(lambda s: jnp.broadcast_to(jnp.log(jnp.abs(s)), shape),
                   [self.scale], "affine_ldj")


class ExpTransform(Transform):
    def forward(self, x):
        return _op(jnp.exp, [_as_t(x)], "exp")

    def inverse(self, y):
        return _op(jnp.log, [_as_t(y)], "log")

    def forward_log_det_jacobian(self, x):
        return _as_t(x)


class SigmoidTransform(Transform):
    def forward(self, x):
        return _op(jax.nn.sigmoid, [_as_t(x)], "sigmoid")

    def inverse(self, y):
        return _op(lambda v: jnp.log(v) - jnp.log1p(-v), [_as_t(y)],
                   "logit")

    def forward_log_det_jacobian(self, x):
        return _op(lambda v: -jax.nn.softplus(-v) - jax.nn.softplus(v),
                   [_as_t(x)], "sigmoid_ldj")


class AbsTransform(Transform):
    def forward(self, x):
        return _op(jnp.abs, [_as_t(x)], "abs")

    def inverse(self, y):
        return _as_t(y)  # principal branch


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def forward(self, x):
        for t in self.transforms:
            x = t.forward(x)
        return x

    def inverse(self, y):
        for t in reversed(self.transforms):
            y = t.inverse(y)
        return y

    def forward_log_det_jacobian(self, x):
        total = None
        for t in self.transforms:
            j = t.forward_log_det_jacobian(x)
            total = j if total is None else _op(
                lambda a, b: a + b, [total, j], "add")
            x = t.forward(x)
        return total
