"""ContinuousBernoulli (reference: python/paddle/distribution/
continuous_bernoulli.py; Loaiza-Ganem & Cunningham 2019).

Density on [0,1]: p(x|λ) = C(λ) λ^x (1-λ)^(1-x), with the normalizer
C(λ) = 2 atanh(1-2λ)/(1-2λ) for λ≠1/2 and 2 at λ=1/2. Near λ=1/2 the
closed form is numerically singular; like the reference we switch to a
Taylor expansion inside ``lims``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _as_t, _op

__all__ = ["ContinuousBernoulli"]


def _outside(p, lims):
    return (p < lims[0]) | (p > lims[1])


def _safe_p(p, lims):
    """probs clamped away from 1/2 for the singular branch."""
    return jnp.where(_outside(p, lims), p, lims[0])


def _log_norm(p, lims):
    """log C(λ), Taylor-expanded around 1/2 inside lims."""
    ps = _safe_p(p, lims)
    exact = jnp.log(2.0 * jnp.abs(jnp.arctanh(1.0 - 2.0 * ps))) \
        - jnp.log(jnp.abs(1.0 - 2.0 * ps))
    x = p - 0.5
    taylor = jnp.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x ** 2) * x ** 2
    return jnp.where(_outside(p, lims), exact, taylor)


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs = _as_t(probs)
        self.lims = tuple(lims)
        super().__init__(batch_shape=tuple(self.probs.shape))

    @property
    def mean(self):
        lims = self.lims

        def fn(p):
            ps = _safe_p(p, lims)
            exact = ps / (2.0 * ps - 1.0) \
                + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * ps))
            x = p - 0.5
            taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x ** 2) * x
            return jnp.where(_outside(p, lims), exact, taylor)

        return _op(fn, [self.probs], "mean")

    @property
    def variance(self):
        lims = self.lims

        def fn(p):
            ps = _safe_p(p, lims)
            exact = ps * (ps - 1.0) / (1.0 - 2.0 * ps) ** 2 \
                + 1.0 / (2.0 * jnp.arctanh(1.0 - 2.0 * ps)) ** 2
            x = p - 0.5
            taylor = 1.0 / 12.0 - (1.0 / 15.0 - 128.0 / 945.0 * x ** 2) \
                * x ** 2
            return jnp.where(_outside(p, lims), exact, taylor)

        return _op(fn, [self.probs], "variance")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), out_shape, minval=1e-6,
                               maxval=1.0 - 1e-6)
        lims = self.lims

        def icdf(p):
            # invert F(x) = (λ^x (1-λ)^(1-x) + λ - 1) / (2λ - 1):
            # (λ/(1-λ))^x = (u(2λ-1)+1-λ)/(1-λ)  =>  x = log w / logit(λ)
            ps = _safe_p(p, lims)
            w = (u * (2.0 * ps - 1.0) + 1.0 - ps) / (1.0 - ps)
            exact = jnp.log(w) / (jnp.log(ps) - jnp.log1p(-ps))
            return jnp.where(_outside(p, lims), exact, u)

        return _op(icdf, [self.probs], "cb_rsample")

    def log_prob(self, value):
        lims = self.lims
        return _op(
            lambda p, v: (_log_norm(p, lims)
                          + v * jnp.log(p) + (1.0 - v) * jnp.log1p(-p)),
            [self.probs, _as_t(value)], "cb_log_prob")

    def cdf(self, value):
        lims = self.lims
        return _op(
            lambda p, v: jnp.clip(jnp.where(
                _outside(p, lims),
                (jnp.power(_safe_p(p, lims), v)
                 * jnp.power(1.0 - _safe_p(p, lims), 1.0 - v)
                 + _safe_p(p, lims) - 1.0)
                / (2.0 * _safe_p(p, lims) - 1.0),
                v), 0.0, 1.0),
            [self.probs, _as_t(value)], "cb_cdf")

    def entropy(self):
        lims = self.lims

        def fn(p, m):
            return -(_log_norm(p, lims) + m * jnp.log(p)
                     + (1.0 - m) * jnp.log1p(-p))

        return _op(fn, [self.probs, self.mean], "cb_entropy")
