"""ExponentialFamily base (reference: python/paddle/distribution/
exponential_family.py).

TPU-native: the generic entropy/KL use the Bregman-divergence identity on
the log-normalizer A(η) — its gradients come from ``jax.grad`` instead of
the reference's double-backward graph, so any subclass only supplies its
natural parameters and ``_log_normalizer``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from .distribution import Distribution, _op


class ExponentialFamily(Distribution):
    """Base for p(x) = h(x) exp(η·T(x) − A(η)).

    Subclasses define ``_natural_parameters`` (tuple of Tensors) and
    ``_log_normalizer(*natural_params) -> array``; ``_mean_carrier_measure``
    is E[log h(x)] (0 for most families of interest).
    """

    _mean_carrier_measure = 0.0

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    def entropy(self):
        """H = A(η) − η·∇A(η) + E[T(x)]·… via the Bregman identity:
        H(p) = A(η) − <η, ∇A(η)> − E[log h(x)]."""
        nat = self._natural_parameters

        def fn(*arrs):
            a, grads = jax.value_and_grad(
                lambda params: jnp.sum(self._log_normalizer(*params)),
            )(arrs)
            ent = self._log_normalizer(*arrs) - self._mean_carrier_measure
            for eta, g in zip(arrs, grads):
                ent = ent - eta * g
            return ent

        return _op(fn, list(nat), "expfamily_entropy")


def bregman_kl(p: ExponentialFamily, q: ExponentialFamily) -> Tensor:
    """Generic same-family KL via the Bregman divergence of A(η):
    KL(p||q) = A(η_q) − A(η_p) − <η_q − η_p, ∇A(η_p)> (reference kl.py
    _kl_expfamily_expfamily)."""
    if type(p) is not type(q):
        raise NotImplementedError(
            "Bregman KL requires two distributions of the same "
            f"exponential family, got {type(p).__name__} vs "
            f"{type(q).__name__}")
    p_nat = list(p._natural_parameters)
    q_nat = list(q._natural_parameters)

    def fn(*arrs):
        k = len(arrs) // 2
        pp, qq = arrs[:k], arrs[k:]
        grads = jax.grad(
            lambda params: jnp.sum(p._log_normalizer(*params)))(pp)
        kl = q._log_normalizer(*qq) - p._log_normalizer(*pp)
        for pe, qe, g in zip(pp, qq, grads):
            kl = kl - (qe - pe) * g
        return kl

    return _op(fn, p_nat + q_nat, "kl_expfamily")
