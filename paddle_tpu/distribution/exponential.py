"""Exponential (reference: python/paddle/distribution/exponential.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _as_t, _op


class Exponential(Distribution):
    def __init__(self, rate):
        self.rate = _as_t(rate)
        super().__init__(batch_shape=tuple(self.rate.shape))

    @property
    def mean(self):
        return _op(lambda r: 1.0 / r, [self.rate], "mean")

    @property
    def variance(self):
        return _op(lambda r: 1.0 / r ** 2, [self.rate], "variance")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        e = jax.random.exponential(self._key(), out_shape)
        return _op(lambda r: e / r, [self.rate], "exponential_rsample")

    def log_prob(self, value):
        return _op(lambda r, v: jnp.log(r) - r * v,
                   [self.rate, _as_t(value)], "exponential_log_prob")

    def entropy(self):
        return _op(lambda r: 1.0 - jnp.log(r), [self.rate], "entropy")
