"""Multinomial (reference: python/paddle/distribution/multinomial.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from ..core.tensor import Tensor
from .distribution import Distribution, _as_t, _op


class Multinomial(Distribution):
    def __init__(self, total_count: int, probs):
        self.total_count = int(total_count)
        t = _as_t(probs)
        self.probs_t = _op(lambda p: p / jnp.sum(p, -1, keepdims=True),
                           [t], "multinomial_norm")
        shape = tuple(self.probs_t.shape)
        super().__init__(batch_shape=shape[:-1], event_shape=shape[-1:])

    @property
    def probs_(self):
        return self.probs_t._data

    @property
    def mean(self):
        n = self.total_count
        return _op(lambda p: n * p, [self.probs_t], "mean")

    @property
    def variance(self):
        n = self.total_count
        return _op(lambda p: n * p * (1 - p), [self.probs_t], "variance")

    def sample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        logits = jnp.log(self.probs_t._data)
        draws = jax.random.categorical(
            self._key(), logits, shape=(self.total_count,) + out_shape)
        k = self.probs_t.shape[-1]
        counts = jax.nn.one_hot(draws, k).sum(axis=0)
        return Tensor(counts)

    def log_prob(self, value):
        n = self.total_count
        return _op(
            lambda p, v: gammaln(n + 1.0) - jnp.sum(gammaln(v + 1.0), -1)
            + jnp.sum(v * jnp.log(p), -1),
            [self.probs_t, _as_t(value)], "multinomial_log_prob")
