"""Uniform (reference: python/paddle/distribution/uniform.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _as_t, _op


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_t(low)
        self.high = _as_t(high)
        shape = jnp.broadcast_shapes(tuple(self.low.shape),
                                     tuple(self.high.shape))
        super().__init__(batch_shape=shape)

    @property
    def mean(self):
        bs = self.batch_shape
        return _op(lambda l, h: jnp.broadcast_to((l + h) / 2, bs),
                   [self.low, self.high], "mean")

    @property
    def variance(self):
        bs = self.batch_shape
        return _op(lambda l, h: jnp.broadcast_to((h - l) ** 2 / 12, bs),
                   [self.low, self.high], "variance")

    def sample(self, shape=()):
        return self.rsample(shape).detach()

    def rsample(self, shape=()):
        out_shape = tuple(shape) + self.batch_shape
        u = jax.random.uniform(self._key(), out_shape)
        return _op(lambda l, h: l + u * (h - l), [self.low, self.high],
                   "uniform_rsample")

    def log_prob(self, value):
        return _op(
            lambda l, h, v: jnp.where((v >= l) & (v < h),
                                      -jnp.log(h - l), -jnp.inf),
            [self.low, self.high, _as_t(value)], "uniform_log_prob")

    def entropy(self):
        bs = self.batch_shape
        return _op(lambda l, h: jnp.broadcast_to(jnp.log(h - l), bs),
                   [self.low, self.high], "entropy")
