"""Paged KV block pool: the allocator under the continuous-batching
engine.

The physical KV cache is a fixed pool of ``num_blocks`` pages of
``block_size`` token slots each (one shared index space across every
layer's pool array — block ``i`` refers to page ``i`` of every layer).
This module owns only the *index* bookkeeping; the tensors themselves
live in :mod:`paddle_tpu.serving.engine` (fp KV or the int8
``{"q8","s"}`` quantized pools — the allocator is deliberately
dtype-agnostic, so int8 pages need no extra allocator state).

Three mechanisms, mirroring the vLLM/"Ragged Paged Attention" design:

* **Refcounted blocks** — ``allocate`` / ``fork`` (share, +1 ref) /
  ``free`` (-1 ref).  A block returns to the free list only at ref 0.
* **Prefix caching** — completed requests ``register_prefix`` their
  full prompt blocks under a rolling hash chain; a later
  ``match_prefix`` on a request with the same prompt head re-uses those
  pages (KV already resident) and skips recomputing the prefill.
  Cached blocks at ref 0 park in an *evictable* LRU rather than the
  free list; allocation evicts them only when the free list runs dry.
* **Copy-on-write** — ``cow`` gives a writer its own page when the
  block is shared (ref > 1).  The engine's sharing policy only ever
  shares *full, immutable* prompt blocks, so its writes never need COW;
  the primitive is here (and property-tested) for schedulers that share
  partially-filled tails.

A ``watermark`` fraction of the pool is held back from *new-request*
admission (``can_allocate``) so in-flight requests can still grow
during decode without immediately triggering preemption.
"""
from __future__ import annotations

import collections
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["BlockManager", "hash_block_tokens"]


def hash_block_tokens(prev_hash: Optional[int],
                      tokens: Sequence[int]) -> int:
    """Rolling hash for one full block of prompt tokens, chained on the
    hash of the previous block so equal blocks at different depths never
    collide into the same cache entry."""
    return hash((prev_hash, tuple(int(t) for t in tokens)))


class BlockManager:
    """Refcounted paged-KV allocator with prefix caching and COW."""

    def __init__(self, num_blocks: int, block_size: int,
                 watermark: float = 0.01,
                 enable_prefix_cache: bool = True):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be > 0")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        # clamp to num_blocks-1: a watermark that withholds the WHOLE
        # pool would make can_allocate(1) false forever and deadlock
        # admission on tiny pools (num_blocks * watermark rounding up to
        # the pool size); at least one block must remain admissible
        self.watermark_blocks = min(max(0, int(watermark * num_blocks)),
                                    max(0, self.num_blocks - 1))
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self._free: collections.deque[int] = collections.deque(
            range(self.num_blocks))  # guarded by: caller (ServingEngine._lock)
        self._ref: Dict[int, int] = {}  # guarded by: caller (ServingEngine._lock)
        # prefix cache: chain hash -> block id holding that block's KV
        self._hash_to_block: Dict[int, int] = {}  # guarded by: caller (ServingEngine._lock)
        self._block_hash: Dict[int, int] = {}  # guarded by: caller (ServingEngine._lock)
        # ref-0 blocks whose KV is still valid (LRU order, oldest first)
        self._evictable: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()  # guarded by: caller (ServingEngine._lock)
        # demotion/registration hooks (cluster KV tier). on_evict fires
        # with (block_id, chain_hash) BEFORE the hash is forgotten and
        # the page reused — the only moment its KV can still be saved;
        # on_register fires with (block_id, chain_hash) when a prefix
        # block is published. Both run under the caller's lock.
        self.on_evict = None  # guarded by: caller (ServingEngine._lock)
        self.on_register = None  # guarded by: caller (ServingEngine._lock)

    # ------------------------------------------------------------- hooks
    def set_hooks(self, on_evict=None, on_register=None) -> None:
        """Install the demotion/registration callbacks (see the
        attribute docs in ``__init__``)."""
        self.on_evict = on_evict
        self.on_register = on_register

    # ------------------------------------------------------------ sizing
    def num_free(self) -> int:
        """Blocks obtainable right now (free list + evictable cache)."""
        return len(self._free) + len(self._evictable)

    def free_list_size(self) -> int:
        """Blocks on the free list alone — obtainable WITHOUT evicting
        a cached prefix (the KV tier's demotion-pressure signal)."""
        return len(self._free)

    def num_in_use(self) -> int:
        return len(self._ref)

    def blocks_for_tokens(self, n_tokens: int) -> int:
        return -(-max(0, n_tokens) // self.block_size)

    def can_allocate(self, n_blocks: int) -> bool:
        """Admission check for NEW requests: leaves the watermark slack
        so running requests can keep appending decode blocks."""
        return self.num_free() - self.watermark_blocks >= n_blocks

    # -------------------------------------------------------- allocation
    def allocate(self, n_blocks: int = 1) -> List[int]:
        """Take ``n_blocks`` fresh blocks (ref 1 each); evicts LRU
        cached blocks if the free list alone can't cover it.  Raises
        ``RuntimeError`` when the pool genuinely runs dry — callers
        (the scheduler) are expected to check ``num_free`` / preempt."""
        if n_blocks > self.num_free():
            raise RuntimeError(
                "KV pool exhausted: need %d blocks, have %d"
                % (n_blocks, self.num_free()))
        out: List[int] = []
        for _ in range(n_blocks):
            if self._free:
                bid = self._free.popleft()
            else:
                bid, _ = self._evictable.popitem(last=False)
                if self.on_evict is not None:
                    h = self._block_hash.get(bid)
                    if h is not None:
                        self.on_evict(bid, h)
                self._forget_hash(bid)
            self._ref[bid] = 1
            out.append(bid)
        return out

    def fork(self, block_ids: Sequence[int]) -> None:
        """Add one reference to each block (prefix sharing)."""
        for bid in block_ids:
            self._ref[bid] += 1

    def free(self, block_ids: Sequence[int]) -> None:
        """Drop one reference per block; ref-0 blocks go back to the
        free list, except prefix-cached ones which park in the
        evictable LRU with their KV intact."""
        for bid in block_ids:
            r = self._ref[bid] - 1
            if r > 0:
                self._ref[bid] = r
                continue
            del self._ref[bid]
            if bid in self._block_hash:
                self._evictable[bid] = None
                self._evictable.move_to_end(bid)
            else:
                self._free.append(bid)

    def cow(self, block_id: int) -> Tuple[int, bool]:
        """Copy-on-write: returns ``(block_id, False)`` when the caller
        is the sole owner (write in place), else drops one ref and
        returns ``(fresh_block, True)`` — the caller must copy the page
        payload before writing."""
        if self._ref[block_id] == 1:
            return block_id, False
        self._ref[block_id] -= 1
        (new_bid,) = self.allocate(1)
        return new_bid, True

    # ------------------------------------------------------ prefix cache
    def match_prefix(self, token_ids: Sequence[int]) -> \
            Tuple[List[int], int]:
        """Longest cached prefix of ``token_ids`` in whole blocks.
        Returns ``(blocks, n_tokens)`` with one ref taken on each
        returned block.  At most ``(len-1)//block_size`` blocks match so
        at least one prompt token is always left to prefill (its logits
        seed the first generated token)."""
        if not self.enable_prefix_cache or not token_ids:
            return [], 0
        limit = (len(token_ids) - 1) // self.block_size
        blocks: List[int] = []
        h: Optional[int] = None
        for i in range(limit):
            chunk = token_ids[i * self.block_size:
                              (i + 1) * self.block_size]
            h = hash_block_tokens(h, chunk)
            bid = self._hash_to_block.get(h)
            if bid is None:
                break
            blocks.append(bid)
        # take the refs only once the walk is done
        for bid in blocks:
            if bid in self._ref:
                self._ref[bid] += 1
            else:                       # revive from the evictable LRU
                self._evictable.pop(bid, None)
                self._ref[bid] = 1
        return blocks, len(blocks) * self.block_size

    def register_prefix(self, token_ids: Sequence[int],
                        block_ids: Sequence[int]) -> int:
        """Publish the full-block prefix of a finished request into the
        cache.  Only whole blocks are hashed (a partial tail block may
        already hold decode KV).  Returns the number of blocks
        registered."""
        if not self.enable_prefix_cache:
            return 0
        n_full = len(token_ids) // self.block_size
        h: Optional[int] = None
        registered = 0
        for i in range(min(n_full, len(block_ids))):
            chunk = token_ids[i * self.block_size:
                              (i + 1) * self.block_size]
            h = hash_block_tokens(h, chunk)
            bid = block_ids[i]
            prev = self._hash_to_block.get(h)
            if prev is not None and prev != bid:
                continue                # first writer wins
            if self._block_hash.get(bid, h) != h:
                continue                # block already cached elsewhere
            self._hash_to_block[h] = bid
            self._block_hash[bid] = h
            if self.on_register is not None:
                self.on_register(bid, h)
            registered += 1
        return registered

    def probe_prefix(self, token_ids: Sequence[int]) -> int:
        """Depth (whole blocks) of the longest cached prefix WITHOUT
        taking refs — the cluster KV store's pre-fetch check for
        whether a remote copy is deeper than what's already local."""
        if not self.enable_prefix_cache or not token_ids:
            return 0
        limit = (len(token_ids) - 1) // self.block_size
        depth = 0
        h: Optional[int] = None
        for i in range(limit):
            h = hash_block_tokens(h, token_ids[i * self.block_size:
                                               (i + 1) * self.block_size])
            if self._hash_to_block.get(h) is None:
                break
            depth += 1
        return depth

    def pop_evictable(self, n: int) -> List[Tuple[int, int]]:
        """Demote up to ``n`` LRU evictable blocks: fires ``on_evict``
        for each (so the KV tier can spill the pages), forgets the
        hash, and returns the blocks to the free list.  Returns the
        ``(block_id, chain_hash)`` pairs demoted.  This is the
        watermark-driven proactive path — same as eviction-on-allocate
        but on the pump's schedule instead of under an allocation."""
        out: List[Tuple[int, int]] = []
        for _ in range(max(0, n)):
            if not self._evictable:
                break
            bid, _ = self._evictable.popitem(last=False)
            h = self._block_hash.get(bid)
            if h is not None and self.on_evict is not None:
                self.on_evict(bid, h)
            self._forget_hash(bid)
            self._free.append(bid)
            if h is not None:
                out.append((bid, h))
        return out

    def _forget_hash(self, bid: int) -> None:
        h = self._block_hash.pop(bid, None)
        if h is not None and self._hash_to_block.get(h) == bid:
            del self._hash_to_block[h]

    def clear_prefix_cache(self) -> None:
        """Drop every cached prefix; evictable blocks rejoin the free
        list."""
        for bid in list(self._evictable):
            self._forget_hash(bid)
            self._free.append(bid)
        self._evictable.clear()
        self._hash_to_block.clear()
        self._block_hash.clear()

    # -------------------------------------------------------- invariants
    def assert_no_leaks(self) -> None:
        """Every block is either free, evictable-cached, or referenced;
        the three sets are disjoint and cover the pool.  Called from
        ``ServingEngine.shutdown`` and the property tests."""
        free = set(self._free)
        evict = set(self._evictable)
        held = set(self._ref)
        assert not (free & evict), "block in free AND evictable"
        assert not (free & held), "block in free AND referenced"
        assert not (evict & held), "block evictable AND referenced"
        total = len(free) + len(evict) + len(held)
        assert total == self.num_blocks, (
            "block leak: %d tracked of %d" % (total, self.num_blocks))
        for bid, r in self._ref.items():
            assert r > 0, "non-positive refcount on block %d" % bid

    def assert_all_free(self) -> None:
        """Stronger shutdown check: no request holds any block."""
        self.assert_no_leaks()
        assert not self._ref, (
            "blocks still referenced at shutdown: %r" % (self._ref,))
