"""ServingEngine: continuous-batching inference over paged KV pools.

The engine owns the physical KV pools (per layer,
``[n_kv, num_blocks, block_size, head_dim]``, fp or int8 ``{"q8","s"}``
pages), a :class:`BlockManager` for the page index space, a
:class:`Scheduler` for slots, and — by default — exactly ONE jitted
program: a fixed-shape RAGGED step (``ragged_paged_attention``) whose
flat ``[token_budget]`` token axis packs every RUNNING slot's decode
token next to as many prefill-chunk tokens as fit, so mixed
prefill+decode traffic costs one dispatch per scheduler tick and
prefill no longer serializes against decode. Rows join and leave by
mask (``query_lens == 0`` = idle slot, position ``-1`` = padding), so
the step compiles once and never again (``ragged_compiles`` asserts
this).

``PADDLE_TPU_SERVE_RAGGED=off`` restores the previous TWO-program
layout byte-for-byte — one ``max_slots``-row decode step plus one
``[1, prefill_chunk]`` prefill step, interleaved (``decode_compiles`` /
``prefill_compiles`` assert their once-only traces there).

All step programs are pure — pools in, pools out — which makes the
dispatch safely retryable: the step body runs under
``resilience.call_with_retry`` (site ``serving.step``) with the retry
deadline derived from the nearest per-request deadline, and
``resilience.faults.check("serving.step")`` is consulted inside the
retried body so injected ``ConnectionError`` faults exercise the same
recovery path real transport errors would.

Requests stream tokens through per-request queues:
``rid = engine.submit(prompt)``, ``for tok in engine.stream(rid)``.
``engine.start()`` runs the step loop on a background thread;
tests may instead call ``engine.step()`` directly for determinism.
"""
from __future__ import annotations

import dataclasses
import os
import queue
import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import observability as _obs
from ..config import knobs as _knobs
from ..distributed.resilience import faults
from ..distributed.resilience.retry import call_with_retry, default_policy
from ..incubate.nn.pallas.paged_attention import quantize_kv_pages
from ..models.generation import _sample
from ..observability.tracing import span
from .block_manager import BlockManager
from .kv_store import codec as kv_codec
from .scheduler import (CANCELLED, FINISHED, HANDOFF, PREFILL, RUNNING,
                        PrefillChunk, Request, Scheduler)

__all__ = ["ServingEngine", "RequestError", "EngineConfig",
           "RequestDescriptor", "EngineStats", "KVHandoff"]


@dataclasses.dataclass(frozen=True)
class RequestDescriptor:
    """Replayable snapshot of one in-flight request. Greedy decoding is
    deterministic, so ``prompt + generated`` resubmitted with
    ``remaining`` new tokens on ANY engine holding the same weights
    continues the exact same stream — this is what the cluster router
    replays after a replica death."""
    rid: int
    prompt: Tuple[int, ...]
    generated: Tuple[int, ...]
    remaining: int
    temperature: float
    top_p: float
    eos_id: Optional[int]
    deadline: Optional[float]          # absolute time.monotonic()
    state: str


@dataclasses.dataclass(frozen=True)
class EngineStats:
    """Lock-held health snapshot for routers/monitors (see
    :meth:`ServingEngine.stats`)."""
    free_blocks: int
    total_blocks: int
    watermark_blocks: int
    block_size: int
    queue_depth: int                   # waiting for a slot
    prefilling: int
    running: int
    active_slots: int
    max_slots: int
    decode_compiles: int
    ragged_compiles: int
    inflight: Tuple[RequestDescriptor, ...]

    def can_admit(self, n_blocks: int) -> bool:
        """Mirror of ``BlockManager.can_allocate`` over the snapshot."""
        return self.free_blocks - self.watermark_blocks >= n_blocks


@dataclasses.dataclass(frozen=True)
class KVHandoff:
    """One prefilled request leaving a prefill replica: prompt KV pages
    (native pool layout — fp arrays or int8 ``{"q8","s"}`` dicts, one
    per layer) plus everything a decode replica needs to seat it
    directly into a RUNNING slot."""
    src_rid: int                       # rid on the PREFILL engine
    prompt: Tuple[int, ...]
    first_token: int
    max_new_tokens: int
    temperature: float
    top_p: float
    eos_id: Optional[int]
    deadline: Optional[float]          # absolute time.monotonic()
    block_size: int
    kv_quant: Optional[str]
    num_blocks: int                    # pages carried per layer
    k_pages: Tuple[object, ...]        # per layer: [n_kv, nb, page, d]
    v_pages: Tuple[object, ...]

    def nbytes(self) -> int:
        return kv_codec.pages_nbytes(self.k_pages) + \
            kv_codec.pages_nbytes(self.v_pages)


class RequestError(RuntimeError):
    """A stream ended abnormally (cancelled / deadline / shutdown)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class EngineConfig:
    """Resolved engine knobs (ctor args win over env vars)."""

    def __init__(self, max_slots=None, block_size=None, num_blocks=None,
                 prefill_chunk=None, max_seq_len=None, kv_quant=None,
                 watermark=0.01, enable_prefix_cache=True, seed=0,
                 ragged=None, token_budget=None, name=None):
        # telemetry source label: access-log records and window
        # snapshots carry it (a Replica passes its replica name)
        self.name = str(name) if name else "engine"
        self.max_slots = max_slots or _knobs.get_int(
            "PADDLE_TPU_SERVE_SLOTS")
        self.block_size = block_size or _knobs.get_int(
            "PADDLE_TPU_SERVE_BLOCK_SIZE")
        self.num_blocks = num_blocks or _knobs.get_int(
            "PADDLE_TPU_SERVE_NUM_BLOCKS")
        self.prefill_chunk = prefill_chunk or _knobs.get_int(
            "PADDLE_TPU_SERVE_PREFILL_CHUNK")
        self.max_seq_len = max_seq_len
        self.kv_quant = kv_quant        # None | "int8"
        self.watermark = watermark
        self.enable_prefix_cache = enable_prefix_cache
        self.seed = seed
        # ragged single-dispatch step: auto (-> on) | on | off.  "off"
        # restores the two-program decode+prefill layout byte-for-byte.
        self.ragged = (ragged or _knobs.get_str(
            "PADDLE_TPU_SERVE_RAGGED")).lower()
        # token axis of the ragged step: decode rows + prefill chunk
        # tokens packed per step (clamped to >= max_slots in the engine)
        self.token_budget = token_budget or _knobs.get_int(
            "PADDLE_TPU_SERVE_TOKEN_BUDGET",
            default=self.max_slots + self.prefill_chunk)
        if self.kv_quant not in (None, "int8"):
            raise ValueError("kv_quant must be None or 'int8'")
        if self.ragged not in ("auto", "on", "off"):
            raise ValueError(
                "PADDLE_TPU_SERVE_RAGGED must be auto|on|off")
        if self.token_budget <= 0:
            raise ValueError("token_budget must be > 0")


class ServingEngine:
    def __init__(self, model, **knobs):
        cfg = EngineConfig(**knobs)
        self.config = cfg
        ad = model.decode_adapter()
        # detach the weights: the jitted steps take them as an argument,
        # so the adapter methods stay pure over arrays
        self._w, ad.weights = ad.weights, None
        self._ad = ad
        model_max = getattr(getattr(model, "config", None),
                            "max_position_embeddings", 2048)
        self.max_seq_len = min(cfg.max_seq_len or model_max, model_max)
        self.pages_per_seq = -(-self.max_seq_len // cfg.block_size)

        self.manager = BlockManager(
            cfg.num_blocks, cfg.block_size, watermark=cfg.watermark,
            enable_prefix_cache=cfg.enable_prefix_cache)
        self.scheduler = Scheduler(self.manager, cfg.max_slots,
                                   cfg.prefill_chunk, self.max_seq_len)

        kvd = self._w["wte"].dtype
        shape = (ad.num_kv_heads, cfg.num_blocks, cfg.block_size,
                 ad.head_dim)
        if cfg.kv_quant == "int8":
            mk = lambda: quantize_kv_pages(jnp.zeros(shape, kvd))  # noqa: E731
        else:
            mk = lambda: jnp.zeros(shape, kvd)                     # noqa: E731
        self._kp = tuple(mk() for _ in range(ad.num_layers))
        self._vp = tuple(mk() for _ in range(ad.num_layers))

        self._key = jax.random.PRNGKey(cfg.seed)
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self.ragged_compiles = 0
        self._decode_fn = jax.jit(self._decode_step)
        self._prefill_fn = jax.jit(self._prefill_step)
        self._ragged_fn = jax.jit(self._ragged_step)
        self._ragged = cfg.ragged != "off"      # auto -> on
        # the flat token axis must cover the worst-case decode rows
        # (max_slots - 1 running + 1 prefill slot needing >= 1 token)
        self._token_budget = max(cfg.token_budget, cfg.max_slots)

        self._lock = threading.RLock()
        self._wakeup = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._requests: Dict[int, Request] = {}  # guarded by: _lock
        self._streams: Dict[int, "queue.Queue"] = {}  # guarded by: _lock
        self._last_emit: Dict[int, float] = {}  # guarded by: _lock
        self._handoff_ready: List[Request] = []  # guarded by: _lock
        self._dead = False  # guarded by: _lock (fail_all called)
        # cluster KV tier hooks (set_kv_hooks): registration/eviction
        # of prefix-cached blocks flows to the cluster store when wired
        self._kv_register = None  # guarded by: _lock
        self._kv_evict = None  # guarded by: _lock
        self.manager.set_hooks(on_evict=self._on_block_evicted,
                               on_register=self._on_block_registered)
        # request-scoped observability (PR 16): access log + rolling
        # windows + SLO engine, all built lazily on first touch so a
        # telemetry-disabled engine allocates none of it
        self._log = None
        self._slo = None

    # --------------------------------------------- request observability
    @property
    def request_log(self):
        """This engine's access log (+ rolling ``rt.*`` windows).
        Created on first access; records accumulate only while
        telemetry is enabled."""
        if self._log is None:
            from ..observability.request_log import RequestLog
            self._log = RequestLog(source=self.config.name)
        return self._log

    @property
    def windows(self):
        """Rolling-window instruments (``rt.*``) for this engine."""
        return self.request_log.windows

    @property
    def slo(self):
        """SLO engine over this engine's rolling windows."""
        if self._slo is None:
            from ..observability.slo import SLOEngine
            self._slo = SLOEngine(self.windows)
        return self._slo

    def ops_snapshot(self) -> dict:
        """One JSON-able dict with everything the ops dashboard
        renders: per-source window snapshots, the SLO report, the
        autoscaler signal feed, latency attribution, and the
        access-log tail. ``tools/ptop.py --snapshot`` reads this shape
        (the router emits the same shape with more replicas)."""
        st = self.stats()
        log = self.request_log
        return {
            "kind": "ops_snapshot", "source": self.config.name,
            "ts": time.time(),
            "replicas": {self.config.name: {
                "alive": not self.dead,
                "queue_depth": st.queue_depth,
                "active_slots": st.active_slots,
                "max_slots": st.max_slots,
                "running": st.running, "prefilling": st.prefilling,
                "free_blocks": st.free_blocks,
                "total_blocks": st.total_blocks,
                "windows": log.windows.snapshot()}},
            "slo": self.slo.evaluate(),
            "signals": self.slo.load_signals(),
            "attribution": log.attribution(),
            "requests": log.tail(50)}

    def dump_ops_snapshot(self, path: str) -> dict:
        snap = self.ops_snapshot()
        from ..observability.request_log import write_snapshot
        write_snapshot(snap, path)
        return snap

    # ----------------------------------------------------- jitted bodies
    def _decode_step(self, w, toks, pos, kp, vp, bt, temp, top_p, key):
        # trace-time side effect BY DESIGN: increments once per compile,
        # which is what lets tests assert decode_compiles == 1
        self.decode_compiles += 1  # ptlint: disable=jit-purity
        if _obs.enabled():
            _obs.registry.counter("serving.decode_compiles").inc()
        lg, kp, vp = self._ad.paged_chunk(
            w, toks[:, None], pos[:, None], kp, vp, bt)
        nxt = _sample(lg[:, 0], key, temp, top_p)
        return nxt, kp, vp

    def _prefill_step(self, w, toks, pos, kp, vp, bt_row, last_idx,
                      temp, top_p, key):
        self.prefill_compiles += 1  # ptlint: disable=jit-purity  (trace-time compile counter)
        lg, kp, vp = self._ad.paged_chunk(w, toks, pos, kp, vp, bt_row)
        row = jnp.take(lg[0], last_idx, axis=0)
        nxt = _sample(row[None], key, temp[None], top_p[None])[0]
        return nxt, kp, vp

    def _ragged_step(self, w, toks, pos, row_of, qs, ql, cl, kp, vp,
                     bt, temp, top_p, key):
        """THE serving step when ragged mode is on: one dispatch covers
        every decode row and every packed prefill-chunk token. Samples
        one candidate token per row from its last logit (idle rows
        sample garbage that the host discards)."""
        self.ragged_compiles += 1  # ptlint: disable=jit-purity  (trace-time compile counter)
        if _obs.enabled():
            _obs.registry.counter("serving.ragged_compiles").inc()
        lg, kp, vp = self._ad.ragged_chunk(
            w, toks, pos, row_of, qs, ql, cl, kp, vp, bt)
        last = jnp.clip(qs + ql - 1, 0, toks.shape[0] - 1)
        nxt = _sample(jnp.take(lg, last, axis=0), key, temp, top_p)
        return nxt, kp, vp

    # ----------------------------------------------------- public intake
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               temperature: float = 0.0, top_p: float = 1.0,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               handoff: bool = False) -> int:
        """Queue a request; returns its rid for stream()/cancel().
        ``handoff=True`` (disaggregated prefill) stops after the prompt
        is prefilled and the first token sampled — the request then
        waits in the handoff queue for :meth:`take_handoff` instead of
        decoding here."""
        prompt = [int(t) for t in prompt]
        if len(prompt) + max_new_tokens > self.max_seq_len:
            raise ValueError(
                "prompt %d + max_new_tokens %d exceeds max_seq_len %d"
                % (len(prompt), max_new_tokens, self.max_seq_len))
        now = time.monotonic()
        req = Request(prompt=prompt, max_new_tokens=max_new_tokens,
                      temperature=float(temperature), top_p=float(top_p),
                      eos_id=eos_id, arrival=now,
                      deadline=None if deadline_s is None
                      else now + deadline_s,
                      handoff=bool(handoff))
        with self._lock:
            if self._dead:
                raise RequestError("replica_dead")
            if _obs.enabled():
                req.timeline = self.request_log.open(
                    req.rid, prompt_tokens=len(prompt))
            self._requests[req.rid] = req
            self._streams[req.rid] = queue.Queue()
            self.scheduler.add(req)
        self._wakeup.set()
        return req.rid

    def stream(self, rid: int) -> Iterator[int]:
        """Per-token iterator; raises RequestError on abnormal end."""
        with self._lock:
            q = self._streams[rid]
        while True:
            kind, val = q.get()
            if kind == "tok":
                yield val
            elif val in ("eos", "length"):
                return
            else:
                raise RequestError(val)

    def cancel(self, rid: int, reason: str = "cancelled") -> None:
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                return
            self.scheduler.cancel(req, reason)
            self._end_stream(req, reason)

    def result(self, rid: int) -> List[int]:
        """Convenience: drain the whole stream into a list."""
        return list(self.stream(rid))

    def events(self, rid: int) -> Iterator[Tuple[str, object]]:
        """Raw per-request event iterator: ``("tok", t)`` items followed
        by one ``("end", reason)``. Unlike :meth:`stream` this exposes
        the termination reason, which the cluster router needs to tell
        a normal end (eos/length) from a replica death it must replay."""
        with self._lock:
            q = self._streams[rid]
        while True:
            kind, val = q.get()
            yield kind, val
            if kind != "tok":
                return

    # ----------------------------------------------------- health/stats
    def _descriptor(self, req: Request) -> RequestDescriptor:  # ptlint: holds=_lock
        return RequestDescriptor(
            rid=req.rid, prompt=tuple(req.prompt),
            generated=tuple(req.generated), remaining=req.remaining,
            temperature=req.temperature, top_p=req.top_p,
            eos_id=req.eos_id, deadline=req.deadline, state=req.state)

    def stats(self) -> EngineStats:
        """Thread-safe health snapshot: free/watermark blocks, slot and
        queue occupancy, and replayable descriptors of every in-flight
        request. The whole snapshot is built under ``_lock`` (the fields
        read here are `# guarded by: _lock` / caller-guarded state) so
        it is internally consistent — a router sees matching queue depth
        and descriptor list, never a torn read."""
        with self._lock:
            prefilling = running = 0
            for r in self.scheduler.slots.values():
                if r.state == RUNNING:
                    running += 1
                elif r.state in (PREFILL, HANDOFF):
                    prefilling += 1
            inflight = tuple(
                self._descriptor(r) for r in self._requests.values()
                if r.state not in (FINISHED, CANCELLED))
            return EngineStats(
                free_blocks=self.manager.num_free(),
                total_blocks=self.manager.num_blocks,
                watermark_blocks=self.manager.watermark_blocks,
                block_size=self.manager.block_size,
                queue_depth=len(self.scheduler.waiting),
                prefilling=prefilling,
                running=running,
                active_slots=self.scheduler.num_active(),
                max_slots=self.config.max_slots,
                decode_compiles=self.decode_compiles,
                ragged_compiles=self.ragged_compiles,
                inflight=inflight)

    @property
    def dead(self) -> bool:
        with self._lock:
            return self._dead

    def fail_all(self, reason: str = "replica_dead") \
            -> Tuple[RequestDescriptor, ...]:
        """Simulated replica crash: atomically capture a replayable
        descriptor for every live request, cancel them all (streams end
        with ``reason``), release every page, and refuse further work.
        The returned descriptors are the router's drain list."""
        with self._lock:
            self._dead = True
            descs = []
            for req in list(self._requests.values()):
                if req.state in (FINISHED, CANCELLED):
                    continue
                descs.append(self._descriptor(req))
                self.scheduler.cancel(req, reason)
                self._end_stream(req, reason)
            self._handoff_ready.clear()
            return tuple(descs)

    # ------------------------------------------------------- AOT warmup
    def warmup(self, token: int = 0) -> None:
        """AOT warmup: run one tiny request through the engine so the
        active step program is traced and compiled before real traffic
        arrives — the single ragged jit by default, or BOTH legacy
        programs (prefill-chunk and fixed-shape decode) when
        ``PADDLE_TPU_SERVE_RAGGED=off`` — so a fresh replica serves its
        first token without a cold compile. The
        1-token prompt registers nothing in the prefix cache (only full
        blocks are hashed) and the pool drains back to empty.

        The warmup request is synthetic, so it records into a scratch
        access log that is discarded afterwards: its compile-inflated
        TTFT must not land in the real ``rt.*`` windows, where one
        multi-second sample would keep the SLO burn (and with it the
        autoscaler's ``want_scale_up`` hint) lit for the whole slow
        horizon."""
        if self._thread is not None:
            raise RuntimeError("warmup() must run before start()")
        from ..observability.request_log import RequestLog
        real_log = self._log
        self._log = RequestLog(source=self.config.name + ".warmup")
        try:
            rid = self.submit([int(token)], max_new_tokens=2)
            steps = 0
            while self.step():
                steps += 1
                if steps > 64:
                    raise RuntimeError("warmup failed to drain")
            list(self.stream(rid))      # queue already holds the end
            with self._lock:
                self._requests.pop(rid, None)
                self._streams.pop(rid, None)
        finally:
            self._log = real_log

    # ------------------------------------------- disaggregated handoff
    def _export_pages(self, blocks: List[int]):  # ptlint: holds=_lock
        """Materialize the KV pages of ``blocks`` (host copies, native
        pool layout) through the shared :mod:`kv_store.codec`."""
        return (kv_codec.take_pages(self._kp, blocks),
                kv_codec.take_pages(self._vp, blocks))

    @staticmethod
    def _import_pages(pool, blocks, pages):
        """Write exported pages into this engine's pool at ``blocks``
        (shared :mod:`kv_store.codec` — the one int8<->fp decode rule)."""
        return kv_codec.put_pages(pool, blocks, pages)

    def take_handoff(self) -> Optional[KVHandoff]:
        """Pop one prefilled request off the handoff queue as a
        :class:`KVHandoff` payload; its pages are exported (host
        copies) and then released here — full prompt blocks go to the
        prefix cache exactly like a normal completion, so repeated
        prefixes still hit on this prefill replica."""
        with self._lock:
            while self._handoff_ready:
                req = self._handoff_ready.pop(0)
                if req.state != HANDOFF:
                    continue            # cancelled while parked
                k, v = self._export_pages(req.blocks)
                payload = KVHandoff(
                    src_rid=req.rid,
                    prompt=tuple(req.prompt),
                    first_token=int(req.handoff_token),
                    max_new_tokens=req.max_new_tokens,
                    temperature=req.temperature, top_p=req.top_p,
                    eos_id=req.eos_id, deadline=req.deadline,
                    block_size=self.manager.block_size,
                    kv_quant=self.config.kv_quant,
                    num_blocks=len(req.blocks), k_pages=k, v_pages=v)
                self.scheduler.finish(req, "handoff")
                self._end_stream(req, "handoff")
                return payload
            return None

    def adopt_handoff(self, payload: KVHandoff) -> Optional[int]:
        """Seat a :class:`KVHandoff` from a prefill replica straight
        into a RUNNING decode slot: allocate pages, import the KV, and
        decode from position ``len(prompt)`` on. Returns the local rid,
        or ``None`` when this engine has no free slot / pages right now
        (the caller re-offers later). The first token was already
        sampled by the prefill replica and is NOT re-emitted here."""
        if payload.block_size != self.manager.block_size:
            raise ValueError(
                "handoff block_size %d != engine block_size %d"
                % (payload.block_size, self.manager.block_size))
        with self._lock:
            if self._dead:
                return None
            need = payload.num_blocks
            if not self.scheduler._free_slots or \
                    not self.manager.can_allocate(need):
                return None
            blocks = self.manager.allocate(need)
            self._kp = tuple(
                self._import_pages(p, blocks, pg)
                for p, pg in zip(self._kp, payload.k_pages))
            self._vp = tuple(
                self._import_pages(p, blocks, pg)
                for p, pg in zip(self._vp, payload.v_pages))
            req = Request(prompt=list(payload.prompt),
                          max_new_tokens=payload.max_new_tokens,
                          temperature=payload.temperature,
                          top_p=payload.top_p, eos_id=payload.eos_id,
                          deadline=payload.deadline,
                          arrival=time.monotonic())
            req.generated = [payload.first_token]
            req.remaining = payload.max_new_tokens - 1
            req.first_token_at = req.arrival
            if _obs.enabled():
                # adopted requests skip queue/prefill here; TTFT is NOT
                # stamped — the first token streamed on the prefill
                # replica, a local ~0 would corrupt the window
                tl = self.request_log.open(
                    req.rid, prompt_tokens=len(req.prompt))
                tl.mark_admitted()
                tl.mark_running(stamp_ttft=False)
                req.timeline = tl
            self.scheduler.place_running(req, blocks)
            self._requests[req.rid] = req
            self._streams[req.rid] = queue.Queue()
        self._wakeup.set()
        return req.rid

    # ------------------------------------------------ cluster KV tier
    def set_kv_hooks(self, on_register=None, on_evict=None) -> None:
        """Wire this engine into a cluster KV store.  ``on_register(h)``
        fires when a prefix block is published under chain hash ``h``;
        ``on_evict(h, k_pages, v_pages)`` fires when a cached block is
        about to be reused, with its pages already exported (host
        copies) so the tier can spill instead of discard.  Both run
        under the engine lock — hooks must not call back into the
        engine (enqueue and return)."""
        with self._lock:
            self._kv_register = on_register
            self._kv_evict = on_evict

    def _on_block_registered(self, bid: int, h: int) -> None:  # ptlint: holds=_lock
        # BlockManager hook; runs under _lock (manager is only mutated
        # under it), may re-enter via the RLock
        cb = self._kv_register
        if cb is not None:
            cb(h)

    def _on_block_evicted(self, bid: int, h: int) -> None:  # ptlint: holds=_lock
        # fires BEFORE the page is reused/forgotten: the one moment the
        # block's KV can still be saved. Export is a single-block host
        # copy — synchronous by necessity (the page is overwritten the
        # instant this returns); quantize/spill happen on the pump.
        cb = self._kv_evict
        if cb is None:
            return
        k, v = self._export_pages([bid])
        cb(h, k, v)

    def probe_prefix(self, prompt: Sequence[int]) -> int:
        """Local prefix-cache depth (whole blocks) without taking refs."""
        with self._lock:
            return self.manager.probe_prefix(list(prompt))

    def export_prefix(self, prompt: Sequence[int]):
        """Export the pages of this engine's longest cached prefix of
        ``prompt`` (host copies, native pool layout).  Returns
        ``(k_pages, v_pages, n_blocks)`` or None when nothing matches.
        The blocks are revived+freed around the copy, so they stay
        MRU in the evictable cache — serving a cross-replica fetch
        refreshes the prefix here too."""
        with self._lock:
            if self._dead:
                return None
            blocks, _ = self.manager.match_prefix(list(prompt))
            if not blocks:
                return None
            k, v = self._export_pages(blocks)
            self.manager.free(blocks)
            return k, v, len(blocks)

    def import_prefix(self, prompt: Sequence[int], n_blocks: int,
                      k_pages, v_pages) -> int:
        """Seat a fetched prefix into this engine's prefix cache:
        allocate pages, import the KV through the shared codec, publish
        the blocks under the prompt's chain hashes, and park them in
        the evictable LRU — the scheduler's normal ``match_prefix``
        then hits them at admission.  Returns tokens made resident (0
        when the local cache is already at least as deep, the pool
        can't take the pages, or prefix caching is off).  Raises
        ``ValueError`` for fp pages offered to an int8 pool (the codec
        refuses lossy requantization)."""
        bs = self.manager.block_size
        with self._lock:
            if self._dead or not self.manager.enable_prefix_cache:
                return 0
            n = min(int(n_blocks), (len(prompt) - 1) // bs)
            if n <= 0 or self.manager.probe_prefix(prompt) >= n:
                return 0
            if not self.manager.can_allocate(n):
                return 0

            def clip(pg):
                if n == n_blocks:
                    return pg
                if isinstance(pg, dict):
                    return {"q8": pg["q8"][:, :n], "s": pg["s"][:, :n]}
                return pg[:, :n]

            blocks = self.manager.allocate(n)
            self._kp = tuple(
                kv_codec.put_pages(p, blocks, clip(pg))
                for p, pg in zip(self._kp, k_pages))
            self._vp = tuple(
                kv_codec.put_pages(p, blocks, clip(pg))
                for p, pg in zip(self._vp, v_pages))
            # first-writer-wins: blocks whose chain hash is already
            # cached here stay unregistered and fall back to the free
            # list on free() — no leak, no double-mapping
            self.manager.register_prefix(list(prompt)[:n * bs], blocks)
            self.manager.free(blocks)
            return n * bs

    def demote_evictable(self, n: int) -> int:
        """Watermark-driven proactive demotion: when the free list has
        drained to the admission watermark, hand up to ``n`` LRU
        evictable blocks to the KV tier (via the eviction hook) and
        return them to the free list.  No-op while free blocks are
        plentiful or no tier is wired."""
        with self._lock:
            if self._dead or self._kv_evict is None:
                return 0
            # pressure signal: the DIRECTLY usable free list (not
            # counting evictable pages) is at/below the watermark
            if self.manager.free_list_size() > \
                    self.manager.watermark_blocks:
                return 0
            return len(self.manager.pop_evictable(n))

    # ------------------------------------------------------- step engine
    def step(self) -> bool:
        """One scheduler round. Ragged mode (the default): admit, then
        ONE mixed dispatch covering every decode row plus packed
        prefill chunks. Off mode: admit, one prefill chunk, one decode
        batch. Returns False when there was nothing to do."""
        t0 = time.monotonic()
        with self._lock, span("serving.step"):
            if self._dead:
                return False
            self._expire_deadlines()
            admitted = self.scheduler.admit()
            for req in admitted:
                if req.num_cached and _obs.enabled():
                    _obs.registry.counter(
                        "serving.prefix_hit_tokens").inc(req.num_cached)
                    if req.timeline is not None:
                        req.timeline.mark_prefix_hit(req.num_cached)
            if self._ragged:
                preempted = self.scheduler.ensure_decode_blocks()
                worked = self._run_ragged()
            else:
                chunk = self.scheduler.next_prefill()
                if chunk is not None:
                    self._run_prefill(chunk)
                preempted = self.scheduler.ensure_decode_blocks()
                running = self.scheduler.running()
                if running:
                    self._run_decode(running)
                worked = chunk is not None or bool(running)
            if _obs.enabled():
                if preempted:
                    _obs.registry.counter("serving.preemptions").inc(
                        len(preempted))
                _obs.registry.gauge("serving.queue_depth").set(
                    len(self.scheduler.waiting))
                _obs.registry.gauge("serving.slot_occupancy").set(
                    self.scheduler.num_active())
                _obs.registry.histogram("serving.step_time").observe(
                    time.monotonic() - t0)
                win = self.request_log.windows
                win.gauge("rt.queue_depth").set(
                    len(self.scheduler.waiting))
                win.gauge("rt.slot_util").set(
                    self.scheduler.num_active() / self.config.max_slots)
            return bool(admitted or worked)

    def _dispatch(self, fn):  # ptlint: holds=_lock
        """Run one jitted step under the resilience machinery: injected
        or real ConnectionError/TimeoutError gets retried with backoff,
        bounded by the nearest per-request deadline."""
        nearest = None
        now = time.monotonic()
        for req in self.scheduler.slots.values():
            if req.deadline is not None:
                left = max(0.0, req.deadline - now)
                nearest = left if nearest is None else min(nearest, left)

        def body():
            act = faults.check("serving.step")
            if act is not None:
                faults.apply(act)
            return fn()

        return call_with_retry(body, default_policy(deadline=nearest),
                               site="serving.step")

    def _run_ragged(self) -> bool:  # ptlint: holds=_lock
        """Build and dispatch ONE ragged mixed batch: every RUNNING
        slot contributes its decode token, then PREFILL slots pack
        prompt chunks into the remaining token budget (oldest first).
        All arrays are fixed padded shapes — [token_budget] tokens,
        [max_slots] rows (row index == slot index) — so the single jit
        traces exactly once for the engine's lifetime."""
        cfg = self.config
        R = cfg.max_slots
        T = self._token_budget
        running = self.scheduler.running()
        chunks = self.scheduler.next_prefills(T - len(running))
        if not running and not chunks:
            return False
        toks = np.zeros(T, np.int32)
        pos = np.full(T, -1, np.int32)
        row_of = np.full(T, -1, np.int32)
        qs = np.zeros(R, np.int32)
        ql = np.zeros(R, np.int32)
        cl = np.zeros(R, np.int32)
        temp = np.zeros(R, np.float32)
        top_p = np.ones(R, np.float32)
        bt = np.zeros((R, self.pages_per_seq), np.int32)
        cursor = 0
        for req in running:
            s = req.slot
            qs[s] = cursor
            ql[s] = 1
            cl[s] = req.total_len()
            toks[cursor] = req.generated[-1]
            pos[cursor] = req.decode_pos()
            row_of[cursor] = s
            temp[s] = req.temperature
            top_p[s] = req.top_p
            bt[s, :len(req.blocks)] = req.blocks
            cursor += 1
        for ch in chunks:
            req = ch.req
            s = req.slot
            n = len(ch.tokens)
            qs[s] = cursor
            ql[s] = n
            cl[s] = ch.start + n
            toks[cursor:cursor + n] = ch.tokens
            pos[cursor:cursor + n] = np.arange(ch.start, ch.start + n)
            row_of[cursor:cursor + n] = s
            temp[s] = req.temperature
            top_p[s] = req.top_p
            bt[s, :len(req.blocks)] = req.blocks
            cursor += n
        n_prefill = cursor - len(running)
        self._key, sub = jax.random.split(self._key)
        with span("serving.ragged_step",
                  args={"rows": len(running) + len(chunks),
                        "tokens": cursor}):
            nxt, self._kp, self._vp = self._dispatch(
                lambda: self._ragged_fn(
                    self._w, jnp.asarray(toks), jnp.asarray(pos),
                    jnp.asarray(row_of), jnp.asarray(qs),
                    jnp.asarray(ql), jnp.asarray(cl), self._kp,
                    self._vp, jnp.asarray(bt), jnp.asarray(temp),
                    jnp.asarray(top_p), sub))
        out = np.asarray(nxt)
        if _obs.enabled():
            _obs.registry.counter("serving.ragged_steps").inc()
            if running:
                _obs.registry.counter("serving.decode_tokens").inc(
                    len(running))
            if n_prefill:
                _obs.registry.counter("serving.prefill_tokens").inc(
                    n_prefill)
            _obs.registry.histogram("serving.ragged_fill").observe(
                cursor / T)
        for req in running:
            if req.state == RUNNING:     # not cancelled mid-dispatch
                self._emit(req, int(out[req.slot]))
        for ch in chunks:
            req = ch.req
            if req.state != PREFILL:     # cancelled mid-dispatch
                continue
            req.prefilled = ch.start + len(ch.tokens)
            if not ch.last:
                continue
            # first token emits in the SAME step the final chunk
            # completes; TTFT is observed once per request (a preempted
            # request re-prefills but already streamed its first token)
            if req.first_token_at is None:
                req.first_token_at = time.monotonic()
                if _obs.enabled():
                    _obs.registry.histogram("serving.ttft").observe(
                        req.first_token_at - req.arrival)
            if req.timeline is not None:
                req.timeline.mark_running()
            if req.handoff:
                req.state = HANDOFF
                req.handoff_token = int(out[req.slot])
                self._handoff_ready.append(req)
            else:
                req.state = RUNNING
                self._emit(req, int(out[req.slot]))
        return True

    def _run_prefill(self, chunk: PrefillChunk) -> None:  # ptlint: holds=_lock
        req, cfg = chunk.req, self.config
        n = len(chunk.tokens)
        toks = np.zeros((1, cfg.prefill_chunk), np.int32)
        pos = np.full((1, cfg.prefill_chunk), -1, np.int32)
        toks[0, :n] = chunk.tokens
        pos[0, :n] = np.arange(chunk.start, chunk.start + n)
        bt = np.zeros((1, self.pages_per_seq), np.int32)
        bt[0, :len(req.blocks)] = req.blocks
        self._key, sub = jax.random.split(self._key)
        with span("serving.prefill", args={"rid": req.rid, "n": n}):
            nxt, self._kp, self._vp = self._dispatch(
                lambda: self._prefill_fn(
                    self._w, jnp.asarray(toks), jnp.asarray(pos),
                    self._kp, self._vp, jnp.asarray(bt),
                    jnp.int32(n - 1), jnp.float32(req.temperature),
                    jnp.float32(req.top_p), sub))
        req.prefilled = chunk.start + n
        if _obs.enabled():
            _obs.registry.counter("serving.prefill_tokens").inc(n)
        if chunk.last:
            # observed once per request: a preempted request re-prefills
            # (prompt + generated folded) but its first token already
            # streamed long ago — re-stamping would corrupt serving.ttft
            if req.first_token_at is None:
                req.first_token_at = time.monotonic()
                if _obs.enabled():
                    _obs.registry.histogram("serving.ttft").observe(
                        req.first_token_at - req.arrival)
            if req.timeline is not None:
                req.timeline.mark_running()
            if req.handoff:
                # disaggregated prefill: park for take_handoff() — the
                # pages stay resident until the payload is exported
                req.state = HANDOFF
                req.handoff_token = int(nxt)
                self._handoff_ready.append(req)
            else:
                req.state = RUNNING
                self._emit(req, int(nxt))

    def _run_decode(self, running: List[Request]) -> None:  # ptlint: holds=_lock
        cfg = self.config
        S = cfg.max_slots
        toks = np.zeros(S, np.int32)
        pos = np.full(S, -1, np.int32)
        temp = np.zeros(S, np.float32)
        top_p = np.ones(S, np.float32)
        bt = np.zeros((S, self.pages_per_seq), np.int32)
        for req in running:
            s = req.slot
            toks[s] = req.generated[-1]
            pos[s] = req.decode_pos()
            temp[s] = req.temperature
            top_p[s] = req.top_p
            bt[s, :len(req.blocks)] = req.blocks
        self._key, sub = jax.random.split(self._key)
        with span("serving.decode", args={"n": len(running)}):
            nxt, self._kp, self._vp = self._dispatch(
                lambda: self._decode_fn(
                    self._w, jnp.asarray(toks), jnp.asarray(pos),
                    self._kp, self._vp, jnp.asarray(bt),
                    jnp.asarray(temp), jnp.asarray(top_p), sub))
        out = np.asarray(nxt)
        if _obs.enabled():
            _obs.registry.counter("serving.decode_tokens").inc(
                len(running))
        for req in running:
            if req.state == RUNNING:     # not cancelled mid-dispatch
                self._emit(req, int(out[req.slot]))

    def _emit(self, req: Request, tok: int) -> None:  # ptlint: holds=_lock
        req.generated.append(tok)
        req.remaining -= 1
        now = time.monotonic()
        last = self._last_emit.get(req.rid)
        if last is not None and _obs.enabled():
            _obs.registry.histogram("serving.token_latency").observe(
                now - last)
        self._last_emit[req.rid] = now
        if req.timeline is not None:
            req.timeline.mark_emit()
        q = self._streams.get(req.rid)
        if q is not None:
            q.put(("tok", tok))
        if req.eos_id is not None and tok == req.eos_id:
            self.scheduler.finish(req, "eos")
            self._end_stream(req, "eos")
        elif req.remaining <= 0:
            self.scheduler.finish(req, "length")
            self._end_stream(req, "length")

    def _end_stream(self, req: Request, reason: str) -> None:  # ptlint: holds=_lock
        q = self._streams.get(req.rid)
        if q is not None:
            q.put(("end", reason))
        self._last_emit.pop(req.rid, None)
        if req.timeline is not None:
            req.timeline.close(reason)
        if _obs.enabled():
            _obs.registry.counter("serving.requests",
                                  tags={"outcome": reason}).inc()

    def _expire_deadlines(self) -> None:  # ptlint: holds=_lock
        now = time.monotonic()
        for req in list(self._requests.values()):
            if req.deadline is not None and now > req.deadline and \
                    req.state not in ("finished", "cancelled"):
                self.scheduler.cancel(req, "deadline")
                self._end_stream(req, "deadline")
                if _obs.enabled():
                    _obs.registry.counter(
                        "serving.deadline_cancels").inc()

    # -------------------------------------------------- lifecycle/thread
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if not self.step():
                    self._wakeup.wait(timeout=0.01)
                    self._wakeup.clear()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serving-engine")
        self._thread.start()

    def shutdown(self, check_leaks: bool = True) -> None:
        """Stop the loop, cancel outstanding requests, and verify the
        block pool drained (every page free or prefix-cached)."""
        self._stop.set()
        self._wakeup.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        with self._lock:
            for req in list(self._requests.values()):
                if req.state not in ("finished", "cancelled"):
                    self.scheduler.cancel(req, "shutdown")
                    self._end_stream(req, "shutdown")
            if check_leaks:
                self.manager.assert_all_free()
