"""Replica: one :class:`ServingEngine` behind a liveness boundary.

A replica is the unit the cluster router schedules over: it owns one
engine (thread-hosted in-process; nothing here assumes shared memory
beyond the engine handle, so a subprocess host only needs to proxy
these same calls), exposes the engine's thread-safe
:meth:`~paddle_tpu.serving.engine.ServingEngine.stats` health snapshot,
and mediates EVERY engine step through the deterministic fault harness
(:mod:`paddle_tpu.distributed.resilience.faults`, site
``cluster.replica``).

Death is simulated, never real: the fault kinds ``kill`` / ``raise`` /
``drop`` at this site are intercepted *before* :func:`faults.apply`
would ``os._exit`` the whole test process — the replica instead calls
:meth:`ServingEngine.fail_all`, which atomically captures a replayable
descriptor of every in-flight request, ends their streams with
``replica_dead``, and releases all KV pages. The descriptors flow to
the router's ``on_death`` callback, which replays them on survivors.
Generic kinds (``delay``) still go through ``faults.apply``.

The ``hang`` kind is the control-plane flavour of death: the replica
goes silent (stops stepping, therefore stops beating its lease) but
stays ``alive`` — nobody reports the crash. Detection is the router's
job: the lease expires, ``missed()`` names the replica, the router
evicts it and calls :meth:`die` to drain-and-replay. This is the
failure mode the lease substrate exists for; ``kill`` deaths are
self-reporting by comparison.

When the router runs a :class:`ClusterControlPlane`, every productive
step also beats the replica's fenced lease — liveness is a byproduct of
doing work, exactly like the elastic DP trainers.
"""
from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence, Tuple

from ... import observability as _obs
from ...distributed.resilience import faults
from ..engine import (EngineStats, KVHandoff, RequestDescriptor,
                      ServingEngine)

__all__ = ["Replica", "FAULT_SITE"]

# the in-tree injection point for seeded replica kills:
#   PADDLE_TPU_FAULT_PLAN="cluster.replica:kill@7"
# fires on the 7th replica step ACROSS the cluster (the counter is per
# site, not per replica), so single-threaded round-robin stepping makes
# the victim deterministic.
FAULT_SITE = "cluster.replica"

_DEATH_KINDS = ("kill", "raise", "drop")


class Replica:
    """One engine + liveness; the router's scheduling unit."""

    def __init__(self, name: str, model, fault_site: str = FAULT_SITE,
                 **engine_knobs):
        self.name = str(name)
        self.fault_site = fault_site
        # access-log records and window snapshots carry the replica
        # name as their source (explicit name= knob wins)
        engine_knobs.setdefault("name", self.name)
        self.engine = ServingEngine(model, **engine_knobs)
        # router hook: called as on_death(replica, descriptors) from the
        # thread that observed the death, BEFORE step() returns
        self.on_death: Optional[
            Callable[["Replica", Tuple[RequestDescriptor, ...]],
                     None]] = None
        # set by ClusterRouter.add_replica when a control plane runs;
        # step() then beats the fenced lease on every productive pass
        self.control_plane = None
        self._lock = threading.Lock()
        self._alive = True  # guarded by: _lock
        self._hung = False  # guarded by: _lock

    # ------------------------------------------------------------ health
    @property
    def alive(self) -> bool:
        with self._lock:
            return self._alive

    @property
    def hung(self) -> bool:
        with self._lock:
            return self._hung

    def stats(self) -> EngineStats:
        """Thread-safe engine health snapshot (lock-held on the engine
        side, so the router never sees a torn read)."""
        return self.engine.stats()

    def warmup(self) -> None:
        """AOT warmup: pre-trace the active step program — the ragged
        mixed prefill+decode jit by default, or the legacy decode +
        prefill-chunk pair under ``PADDLE_TPU_SERVE_RAGGED=off`` — so
        this replica's first real token pays no cold compile."""
        self.engine.warmup()

    # ----------------------------------------------------- engine facade
    def submit(self, prompt: Sequence[int], **kw) -> int:
        return self.engine.submit(prompt, **kw)

    def events(self, rid: int):
        return self.engine.events(rid)

    def cancel(self, rid: int, reason: str = "cancelled") -> None:
        self.engine.cancel(rid, reason)

    def take_handoff(self) -> Optional[KVHandoff]:
        return self.engine.take_handoff()

    def adopt_handoff(self, payload: KVHandoff) -> Optional[int]:
        return self.engine.adopt_handoff(payload)

    # ----------------------------------------------------------- driving
    def step(self) -> bool:
        """One engine step, gated on the fault harness. Returns False
        when dead or idle. A death fault makes this replica drain
        in-flight work into descriptors and hand them to ``on_death``
        synchronously — by the time step() returns, the router has
        already replayed them."""
        with self._lock:
            if not self._alive or self._hung:
                return False
        act = faults.check(self.fault_site)
        if act is not None:
            if act.kind in _DEATH_KINDS:
                self.die()
                return False
            if act.kind == "hang":
                # go silent: stop stepping (and therefore beating), but
                # stay alive — the router must DISCOVER this through the
                # missed lease, there is no crash report
                with self._lock:
                    self._hung = True
                return False
            faults.apply(act)
        if self.control_plane is not None:
            self.control_plane.beat(self.name)
        return self.engine.step()

    def die(self) -> Tuple[RequestDescriptor, ...]:
        """Simulate a crash of this replica (idempotent)."""
        with self._lock:
            if not self._alive:
                return ()
            self._alive = False
        descs = self.engine.fail_all("replica_dead")
        if _obs.enabled():
            _obs.registry.counter("cluster.replica_deaths").inc()
        cb = self.on_death
        if cb is not None:
            cb(self, descs)
        return descs

    def retire(self) -> Tuple[RequestDescriptor, ...]:
        """Planned departure (autoscaler scale-in): the same atomic
        drain-and-replay path as :meth:`die` — in-flight work becomes
        descriptors the router replays token-exactly on survivors — but
        NOT counted as a death: the control plane published a clean
        leave, nothing crashed."""
        with self._lock:
            if not self._alive:
                return ()
            self._alive = False
        descs = self.engine.fail_all("replica_dead")
        cb = self.on_death
        if cb is not None:
            cb(self, descs)
        return descs

    def shutdown(self, check_leaks: bool = True) -> None:
        self.engine.shutdown(check_leaks=check_leaks)
