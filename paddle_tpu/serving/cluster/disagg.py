"""Disaggregated prefill/decode: split the replica set into a
prefill tier and a decode tier.

Prefill replicas run chunked prefill only (requests submitted with
``handoff=True`` stop after the prompt KV is resident and the first
token sampled); the pump then moves each finished prompt to a decode
replica as a :class:`KVHandoff` — the KV pages travel in the engines'
native pool layout through the shared page codec
(:mod:`paddle_tpu.serving.kv_store.codec`), which for
``kv_quant="int8"`` is the existing ``quantize_kv_pages``
``{"q8","s"}`` serialization, i.e. the quantized
path IS the wire format (4x smaller than fp32 pages). The decode
replica seats the payload straight into a RUNNING slot
(:meth:`ServingEngine.adopt_handoff`) and decodes from position
``len(prompt)`` — the prompt is never recomputed.

Why bother: prefill batches are compute-bound and bursty, decode
batches are memory-bound and steady; splitting the tiers isolates the
mixed-phase interference (a long prompt no longer stalls every decode
stream behind one chunk). On the decode tier each adopted handoff
seats as a plain RUNNING slot, i.e. a ``query_lens == 1`` row of the
ragged mixed-phase batch — the decode tier's ragged step is simply
all-decode, so adoption needs no special dispatch path.

The pump is crash-aware in both directions: a payload already exported
from a prefill replica survives that replica's death (it is host data),
and if every decode replica is dead the pump falls back to resubmitting
the request from scratch on any alive replica.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ... import observability as _obs
from ...observability.tracing import span
from ..engine import KVHandoff, RequestError
from ..kv_store import codec as kv_codec
from .replica import Replica

__all__ = ["DisaggPolicy"]


class DisaggPolicy:
    """Prefill/decode split + the handoff pump between the tiers."""

    def __init__(self, prefill: Sequence[Replica],
                 decode: Sequence[Replica]):
        if not prefill or not decode:
            raise ValueError("need >=1 prefill and >=1 decode replica")
        self.prefill = list(prefill)
        self.decode = list(decode)
        # payloads exported but not yet adopted (decode side busy);
        # entries are (source replica, payload)
        self._pending: List[Tuple[Replica, KVHandoff]] = []

    @classmethod
    def split(cls, replicas: Sequence[Replica],
              n_prefill: Optional[int] = None) -> "DisaggPolicy":
        """Default split: first ``n_prefill`` (half, rounded down, min
        1) replicas prefill, the rest decode."""
        if len(replicas) < 2:
            raise ValueError("disagg needs >= 2 replicas")
        n = n_prefill if n_prefill is not None else \
            max(1, len(replicas) // 2)
        if not 1 <= n < len(replicas):
            raise ValueError("n_prefill out of range")
        return cls(replicas[:n], replicas[n:])

    def _least_loaded_decode(self) -> Optional[Replica]:
        alive = [r for r in self.decode if r.alive]
        if not alive:
            return None
        st = {r: r.stats() for r in alive}
        return min(alive, key=lambda r: (st[r].active_slots +
                                         st[r].queue_depth))

    def pump(self, router) -> int:
        """Move every ready payload prefill -> decode; returns how many
        were adopted this pass. Payloads a busy decode tier rejects stay
        pending and are re-offered next pump."""
        for p in self.prefill:
            if not p.alive:
                continue
            while True:
                pay = p.take_handoff()
                if pay is None:
                    break
                self._pending.append((p, pay))
        moved = 0
        still: List[Tuple[Replica, KVHandoff]] = []
        for src, pay in self._pending:
            with span("cluster.handoff",
                      args={"blocks": pay.num_blocks,
                            "bytes":
                            kv_codec.pages_nbytes(pay.k_pages) +
                            kv_codec.pages_nbytes(pay.v_pages)}):
                target = self._least_loaded_decode()
                rid = target.adopt_handoff(pay) if target is not None \
                    else None
            if rid is not None:
                router.retarget_handoff(src, pay.src_rid, target, rid,
                                        inject=[pay.first_token])
                if _obs.enabled():
                    _obs.registry.counter("cluster.handoffs").inc()
                moved += 1
            elif target is None:
                # whole decode tier is dead: restart from the prompt on
                # any alive replica (the client saw zero tokens so a
                # fresh full stream is seamless)
                self._resubmit(router, src, pay)
            else:
                still.append((src, pay))
        self._pending = still
        return moved

    def _resubmit(self, router, src: Replica, pay: KVHandoff) -> None:
        for r in router.replicas:
            if not r.alive:
                continue
            try:
                rid = r.submit(list(pay.prompt),
                               max_new_tokens=pay.max_new_tokens,
                               temperature=pay.temperature,
                               top_p=pay.top_p, eos_id=pay.eos_id)
                router.retarget_handoff(src, pay.src_rid, r, rid,
                                        inject=[])
                return
            except RequestError:
                continue
        # nobody alive: let the stream's replay timeout fail it
