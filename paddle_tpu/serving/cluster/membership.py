"""ClusterControlPlane: serving replicas as lease-holding members.

The serving cluster is the first NEW consumer of the shared
control-plane substrate (``distributed/control_plane/``): instead of a
static replica list with a manual-only ``fail_all()`` crash path, each
replica holds a generation-fenced heartbeat lease (beaten from its own
``step()``), membership changes are committed epochs, and the router
discovers death through **missed beats** — the exact discipline the
elastic DP and PS tiers run across processes, here over an in-process
:class:`~paddle_tpu.distributed.control_plane.LocalStore` (any
TCPStore-surface store works; a multi-host pool would pass the job
store).

Epoch policy is the single-committer special case: the router is the
sole proposer and committer, so a join/leave/evict is
propose -> self-ack -> commit in one call. What stays shared with the
multi-process tiers is everything that matters for drills — key
layout, fencing, clean-leave vs missed-beat disambiguation, and the
``cp.lease`` / ``cp.epoch`` fault sites.

Env knobs: ``PADDLE_TPU_CLUSTER_BEAT`` (replica beat interval hint,
seconds; the router beats on every replica step, so this mostly feeds
derived deadlines) and ``PADDLE_TPU_CLUSTER_LEASE_TIMEOUT`` (seconds
without a beat before a replica is presumed dead — the failure
budget).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional

from ... import observability as _obs
from ...config import knobs
from ...distributed import control_plane as _cp
from ...distributed.control_plane import (EpochRegistry, LeaseTable,
                                          LocalStore)

__all__ = ["ClusterControlPlane"]


class ClusterControlPlane:
    """Lease + epoch view of one replica pool. Clock-injectable: the
    autoscale smoke and the control-plane tests drive it with
    ManualClock, zero sleeps."""

    def __init__(self, namespace: str = "cluster",
                 beat_interval: Optional[float] = None,
                 lease_timeout: Optional[float] = None,
                 clock: Callable[[], float] = time.time,
                 store=None):
        self.ns = str(namespace)
        self.beat_interval = beat_interval if beat_interval is not None \
            else knobs.get_float("PADDLE_TPU_CLUSTER_BEAT")
        self.lease_timeout = lease_timeout if lease_timeout is not None \
            else knobs.get_float("PADDLE_TPU_CLUSTER_LEASE_TIMEOUT")
        self.clock = clock
        self.store = store if store is not None else LocalStore()
        self.leases = LeaseTable(self.store, self.ns,
                                 self.lease_timeout, clock)
        self.epochs = EpochRegistry(self.store, self.ns, clock)
        self._lock = threading.Lock()
        self.epoch = 0                    # guarded by: _lock
        self._members: List[str] = []     # guarded by: _lock
        self._gens: dict = {}             # guarded by: _lock
        self._transitions: deque = deque(maxlen=64)  # guarded by: _lock
        _cp.register_plane(self)

    # ------------------------------------------------------------ state
    @property
    def members(self) -> List[str]:
        with self._lock:
            return list(self._members)

    def _commit(self, members: List[str], reason: str) -> int:
        """Single-committer epoch bump: propose, self-ack for every
        member (the router answers for its in-process replicas), and
        commit — the substrate's ``cp.epoch`` fault site fires inside
        ``commit``."""
        with self._lock:
            prev = self.epoch
        n = self.epochs.propose(sorted(members), reason,
                                proposer="router", prev=prev)
        for m in members:
            self.epochs.ack(n, m)
        self.epochs.commit(n)
        with self._lock:
            self.epoch = n
            self._members = sorted(members)
            self._transitions.append(
                {"t": self.clock(), "kind": "epoch", "epoch": n,
                 "members": sorted(members), "reason": reason})
        if _obs.enabled():
            _obs.flight_recorder.record(
                "cp.epoch_commit", ns=self.ns, epoch=n,
                members=sorted(members), reason=reason)
        return n

    # ---------------------------------------------------------- lifecycle
    def join(self, name: str) -> int:
        """Grant ``name`` a fenced lease and commit the grown epoch.
        Returns the lease generation the member's beats must carry."""
        gen = self.leases.grant(name)
        with self._lock:
            self._gens[name] = gen
            members = sorted(set(self._members) | {name})
        self._commit(members, f"join {name}")
        return gen

    def leave(self, name: str) -> None:
        """Clean departure: publish the leave marker (so the next scan
        never reports this as a missed beat), then commit the shrunk
        epoch."""
        self.leases.leave(name)
        with self._lock:
            members = sorted(m for m in self._members if m != name)
            self._gens.pop(name, None)
        self._commit(members, f"leave {name}")
        self.leases.forget(name)

    def beat(self, name: str) -> bool:
        """One fenced lease beat for ``name`` (False when fenced out or
        dropped at ``cp.lease``)."""
        with self._lock:
            gen = self._gens.get(name)
        return self.leases.beat(name, gen=gen)

    # ---------------------------------------------------------- liveness
    def fresh(self, name: str) -> bool:
        return self.leases.fresh(name)

    def generation(self, name: str) -> int:
        """Current lease generation of ``name`` (store-authoritative).
        Generations survive rejoin (``forget`` keeps the counter), so
        state fenced with an old generation — e.g. cluster KV index
        entries from a previous incarnation — verifiably goes stale."""
        return self.leases.generation(name)

    def missed(self) -> List[str]:
        """Members whose lease expired WITHOUT a clean-leave marker —
        the router's eviction candidates."""
        return self.leases.missed(self.members)

    def evict(self, name: str, reason: str = "missed_beat") -> None:
        """Remove a presumed-dead member: epoch shrinks, lease keys are
        reaped. The caller (router) owns draining the replica itself."""
        with self._lock:
            if name not in self._members:
                return
            members = sorted(m for m in self._members if m != name)
            self._gens.pop(name, None)
        # only genuine lease expiries count; self-reported deaths
        # arrive here with reason="died"
        if _obs.enabled() and reason == "missed_beat":
            _obs.registry.counter("cp.lease_expiries").inc()
        self._commit(members, f"evict {name}: {reason}")
        self.leases.forget(name)

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """The ``control_plane.json`` bundle payload for this pool:
        current epoch, members, per-member lease freshness, and the
        recent transition ring."""
        with self._lock:
            members = list(self._members)
            epoch = self.epoch
            transitions = list(self._transitions)
        now = self.clock()
        leases = {}
        for m in members:
            b = self.leases.read(m)
            leases[m] = {
                "beat": b,
                "fresh": b is not None and
                now - float(b.get("t", 0.0)) <= self.lease_timeout,
                "generation": self.leases.generation(m),
            }
        return {"kind": "cluster_control_plane", "ns": self.ns,
                "epoch": epoch, "members": members,
                "lease_timeout": self.lease_timeout, "now": now,
                "leases": leases, "transitions": transitions}
