"""Multi-replica serving cluster: prefix-affinity router, disaggregated
prefill/decode, drain-and-replay resilience, lease-based liveness, and
SLO-driven autoscaling.

Quick start::

    import paddle_tpu as pt
    from paddle_tpu.serving.cluster import Replica, ClusterRouter

    reps = [Replica("r%d" % i, model, max_slots=4) for i in range(2)]
    for r in reps:
        r.warmup()                       # pre-trace both jits
    router = ClusterRouter(reps)
    crid = router.submit(prompt_ids, max_new_tokens=32)
    while router.step():                 # or router.start() for threads
        pass
    tokens = router.result(crid)
    router.shutdown()

Disaggregated prefill/decode::

    from paddle_tpu.serving.cluster import DisaggPolicy
    router = ClusterRouter(reps, disagg=DisaggPolicy.split(reps))

Control plane + autoscaling (PR 18)::

    from paddle_tpu.serving.cluster import (Autoscaler, AutoscaleConfig,
                                            ClusterControlPlane, Replica,
                                            ClusterRouter)

    cp = ClusterControlPlane()           # leases + epochs (LocalStore)
    router = ClusterRouter(reps, control_plane=cp)
    scaler = Autoscaler(router, spawn=lambda name: Replica(name, model),
                        config=AutoscaleConfig(min_replicas=1,
                                               max_replicas=4))
    while router.step():                 # router evicts missed leases
        scaler.tick()                    # scaler grows/shrinks the pool

Replicas beat generation-fenced leases from their own ``step()``; the
router discovers silent failures (the ``hang`` fault kind) through
missed beats and drains them via the same token-exact replay path used
for crashes. The substrate is shared with the elastic-DP and PS tiers
(``paddle_tpu.distributed.control_plane``).

``PADDLE_TPU_CLUSTER_REPLICAS`` / ``PADDLE_TPU_CLUSTER_MAX_QUEUE``
size the default topology in ``bench.py --cluster`` and
``tools/serve_smoke.py --cluster``; ``PADDLE_TPU_CLUSTER_BEAT`` /
``PADDLE_TPU_CLUSTER_LEASE_TIMEOUT`` shape the liveness budget and
``PADDLE_TPU_AUTOSCALE_*`` the scaling policy. The seeded kill used by
the resilience tests is ``PADDLE_TPU_FAULT_PLAN="cluster.replica:kill@N"``
(``hang@N`` for the silent flavour).
"""
from .autoscaler import AutoscaleConfig, Autoscaler  # noqa: F401
from .disagg import DisaggPolicy  # noqa: F401
from .membership import ClusterControlPlane  # noqa: F401
from .replica import FAULT_SITE, Replica  # noqa: F401
from .router import ClusterRouter, Overloaded  # noqa: F401

__all__ = ["Replica", "ClusterRouter", "Overloaded", "DisaggPolicy",
           "FAULT_SITE", "ClusterControlPlane", "Autoscaler",
           "AutoscaleConfig"]
