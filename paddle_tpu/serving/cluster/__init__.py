"""Multi-replica serving cluster: prefix-affinity router, disaggregated
prefill/decode, drain-and-replay resilience.

Quick start::

    import paddle_tpu as pt
    from paddle_tpu.serving.cluster import Replica, ClusterRouter

    reps = [Replica("r%d" % i, model, max_slots=4) for i in range(2)]
    for r in reps:
        r.warmup()                       # pre-trace both jits
    router = ClusterRouter(reps)
    crid = router.submit(prompt_ids, max_new_tokens=32)
    while router.step():                 # or router.start() for threads
        pass
    tokens = router.result(crid)
    router.shutdown()

Disaggregated prefill/decode::

    from paddle_tpu.serving.cluster import DisaggPolicy
    router = ClusterRouter(reps, disagg=DisaggPolicy.split(reps))

``PADDLE_TPU_CLUSTER_REPLICAS`` / ``PADDLE_TPU_CLUSTER_MAX_QUEUE``
size the default topology in ``bench.py --cluster`` and
``tools/serve_smoke.py --cluster``; the seeded kill used by the
resilience tests is ``PADDLE_TPU_FAULT_PLAN="cluster.replica:kill@N"``.
"""
from .disagg import DisaggPolicy  # noqa: F401
from .replica import FAULT_SITE, Replica  # noqa: F401
from .router import ClusterRouter, Overloaded  # noqa: F401

__all__ = ["Replica", "ClusterRouter", "Overloaded", "DisaggPolicy",
           "FAULT_SITE"]
