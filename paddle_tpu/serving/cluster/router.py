"""ClusterRouter: the front-end over N serving replicas.

Submit/stream/cancel parity with :class:`ServingEngine`, plus the three
cluster-only behaviours:

* **Prefix-affinity routing** — the router keys each prompt by the same
  rolling block-hash chain the engines' prefix caches use
  (:func:`block_manager.hash_block_tokens` over whole
  ``block_size``-token blocks) and remembers which replica last served
  each chain hash. A new prompt routes to the replica holding its
  deepest known prefix — that replica's paged prefix cache then skips
  recomputing those blocks (``serving.prefix_hit_tokens`` proves the
  hit). Least-loaded fallback otherwise.

* **Admission control / load shedding** — before accepting, the router
  checks the candidate's health snapshot: per-replica queue depth below
  ``max_queue`` AND enough free blocks above the engine's free-list
  watermark for the prompt (+1 decode block). When no alive replica
  admits, submit raises the typed :class:`Overloaded` immediately —
  clients get a signal to back off, never a hang or an unbounded queue.

* **Drain-and-replay resilience** — replica death hands the router the
  dead engine's in-flight :class:`RequestDescriptor`s. Greedy decoding
  is deterministic, so replaying ``prompt + generated`` with
  ``remaining`` new tokens on a survivor continues each stream exactly
  where it stopped. Client streams are *segmented*: every emitted token
  survives in the dead engine's queue, so the client-facing generator
  drains segment N fully (tokens, then the ``replica_dead`` marker)
  before crossing into the replayed segment N+1 — no token is lost or
  duplicated. Replays bypass admission control on purpose: shedding is
  for new work, not for work the cluster already accepted.

* **Control-plane liveness (PR 18)** — pass a
  :class:`ClusterControlPlane` and every replica becomes a
  lease-holding member: it beats a generation-fenced lease from its
  own ``step()``, joins/leaves through committed epochs, and the
  router's per-step ``_cp_scan`` EVICTS members whose lease expired
  without a clean-leave marker (then drains them through the same
  replay path). That turns silent failures (the ``hang`` fault kind —
  a replica that stops working without crashing) into bounded-time
  recoveries; ``fail_all``-style crashes stay self-reporting. The pool
  is also elastic: :meth:`add_replica` (warmup → lease grant → epoch
  commit → routable) and :meth:`remove_replica` (clean leave →
  drain-and-replay → gone) are what the
  :class:`~paddle_tpu.serving.cluster.autoscaler.Autoscaler` drives.

Driving: ``router.step()`` runs one synchronous round-robin pass over
all replicas (deterministic — this is what tests and the fault plans
use, since the ``cluster.replica`` fault counter is per-site);
``router.start()`` instead hosts one stepping thread per replica (plus
a disagg pump thread) for throughput runs.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ... import observability as _obs
from ...config import knobs
from ...observability.tracing import span
from ..block_manager import hash_block_tokens
from ..engine import RequestDescriptor, RequestError
from .replica import Replica

__all__ = ["ClusterRouter", "Overloaded"]


class Overloaded(RequestError):
    """Typed load-shed result: every alive replica is beyond its queue
    bound or free-list watermark. Back off and resubmit."""

    def __init__(self, detail: str = ""):
        super().__init__("overloaded")
        self.detail = detail


class _ClientReq:
    """Router-side record of one client request. ``segments`` is the
    ordered list of (replica, engine_rid, inject_tokens) hops the
    request has made — one entry at submit, +1 per replay or disagg
    handoff. All fields are guarded by the router condition lock."""

    __slots__ = ("crid", "segments", "failed")

    def __init__(self, crid: int,
                 segments: List[Tuple[Replica, int, List[int]]]):
        self.crid = crid
        self.segments = segments
        self.failed = False


class ClusterRouter:
    # stream() waits at most this long for a dead/handoff segment to be
    # retargeted before declaring the request failed — the "never a
    # hang" contract extends to replays, not just admission
    REPLAY_TIMEOUT_S = 60.0

    def __init__(self, replicas: Sequence[Replica],
                 max_queue: Optional[int] = None,
                 disagg: Optional[object] = None,
                 control_plane: Optional[object] = None,
                 kv_store: Optional[object] = None):
        if not replicas:
            raise ValueError("need at least one replica")
        self.replicas = list(replicas)
        self.max_queue = max_queue if max_queue is not None else \
            knobs.get_int("PADDLE_TPU_CLUSTER_MAX_QUEUE")
        self.disagg = disagg            # DisaggPolicy or None
        self.control_plane = control_plane  # ClusterControlPlane or None
        # cluster KV tier (ClusterKVStore or None): pass one explicitly,
        # or set PADDLE_TPU_KV_TIER=host and the router builds it on the
        # control plane's store. Default off — zero behavior change.
        if kv_store is None and \
                knobs.is_set("PADDLE_TPU_KV_TIER") and \
                knobs.get_str("PADDLE_TPU_KV_TIER").lower() == "host":
            from ..kv_store import ClusterKVStore
            kv_store = ClusterKVStore(control_plane=control_plane)
        self.kv_store = kv_store
        self.autoscaler = None          # set by Autoscaler.__init__
        self.block_size = \
            self.replicas[0].engine.manager.block_size
        for r in self.replicas:
            if r.engine.manager.block_size != self.block_size:
                raise ValueError("replicas disagree on block_size")
            r.on_death = self._on_death
            if control_plane is not None:
                r.control_plane = control_plane
                control_plane.join(r.name)
        if self.kv_store is not None:
            # after join: replica registrations fence with the lease
            # generation they hold NOW
            for r in self.replicas:
                self.kv_store.attach(r)
        self._cond = threading.Condition()
        self._crid = 0  # guarded by: _cond
        self._recs: Dict[int, _ClientReq] = {}  # guarded by: _cond
        # (replica name, engine rid) -> crid, for the CURRENT segment
        self._by_engine: Dict[Tuple[str, int], int] = {}  # guarded by: _cond
        # prefix chain hash -> replica that last served it
        self._affinity: Dict[int, Replica] = {}  # guarded by: _cond
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        # request-scoped observability (PR 16): the router's own access
        # log records sheds (arrivals that never reach an engine); the
        # SLO engine merges it with every replica's windows
        self._log = None
        self._slo = None

    # --------------------------------------------- request observability
    @property
    def request_log(self):
        """Router-side access log: records admission sheds (each shed
        counts as one arrival + one shed in the router's windows, so
        the merged cluster shed rate is shed / total arrivals)."""
        if self._log is None:
            from ...observability.request_log import RequestLog
            self._log = RequestLog(source="router")
        return self._log

    @property
    def slo(self):
        """Cluster SLO engine: evaluates the default serving
        objectives over the router's windows MERGED with every
        replica's — per-replica state stays local, aggregation happens
        at evaluation time (windows.merge_states)."""
        if self._slo is None:
            from ...observability.slo import SLOEngine
            self._slo = SLOEngine(
                [self.request_log.windows] +
                [r.engine.windows for r in self.replicas])
        return self._slo

    def stats(self) -> dict:
        """Cluster health snapshot: per-replica liveness, queue depth,
        slot utilization, and each replica's rolling-window state
        (utilization / queue-depth EWMAs, prefix-hit and latency
        windows). JSON-able — this is what monitors poll."""
        per: Dict[str, dict] = {}
        for r in self.replicas:
            entry: dict = {"alive": r.alive}
            if r.alive:
                st = r.stats()
                entry.update(
                    queue_depth=st.queue_depth,
                    active_slots=st.active_slots,
                    max_slots=st.max_slots,
                    running=st.running, prefilling=st.prefilling,
                    free_blocks=st.free_blocks,
                    total_blocks=st.total_blocks)
            entry["windows"] = r.engine.windows.snapshot()
            per[r.name] = entry
        return {"alive": self.num_alive(),
                "max_queue": self.max_queue,
                "router_windows": self.request_log.windows.snapshot(),
                "replicas": per}

    def ops_snapshot(self) -> dict:
        """The dashboard/bundle payload: :meth:`stats` plus the SLO
        report, the autoscaler signal feed, merged latency
        attribution, and the cluster-wide access-log tail. Same shape
        as :meth:`ServingEngine.ops_snapshot` (more replicas)."""
        from ...observability.request_log import attribution_of

        st = self.stats()
        all_windows = [self.request_log.windows] + \
            [r.engine.windows for r in self.replicas]
        tails = self.request_log.tail(50)
        for r in self.replicas:
            tails.extend(r.engine.request_log.tail(50))
        tails.sort(key=lambda rec: rec.get("ts", 0.0))
        return {"kind": "ops_snapshot", "source": "cluster",
                "ts": time.time(),
                "replicas": st["replicas"],
                "router": {"windows": st["router_windows"],
                           "max_queue": self.max_queue},
                "slo": self.slo.evaluate(),
                "signals": self.slo.load_signals(),
                "control_plane": (self.control_plane.snapshot()
                                  if self.control_plane is not None
                                  else None),
                "kv": (self.kv_store.snapshot()
                       if self.kv_store is not None else None),
                "scale": (self.autoscaler.snapshot()
                          if self.autoscaler is not None else None),
                "attribution": attribution_of(all_windows),
                "requests": tails[-50:]}

    def dump_ops_snapshot(self, path: str) -> dict:
        from ...observability.request_log import write_snapshot

        snap = self.ops_snapshot()
        write_snapshot(snap, path)
        return snap

    # ---------------------------------------------------------- routing
    def _chain(self, prompt: Sequence[int]) -> List[int]:
        bs = self.block_size
        h: Optional[int] = None
        out: List[int] = []
        for i in range(len(prompt) // bs):
            h = hash_block_tokens(h, prompt[i * bs:(i + 1) * bs])
            out.append(h)
        return out

    def _submit_pool(self) -> List[Replica]:
        if self.disagg is not None:
            pool = self.disagg.prefill
        else:
            with self._cond:
                pool = list(self.replicas)
        return [r for r in pool if r.alive]

    def _replay_pool(self) -> List[Replica]:
        if self.disagg is not None:
            dec = [r for r in self.disagg.decode if r.alive]
            if dec:
                return dec
        with self._cond:
            pool = list(self.replicas)
        return [r for r in pool if r.alive]

    def _route(self, prompt: List[int]) -> Tuple[Replica, str]:
        """Pick a replica for a NEW prompt or raise :class:`Overloaded`.
        Order: deepest-affinity replica first, then alive replicas by
        load; the first one passing admission wins."""
        alive = self._submit_pool()
        if not alive:
            raise RequestError("no_replicas")
        chain = self._chain(prompt)
        aff: Optional[Replica] = None
        with self._cond:
            for h in reversed(chain):
                r = self._affinity.get(h)
                if r is not None and r.alive and r in alive:
                    aff = r
                    break
        st = {r: r.stats() for r in alive}
        order = sorted(alive, key=lambda r: (st[r].queue_depth +
                                             st[r].active_slots))
        if aff is not None:
            order = [aff] + [r for r in order if r is not aff]
        need = -(-(len(prompt) + 1) // self.block_size)
        for r in order:
            if st[r].queue_depth < self.max_queue and \
                    st[r].can_admit(need):
                route = "affinity" if r is aff else "least_loaded"
                with self._cond:
                    for h in chain:
                        self._affinity[h] = r
                if _obs.enabled():
                    _obs.registry.counter(
                        "cluster.submitted", tags={"route": route}).inc()
                    if route == "affinity":
                        _obs.registry.counter(
                            "cluster.affinity_hits").inc()
                return r, route
        if _obs.enabled():
            _obs.registry.counter("cluster.shed").inc()
            self.request_log.shed(prompt_tokens=len(prompt))
        raise Overloaded(
            "all %d alive replicas at queue/watermark limits"
            % len(alive))

    # ----------------------------------------------------------- intake
    def submit(self, prompt: Sequence[int], max_new_tokens: int = 16,
               temperature: float = 0.0, top_p: float = 1.0,
               eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None) -> int:
        """Route and queue one request; returns a cluster-level rid.
        Raises :class:`Overloaded` when admission control sheds it."""
        prompt = [int(t) for t in prompt]
        handoff = self.disagg is not None
        with span("cluster.route"):
            for _ in range(len(self.replicas) + 1):
                rep, _route = self._route(prompt)
                if self.kv_store is not None:
                    # pull the deepest cluster-cached prefix into the
                    # target BEFORE it queues: admission then sees the
                    # pages locally resident (miss/stale/CRC failure all
                    # degrade to recompute inside prefetch)
                    self.kv_store.prefetch(rep, prompt)
                try:
                    rid = rep.submit(
                        prompt, max_new_tokens=max_new_tokens,
                        temperature=temperature, top_p=top_p,
                        eos_id=eos_id, deadline_s=deadline_s,
                        handoff=handoff)
                    break
                except RequestError:
                    continue            # died between stats and submit
            else:
                raise RequestError("no_replicas")
        with self._cond:
            self._crid += 1
            crid = self._crid
            self._recs[crid] = _ClientReq(crid, [(rep, rid, [])])
            self._by_engine[(rep.name, rid)] = crid
        return crid

    def cancel(self, crid: int, reason: str = "cancelled") -> None:
        with self._cond:
            rec = self._recs.get(crid)
            if rec is None:
                return
            rep, rid, _ = rec.segments[-1]
        rep.cancel(rid, reason)

    # --------------------------------------------------------- streaming
    def stream(self, crid: int) -> Iterator[int]:
        """Per-token iterator with :class:`ServingEngine.stream` parity;
        replays and disagg handoffs are invisible joins."""
        for kind, val in self._events(crid):
            if kind == "tok":
                yield val
            elif val in ("eos", "length"):
                return
            else:
                raise RequestError(val)

    def result(self, crid: int) -> List[int]:
        return list(self.stream(crid))

    def _events(self, crid: int) -> Iterator[Tuple[str, object]]:
        with self._cond:
            rec = self._recs[crid]
        i = 0
        while True:
            with self._cond:
                deadline = time.monotonic() + self.REPLAY_TIMEOUT_S
                while len(rec.segments) <= i and not rec.failed:
                    left = deadline - time.monotonic()
                    if left <= 0 or not self._cond.wait(timeout=left):
                        rec.failed = True
                if rec.failed and len(rec.segments) <= i:
                    yield ("end", "replica_dead")
                    return
                rep, rid, inject = rec.segments[i]
            for t in inject:
                yield ("tok", t)
            ended: Optional[str] = None
            for kind, val in rep.events(rid):
                if kind == "tok":
                    yield ("tok", val)
                else:
                    ended = str(val)
            if ended in ("replica_dead", "handoff"):
                i += 1                   # wait for the next segment
                continue
            yield ("end", ended)
            return

    # --------------------------------------------------------- resilience
    def _on_death(self, replica: Replica,
                  descs: Tuple[RequestDescriptor, ...]) -> None:
        """Replica death callback: replay every drained descriptor on a
        survivor. Runs on the thread that observed the death, before its
        step() returns."""
        for d in descs:
            with self._cond:
                crid = self._by_engine.pop((replica.name, d.rid), None)
            if crid is None:
                continue                 # not one of ours (warmup etc.)
            self._replay(crid, d)
        # self-reporting deaths (kill/raise/drop) shrink the epoch here;
        # lease-discovered ones were already evicted by _cp_scan and
        # clean leaves by remove_replica — evict() is idempotent
        if self.control_plane is not None:
            self.control_plane.evict(replica.name, reason="died")
        if self.kv_store is not None:
            # optional hygiene: the dead replica's index entries already
            # fail lease/generation validation
            self.kv_store.on_replica_dead(replica.name)

    def _replay(self, crid: int, d: RequestDescriptor) -> None:
        with span("cluster.replay"):
            survivors = self._replay_pool()
            rep: Optional[Replica] = None
            rid: Optional[int] = None
            if survivors:
                st = {r: r.stats() for r in survivors}
                order = sorted(survivors,
                               key=lambda r: (st[r].queue_depth +
                                              st[r].active_slots))
                prompt = list(d.prompt) + list(d.generated)
                deadline_s = None if d.deadline is None else \
                    max(0.0, d.deadline - time.monotonic())
                for r in order:          # no shedding for replays
                    try:
                        rid = r.submit(prompt,
                                       max_new_tokens=d.remaining,
                                       temperature=d.temperature,
                                       top_p=d.top_p, eos_id=d.eos_id,
                                       deadline_s=deadline_s)
                        rep = r
                        break
                    except RequestError:
                        continue
            with self._cond:
                rec = self._recs.get(crid)
                if rec is None:
                    if rep is not None:
                        rep.cancel(rid)
                    return
                if rep is None:
                    rec.failed = True
                else:
                    rec.segments.append((rep, rid, []))
                    self._by_engine[(rep.name, rid)] = crid
                    if _obs.enabled():
                        _obs.registry.counter("cluster.replays").inc()
                self._cond.notify_all()

    def retarget_handoff(self, src: Replica, src_rid: int,
                         target: Replica, rid: int,
                         inject: List[int]) -> None:
        """Disagg pump callback: the request that prefilled as
        ``src_rid`` on ``src`` now decodes as ``rid`` on ``target``;
        ``inject`` carries the prefill-sampled first token the decode
        engine will not re-emit."""
        with self._cond:
            crid = self._by_engine.pop((src.name, src_rid), None)
            if crid is None:
                return
            rec = self._recs.get(crid)
            if rec is None:
                return
            rec.segments.append((target, rid, list(inject)))
            self._by_engine[(target.name, rid)] = crid
            self._cond.notify_all()

    # --------------------------------------------------------- elasticity
    def add_replica(self, replica: Replica, warm: bool = True) -> None:
        """Grow the pool by one replica: warm it up FIRST (pre-trace the
        step programs so its first routed token pays zero cold
        compiles), grant its lease + commit the grown epoch on the
        control plane, then make it routable. Safe in both driving
        modes — threaded mode gets a stepping thread on the spot."""
        if replica.engine.manager.block_size != self.block_size:
            raise ValueError("replicas disagree on block_size")
        if warm:
            replica.warmup()
        replica.on_death = self._on_death
        if self.control_plane is not None:
            replica.control_plane = self.control_plane
            self.control_plane.join(replica.name)
        if self.kv_store is not None:
            self.kv_store.attach(replica)
        with self._cond:
            self.replicas.append(replica)
        if self._slo is not None:
            self._slo.add_windows(replica.engine.windows)
        if self._threads and not self._stop.is_set():
            self._spawn_rep_thread(replica)
        if _obs.enabled():
            _obs.flight_recorder.record("cluster.replica_join",
                                        replica=replica.name,
                                        warm=bool(warm))

    def remove_replica(self, replica: Replica,
                       drain: bool = True) -> None:
        """Shrink the pool by one replica, cleanly: publish the
        clean-leave marker + commit the shrunk epoch FIRST (so no
        concurrent scan mistakes the drain for a missed beat), then
        drain — in-flight requests become descriptors the usual
        ``on_death`` path replays token-exactly on survivors. Replays
        bypass admission control: this is work the cluster already
        accepted."""
        if self.control_plane is not None:
            self.control_plane.leave(replica.name)
        if self.kv_store is not None:
            self.kv_store.detach(replica)
        if drain:
            replica.retire()
        with self._cond:
            if replica in self.replicas:
                self.replicas.remove(replica)
            stale = [h for h, r in self._affinity.items()
                     if r is replica]
            for h in stale:
                del self._affinity[h]
        # fail_all released every page, so the leak check must pass
        replica.shutdown(check_leaks=drain)
        if _obs.enabled():
            _obs.flight_recorder.record("cluster.replica_leave",
                                        replica=replica.name,
                                        drained=bool(drain))

    def _cp_scan(self) -> None:
        """Evict members whose lease expired without a clean leave —
        the discovery path for SILENT failures (``hang``): the epoch
        shrinks, then :meth:`Replica.die` drains the zombie so its
        in-flight work replays on survivors."""
        if self.control_plane is None:
            return
        for name in self.control_plane.missed():
            rep = next((r for r in self.replicas if r.name == name),
                       None)
            self.control_plane.evict(name, "missed_beat")
            if rep is not None and rep.alive:
                rep.die()

    # ----------------------------------------------------------- driving
    def num_alive(self) -> int:
        return sum(1 for r in self.replicas if r.alive)

    def step(self) -> bool:
        """One synchronous round: scan the control plane for expired
        leases, step every alive replica round-robin, pump disagg
        handoffs, publish cluster gauges. Deterministic — the
        test/fault-plan driver."""
        t0 = time.monotonic()
        self._cp_scan()
        did = False
        for rep in list(self.replicas):
            if rep.alive:
                did = rep.step() or did
        if self.disagg is not None:
            did = (self.disagg.pump(self) > 0) or did
        if self.kv_store is not None:
            did = (self.kv_store.pump() > 0) or did
        if _obs.enabled():
            _obs.registry.gauge("cluster.replicas_alive").set(
                self.num_alive())
            _obs.registry.gauge("cluster.queue_depth").set(
                sum(r.stats().queue_depth
                    for r in self.replicas if r.alive))
            _obs.registry.histogram("cluster.step_time").observe(
                time.monotonic() - t0)
        return did

    def _spawn_rep_thread(self, rep: Replica) -> None:
        def rep_loop() -> None:
            while not self._stop.is_set():
                if not (rep.alive and rep.step()):
                    time.sleep(0.001)

        t = threading.Thread(target=rep_loop, daemon=True,
                             name="cluster-%s" % rep.name)
        t.start()
        self._threads.append(t)

    def start(self) -> None:
        """Threaded mode: one stepping thread per replica (XLA releases
        the GIL during compute, so replicas overlap on CPU too) plus a
        handoff pump thread when disaggregated."""
        if self._threads:
            return
        self._stop.clear()
        for rep in self.replicas:
            self._spawn_rep_thread(rep)
        if self.disagg is not None:
            def pump_loop() -> None:
                while not self._stop.is_set():
                    if self.disagg.pump(self) == 0:
                        time.sleep(0.001)

            t = threading.Thread(target=pump_loop, daemon=True,
                                 name="cluster-disagg-pump")
            t.start()
            self._threads.append(t)
        if self.kv_store is not None:
            self.kv_store.start()

    def shutdown(self, check_leaks: bool = True) -> None:
        self._stop.set()
        if self.kv_store is not None:
            self.kv_store.stop()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []
        with self._cond:
            for rec in self._recs.values():
                rec.failed = True        # unblock any waiting streams
            self._cond.notify_all()
        for rep in self.replicas:
            rep.shutdown(check_leaks=check_leaks and rep.alive)
