"""SLO-driven elastic replica pool: the Autoscaler.

PR 16 built the sensory organ (:meth:`SLOEngine.load_signals`); this is
the motor neuron. Each :meth:`Autoscaler.tick` reads one signal frame
from the router's SLO engine plus the pool's queue/slot state and
drives the pool between ``min_replicas`` and ``max_replicas``:

* **scale-out** when pressure is SUSTAINED (``up_ticks`` consecutive
  ticks) — pressure being the slow-horizon burn hint
  (``want_scale_up``), a nonzero admission shed rate, or aggregate
  queue depth at/over ``queue_hwm`` per alive replica, gated on
  CURRENT demand (work queued, slots active, or fresh sheds): stale
  burn over an idle pool never grows it. The spawned
  replica ``warmup()``s the ragged+prefill jits BEFORE joining the
  pool, so its first real token pays zero cold compiles.
* **scale-in** when the pool is SUSTAINED idle (``idle_ticks``
  consecutive ticks with empty queues, no active slots, and no sheds)
  or the SLO engine's ``want_scale_down`` hint fires (sustained all-OK
  + low utilization EWMA). The victim drains before leaving: clean
  leave marker on the control plane, in-flight descriptors replayed
  onto survivors token-exactly (greedy decoding makes the continuation
  exact — the same replay path replica death uses).

Every scale event sits behind a ``cooldown_ticks`` refractory window so
one burst cannot slam the pool back and forth.

Env knobs (ctor args win): ``PADDLE_TPU_AUTOSCALE_MIN`` / ``_MAX`` /
``_UP_TICKS`` / ``_IDLE_TICKS`` / ``_COOLDOWN_TICKS`` / ``_QUEUE_HWM``
/ ``_SHED_THRESHOLD``.
"""
from __future__ import annotations

import time
from typing import Callable, Optional

from ... import observability as _obs
from ...config import knobs
from .replica import Replica

__all__ = ["Autoscaler", "AutoscaleConfig"]


class AutoscaleConfig:
    """Scaling policy knobs (``PADDLE_TPU_AUTOSCALE_*``)."""

    def __init__(self, min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 up_ticks: Optional[int] = None,
                 idle_ticks: Optional[int] = None,
                 cooldown_ticks: Optional[int] = None,
                 queue_hwm: Optional[int] = None,
                 shed_threshold: Optional[float] = None):
        self.min_replicas = min_replicas if min_replicas is not None \
            else knobs.get_int("PADDLE_TPU_AUTOSCALE_MIN")
        self.max_replicas = max_replicas if max_replicas is not None \
            else knobs.get_int("PADDLE_TPU_AUTOSCALE_MAX")
        # consecutive pressured ticks before scale-out
        self.up_ticks = up_ticks if up_ticks is not None \
            else knobs.get_int("PADDLE_TPU_AUTOSCALE_UP_TICKS")
        # consecutive idle ticks before scale-in
        self.idle_ticks = idle_ticks if idle_ticks is not None \
            else knobs.get_int("PADDLE_TPU_AUTOSCALE_IDLE_TICKS")
        # refractory ticks after ANY scale event
        self.cooldown_ticks = cooldown_ticks \
            if cooldown_ticks is not None \
            else knobs.get_int("PADDLE_TPU_AUTOSCALE_COOLDOWN_TICKS")
        # aggregate queue depth per alive replica that counts as
        # pressure even before sheds/burn appear
        self.queue_hwm = queue_hwm if queue_hwm is not None \
            else knobs.get_int("PADDLE_TPU_AUTOSCALE_QUEUE_HWM")
        # fast-horizon shed rate above this is pressure
        self.shed_threshold = shed_threshold \
            if shed_threshold is not None \
            else knobs.get_float("PADDLE_TPU_AUTOSCALE_SHED_THRESHOLD")
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas < min_replicas")


class Autoscaler:
    """One scaling loop over a :class:`ClusterRouter`. ``spawn(name)``
    is the replica factory (model + engine knobs live with the caller);
    the Autoscaler owns WHEN, the router owns HOW (warmup, control-plane
    join, drain-before-leave)."""

    def __init__(self, router, spawn: Callable[[str], Replica],
                 config: Optional[AutoscaleConfig] = None,
                 clock: Callable[[], float] = time.time):
        self.router = router
        self.spawn = spawn
        self.cfg = config or AutoscaleConfig()
        self.clock = clock
        self.last_event: Optional[dict] = None
        self._up = 0
        self._idle = 0
        self._cooldown = 0
        self._ticks = 0
        self._next_index = len(router.replicas)
        router.autoscaler = self

    # ------------------------------------------------------------- state
    def _pool(self):
        return [r for r in self.router.replicas if r.alive]

    def _fresh_name(self) -> str:
        taken = {r.name for r in self.router.replicas}
        while True:
            name = "r%d" % self._next_index
            self._next_index += 1
            if name not in taken:
                return name

    # -------------------------------------------------------------- tick
    def tick(self) -> Optional[dict]:
        """One control decision. Returns the scale event fired this
        tick (None for the common no-op tick)."""
        self._ticks += 1
        # cooldown_ticks=N blocks exactly the N ticks after an event
        # (streak counters keep accumulating underneath)
        in_cooldown = self._cooldown > 0
        if in_cooldown:
            self._cooldown -= 1
        sig = self.router.slo.load_signals()
        pool = self._pool()
        if not pool:
            return None
        stats = [r.stats() for r in pool]
        queue = sum(s.queue_depth for s in stats)
        active = sum(s.active_slots for s in stats)

        # burn/shed hints count as pressure only while there is CURRENT
        # demand: historical burn over an empty idle pool cannot be
        # fixed by adding replicas (with a full-span slow horizon it
        # never ages out, and hint-driven scale-out would flap forever
        # against idle scale-in)
        demand = queue > 0 or active > 0 \
            or sig.get("shed_rate_fast", 0.0) > 0.0
        pressure = demand and (
            sig.get("want_scale_up", 0.0) >= 1.0
            or sig.get("shed_rate_fast", 0.0) > self.cfg.shed_threshold
            or queue >= self.cfg.queue_hwm * len(pool))
        idle = queue == 0 and active == 0 and \
            sig.get("shed_rate_fast", 0.0) == 0.0
        want_down = sig.get("want_scale_down", 0.0) >= 1.0

        self._up = self._up + 1 if pressure else 0
        self._idle = self._idle + 1 if idle else 0

        if in_cooldown:
            return None
        if self._up >= self.cfg.up_ticks and \
                len(pool) < self.cfg.max_replicas:
            return self._scale_out(sig, queue)
        if len(pool) > self.cfg.min_replicas and \
                (self._idle >= self.cfg.idle_ticks
                 or (want_down and idle)):
            return self._scale_in(sig)
        return None

    # ------------------------------------------------------------ actions
    def _scale_out(self, sig: dict, queue: int) -> dict:
        name = self._fresh_name()
        replica = self.spawn(name)
        # warm=True: the joining replica pre-traces the ragged+prefill
        # jits before it is routable — zero cold compiles under traffic
        self.router.add_replica(replica, warm=True)
        event = {"kind": "scale_up", "replica": name,
                 "t": self.clock(), "tick": self._ticks,
                 "queue": queue,
                 "want_scale_up": sig.get("want_scale_up", 0.0),
                 "shed_rate_fast": sig.get("shed_rate_fast", 0.0)}
        self._after(event)
        if _obs.enabled():
            _obs.registry.counter("cluster.scale_up").inc()
        return event

    def _scale_in(self, sig: dict) -> dict:
        # victim: the most recently added alive replica — the pool
        # shrinks in LIFO order, keeping the long-lived replicas (and
        # their prefix caches) hot
        victim = next(r for r in reversed(self.router.replicas)
                      if r.alive)
        self.router.remove_replica(victim, drain=True)
        event = {"kind": "scale_down", "replica": victim.name,
                 "t": self.clock(), "tick": self._ticks,
                 "idle_ticks": self._idle,
                 "want_scale_down": sig.get("want_scale_down", 0.0)}
        self._after(event)
        if _obs.enabled():
            _obs.registry.counter("cluster.scale_down").inc()
        return event

    def _after(self, event: dict) -> None:
        self.last_event = event
        self._up = 0
        self._idle = 0
        self._cooldown = self.cfg.cooldown_ticks
        if _obs.enabled():
            # the event's own "kind" (scale_up/scale_down) must not
            # shadow the recorder's positional event kind
            _obs.flight_recorder.record(
                "cluster.scale",
                **{k: v for k, v in event.items() if k != "kind"},
                direction=event["kind"])

    # ----------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """The ``scale`` section of the router's ops snapshot (what
        ``tools/ptop.py`` renders)."""
        return {"replicas": len(self._pool()),
                "min": self.cfg.min_replicas,
                "max": self.cfg.max_replicas,
                "up_ticks": self._up, "idle_ticks": self._idle,
                "cooldown": self._cooldown, "ticks": self._ticks,
                "last_event": self.last_event}
