"""ClusterKVStore: the cluster-wide KV cache tier between replicas
and recompute.

Wires the three kv_store pieces into the serving cluster:

* On admission (``ClusterRouter.submit``, after routing picks a
  target), :meth:`prefetch` consults the
  :class:`~paddle_tpu.serving.kv_store.index.GlobalPrefixIndex` for
  the prompt's deepest VALID cached prefix anywhere in the cluster.
  A hit on another replica exports the pages there
  (:meth:`ServingEngine.export_prefix`) and imports them into the
  target (:meth:`ServingEngine.import_prefix` — the
  ``adopt_handoff``-style page move, int8->fp dequant included); a hit
  on the host tier promotes the int8 spill back into the target pool.
  Either way the target's OWN prefix cache then matches the blocks at
  admission (``serving.prefix_hit_tokens`` counts the saved prefill).
  Any miss, stale entry, or CRC failure falls back to recompute —
  the tier can only ever save work, never corrupt a stream.

* On eviction, the :class:`BlockManager` demotion hook hands each
  evicted prefix block's pages to :meth:`_on_evict` instead of
  discarding them; the **async pump** (:meth:`pump`, driven from
  ``router.step()`` or the threaded :meth:`start` loop) quantizes them
  to the universal int8 spill layout, CRC-stamps them into the
  :class:`~paddle_tpu.serving.kv_store.host_tier.HostTier`, and
  registers the host location in the global index. The pump also runs
  **watermark-driven demotion**: replicas whose free list dropped to
  the admission watermark proactively spill their LRU evictable
  blocks (``ServingEngine.demote_evictable``) so pool pressure turns
  into host-tier capacity instead of silent discards.

Activation: pass ``kv_store=ClusterKVStore(...)`` to
:class:`ClusterRouter`, or set ``PADDLE_TPU_KV_TIER=host`` and the
router builds one on the control plane's store automatically. Default
is off — zero behavior change.

Exactness: cross-replica fetches move pages in the native pool layout
(bit-exact). Host-tier restores are bit-exact when the serving pools
are int8 (``kv_quant="int8"`` — the spill IS the pool layout); with fp
pools the spill quantization is lossy, so deploy the host tier with
int8 pools when token-exact parity with recompute matters (the bench
and smoke arms assert exactly this).
"""
from __future__ import annotations

import collections
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ... import observability as _obs
from ...distributed.control_plane import LocalStore
from ...config import knobs
from ...observability.tracing import span
from ...observability.windows import Windows
from ..block_manager import hash_block_tokens
from . import codec
from .host_tier import HostTier
from .index import HOST_OWNER, GlobalPrefixIndex

__all__ = ["ClusterKVStore", "KVStoreConfig"]


class KVStoreConfig:
    """Resolved cluster-KV knobs (ctor args win over env vars)."""

    def __init__(self, tier: Optional[str] = None,
                 host_mb: Optional[float] = None,
                 pump_interval_s: Optional[float] = None,
                 demote_batch: int = 8,
                 max_demote_queue: int = 256):
        # "off" = global index only (cross-replica fetches still work);
        # "host" adds the host-RAM spill tier
        self.tier = (tier or knobs.get_str("PADDLE_TPU_KV_TIER")
                     ).lower()
        self.host_mb = host_mb if host_mb is not None else \
            knobs.get_float("PADDLE_TPU_KV_HOST_MB")
        self.pump_interval_s = pump_interval_s \
            if pump_interval_s is not None \
            else knobs.get_float("PADDLE_TPU_KV_PUMP_S")
        self.demote_batch = int(demote_batch)
        self.max_demote_queue = int(max_demote_queue)
        if self.tier not in ("off", "host"):
            raise ValueError("PADDLE_TPU_KV_TIER must be off|host")
        if self.demote_batch <= 0 or self.max_demote_queue <= 0:
            raise ValueError(
                "demote_batch and max_demote_queue must be > 0")


# plain-int counter keys (always maintained, telemetry on or off, so
# smokes/benches can assert behavior without enabling observability)
_COUNTS = ("lookups", "index_hits", "index_misses", "fetches_replica",
           "fetches_host", "fetch_tokens", "stale_skips", "promotes",
           "demotes", "host_evictions", "crc_failures", "queue_drops")


class ClusterKVStore:
    """Global prefix index + host tier + promote/demote pump."""

    def __init__(self, control_plane=None,
                 config: Optional[KVStoreConfig] = None,
                 store=None, namespace: str = "kv"):
        self.config = config or KVStoreConfig()
        self.control_plane = control_plane
        if store is None:
            store = control_plane.store if control_plane is not None \
                else LocalStore()
        self.index = GlobalPrefixIndex(store, namespace)
        # HostTier serializes put/get/drop behind its own lock — the
        # pump thread's put vs the fetch path's get is its contract
        self.host = HostTier(  # ptlint: disable=thread-escape
            self.config.host_mb) if self.config.tier == "host" else None
        self._lock = threading.Lock()
        self._replicas: Dict[str, object] = {}  # guarded by: _lock
        self._gens: Dict[str, Optional[int]] = {}  # guarded by: _lock
        # evicted blocks awaiting quantize+spill: (hash, k, v, tokens).
        # Bounded: overflow drops the OLDEST (it was the coldest), which
        # degrades to the pre-tier discard behavior, never blocks.
        self._queue: "collections.deque" = collections.deque(
            maxlen=self.config.max_demote_queue)  # guarded by: _lock
        self._counts = {k: 0 for k in _COUNTS}  # guarded by: _lock
        # rolling hit-rate windows for ptop / SLO-style dashboards
        self.windows = Windows("kv")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -------------------------------------------------------- accounting
    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counts[key] += n

    @property
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    # --------------------------------------------------- replica wiring
    def attach(self, replica) -> None:
        """Hook one replica's engine into the tier: prefix
        registrations flow to the global index (generation-fenced with
        the replica's current lease generation) and LRU evictions flow
        to the demote queue instead of being discarded."""
        name = replica.name
        gen = None
        if self.control_plane is not None:
            gen = self.control_plane.generation(name)
        with self._lock:
            self._replicas[name] = replica
            self._gens[name] = gen
        replica.engine.set_kv_hooks(
            on_register=lambda h, _n=name: self._on_register(_n, h),
            on_evict=lambda h, k, v, _n=name:
                self._on_evict(_n, h, k, v))

    def detach(self, replica) -> None:
        with self._lock:
            self._replicas.pop(replica.name, None)
            self._gens.pop(replica.name, None)
        replica.engine.set_kv_hooks(on_register=None, on_evict=None)
        self.index.purge_owner(replica.name)

    def on_replica_dead(self, name: str) -> None:
        """Death/eviction cleanup. Optional for correctness — a dead
        replica's entries already fail lease/generation validation —
        but keeps the index lean."""
        with self._lock:
            self._replicas.pop(name, None)
            self._gens.pop(name, None)
        self.index.purge_owner(name)

    # ------------------------------------------------------ engine hooks
    def _on_register(self, name: str, h: int) -> None:
        with self._lock:
            gen = self._gens.get(name)
        self.index.register(h, name, gen=gen)

    def _on_evict(self, name: str, h: int, k_pages, v_pages) -> None:
        """BlockManager demotion hook (fires under the engine lock):
        the replica no longer holds ``h``; queue its pages for the
        async spill instead of discarding them."""
        self.index.unregister(h, name)
        if self.host is None:
            return
        with self._lock:
            if len(self._queue) == self._queue.maxlen:
                self._counts["queue_drops"] += 1
            self._queue.append((int(h), k_pages, v_pages))

    # ----------------------------------------------------------- lookup
    def _chain(self, prompt: Sequence[int], bs: int) -> List[int]:
        # same limit as BlockManager.match_prefix: at least one prompt
        # token always prefills, so only (len-1)//bs blocks can help
        h: Optional[int] = None
        out: List[int] = []
        for i in range((len(prompt) - 1) // bs):
            h = hash_block_tokens(h, prompt[i * bs:(i + 1) * bs])
            out.append(h)
        return out

    def _valid(self, h: int, owner: str, entry: dict) -> bool:
        """Lookup-time liveness: host entries must be present in the
        tier; replica entries need an attached, alive replica whose
        lease is fresh AND whose current generation matches the one
        fenced into the entry — a dead replica's registrations are
        invalidated by its lease expiry, no cleanup write needed."""
        if entry.get("tier") == "host":
            return self.host is not None and owner == HOST_OWNER \
                and h in self.host
        with self._lock:
            rep = self._replicas.get(owner)
        if rep is None or not rep.alive:
            return False
        cp = self.control_plane
        if cp is not None:
            if not cp.fresh(owner):
                return False
            gen = entry.get("gen")
            if gen is None or int(gen) != cp.generation(owner):
                return False
        return True

    # ------------------------------------------------------------ fetch
    def prefetch(self, rep, prompt: Sequence[int]) -> int:
        """Admission-time fetch: pull the prompt's deepest valid cached
        prefix into ``rep`` so the scheduler's normal ``match_prefix``
        hits it. Returns tokens imported (0 = recompute, the only
        fallback). Never raises past a stale owner or CRC failure."""
        bs = rep.engine.manager.block_size
        chain = self._chain(prompt, bs)
        if not chain:
            return 0
        with span("kv.fetch"):
            self._count("lookups")
            if _obs.enabled():
                self.windows.counter("kv.lookups").inc()
            local = rep.engine.probe_prefix(prompt)
            hit = self.index.lookup(
                chain, lambda h, o, e: self._valid(h, o, e))
            if hit is None or hit[0] <= local or hit[1] == rep.name:
                # nothing anywhere, or the target already holds it
                self._count("index_misses")
                if _obs.enabled():
                    _obs.registry.counter("kv.index_misses").inc()
                return 0
            depth, owner, tier = hit
            self._count("index_hits")
            if _obs.enabled():
                _obs.registry.counter("kv.index_hits").inc()
            if tier == "replica":
                imported = self._fetch_replica(owner, rep, prompt,
                                               depth, bs)
                source = "replica"
            else:
                t0 = time.monotonic()
                with span("kv.promote", args={"blocks": depth}):
                    imported = self._fetch_host(rep, prompt, chain,
                                                depth, bs)
                if imported and _obs.enabled():
                    _obs.registry.histogram(
                        "kv.promote_time").observe(
                            time.monotonic() - t0)
                source = "host"
            if imported:
                self._count("fetches_%s" % source)
                self._count("fetch_tokens", imported)
                if _obs.enabled():
                    _obs.registry.counter(
                        "kv.fetches", tags={"source": source}).inc()
                    _obs.registry.counter(
                        "kv.fetch_tokens",
                        tags={"source": source}).inc(imported)
                    self.windows.counter("kv.hits").inc()
            else:
                self._count("stale_skips")
                if _obs.enabled():
                    _obs.registry.counter("kv.stale_skips").inc()
            return imported

    def _fetch_replica(self, owner: str, rep, prompt, depth: int,
                       bs: int) -> int:
        with self._lock:
            src = self._replicas.get(owner)
        if src is None or not src.alive:
            return 0
        # full prompt, not prompt[:depth*bs] — match_prefix's
        # (len-1)//bs limit would shave the deepest block off a
        # truncated prompt
        out = src.engine.export_prefix(list(prompt))
        if out is None:
            return 0                    # evicted between lookup & now
        k_pages, v_pages, n = out
        n = min(n, depth)
        try:
            return rep.engine.import_prefix(prompt, n, k_pages,
                                            v_pages)
        except ValueError:
            # heterogeneous pools (fp export into int8 target): the
            # codec refuses lossy requantization — recompute instead
            return 0

    def _fetch_host(self, rep, prompt, chain, depth: int,
                    bs: int) -> int:
        """Promote the longest contiguous run of spilled blocks from
        block 0; any gap or CRC failure truncates the run (the rest is
        recomputed)."""
        if self.host is None:
            return 0
        entries = []
        for i in range(depth):
            crc0 = self.host.crc_failures
            ent = self.host.get(chain[i])
            if ent is None:
                failed = self.host.crc_failures - crc0
                if failed:
                    self._count("crc_failures", failed)
                    self.index.unregister(chain[i], HOST_OWNER)
                    if _obs.enabled():
                        _obs.registry.counter(
                            "kv.crc_failures").inc(failed)
                break
            entries.append(ent)
        if not entries:
            return 0
        n = len(entries)
        nl = len(entries[0].k_spill)

        def cat(spills):
            return tuple(
                {"q8": np.concatenate([s[i]["q8"] for s in spills],
                                      axis=1),
                 "s": np.concatenate([s[i]["s"] for s in spills],
                                     axis=1)} for i in range(nl))

        k_pages = cat([e.k_spill for e in entries])
        v_pages = cat([e.v_spill for e in entries])
        try:
            imported = rep.engine.import_prefix(prompt, n, k_pages,
                                                v_pages)
        except ValueError:
            return 0
        if imported:
            self._count("promotes")
            if _obs.enabled():
                _obs.registry.counter("kv.promotes").inc()
        return imported

    # ------------------------------------------------------------- pump
    def pump(self, max_items: Optional[int] = None) -> int:
        """One async promote/demote pass (called from ``router.step()``
        or the threaded loop): proactively demote LRU evictable blocks
        on watermark-pressured replicas, then quantize + CRC + store
        every queued eviction into the host tier and publish the host
        locations in the index. Returns blocks spilled this pass."""
        if self.host is None:
            return 0
        with self._lock:
            reps = list(self._replicas.values())
        for rep in reps:
            if rep.alive:
                # fires the demotion hook under the engine lock, which
                # enqueues onto self._queue — drained just below
                rep.engine.demote_evictable(self.config.demote_batch)
        budget = max_items if max_items is not None else \
            max(self.config.demote_batch * 4, 1)
        moved = 0
        while moved < budget:
            with self._lock:
                if not self._queue:
                    break
                h, k_pages, v_pages = self._queue.popleft()
            t0 = time.monotonic()
            with span("kv.demote", args={"hash": h}):
                k_spill = codec.to_spill(k_pages)
                v_spill = codec.to_spill(v_pages)
                crc = codec.spill_crc(k_spill, v_spill)
                evicted = self.host.put(h, k_spill, v_spill, crc)
            if h in evicted:
                continue                # bigger than the whole budget
            for ev in evicted:
                self.index.unregister(ev, HOST_OWNER)
            if evicted:
                self._count("host_evictions", len(evicted))
            self.index.register_host(h)
            self._count("demotes")
            moved += 1
            if _obs.enabled():
                _obs.registry.counter("kv.demotes").inc()
                if evicted:
                    _obs.registry.counter(
                        "kv.host_evictions").inc(len(evicted))
                _obs.registry.histogram("kv.demote_time").observe(
                    time.monotonic() - t0)
        if _obs.enabled():
            snap = self.host.snapshot()
            _obs.registry.gauge("kv.host_blocks").set(snap["blocks"])
            _obs.registry.gauge("kv.host_bytes").set(snap["bytes"])
            _obs.registry.gauge("kv.index_entries").set(
                self.index.num_entries())
        return moved

    # --------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Threaded pump for ``router.start()`` mode; cadence is
        ``PADDLE_TPU_KV_PUMP_S``. The synchronous ``router.step()``
        driver calls :meth:`pump` directly instead."""
        if self._thread is not None:
            return
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                if self.pump() == 0:
                    time.sleep(self.config.pump_interval_s)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="kv-store-pump")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """The ``kv`` section of the cluster ops snapshot (ptop's KV
        tier panel + diagnose's bundle view read this shape)."""
        counts = self.counts
        looked = counts["lookups"]
        served = counts["fetches_replica"] + counts["fetches_host"]
        with self._lock:
            qlen = len(self._queue)
        return {"kind": "kv_store", "tier": self.config.tier,
                "counts": counts,
                "hit_rate": (served / looked) if looked else 0.0,
                "demote_queue": qlen,
                "host": self.host.snapshot()
                if self.host is not None else None,
                "index": self.index.snapshot(),
                "windows": self.windows.snapshot()}
