"""The ONE page wire/spill codec for paged KV.

Until this module, the int8 ``{"q8","s"}`` page layout had two private
encoders: :meth:`ServingEngine._export_pages` /
:meth:`ServingEngine._import_pages` (the disagg handoff's take/put) and
:meth:`KVHandoff.nbytes` (the disagg pump's byte accounting). Both are
now thin wrappers over this module, and the cluster KV store
(``kv_store/host_tier.py``) reuses the exact same layout as its spill
format — pages quantized once (`quantize_kv_pages`), decoded through
the one ``_dequant`` rule, CRC-checked on every host-tier round trip.

Layout (per layer):

* fp pages: ``np.ndarray [n_kv, nb, page, d]`` in the pool dtype;
* int8 pages: ``{"q8": int8 [n_kv, nb, page, d],
  "s": f32 [n_kv, nb, page]}`` — the PR 12 handoff serialization.

``take_pages`` always returns HOST copies (np.asarray), so a payload
survives the source pool being overwritten or its replica dying.
"""
from __future__ import annotations

import zlib
from typing import List, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ...incubate.nn.pallas.paged_attention import (_dequant,
                                                   quantize_kv_pages)

__all__ = ["take_pages", "put_pages", "pages_nbytes", "to_spill",
           "spill_crc"]


def take_pages(pools: Sequence[object], blocks: Sequence[int]) -> Tuple:
    """Materialize the KV pages of ``blocks`` out of per-layer pools
    (host copies, native pool layout: fp arrays or int8 ``{"q8","s"}``
    dicts). This is the export half of every page move in the tree —
    disagg handoffs, cross-replica prefix fetches, host-tier spills."""
    idx = np.asarray(blocks, np.int32)

    def take(pool):
        if isinstance(pool, dict):
            return {"q8": np.asarray(pool["q8"][:, idx]),
                    "s": np.asarray(pool["s"][:, idx])}
        return np.asarray(pool[:, idx])

    return tuple(take(p) for p in pools)


def put_pages(pool, blocks: Sequence[int], pages):
    """Write exported pages into a pool at ``blocks`` (returns the new
    pool). int8 payloads land in an fp pool through the shared
    ``_dequant`` rule; fp payloads cannot be requantized losslessly, so
    offering them to an int8 pool raises."""
    idx = np.asarray(blocks, np.int32)
    if isinstance(pool, dict):
        if not isinstance(pages, dict):
            raise ValueError("fp pages offered to an int8 pool")
        return {"q8": pool["q8"].at[:, idx].set(
                    jnp.asarray(pages["q8"])),
                "s": pool["s"].at[:, idx].set(
                    jnp.asarray(pages["s"]))}
    if isinstance(pages, dict):
        # int8 wire payload into an fp pool: decode through the
        # shared page-codec rule
        deq = _dequant(pages["q8"], pages["s"])
        return pool.at[:, idx].set(jnp.asarray(deq, pool.dtype))
    return pool.at[:, idx].set(jnp.asarray(pages, pool.dtype))


def pages_nbytes(pages: Sequence[object]) -> int:
    """Payload bytes of a per-layer page sequence (fp arrays or int8
    dicts) — the disagg pump's span accounting."""
    total = 0
    for p in pages:
        if isinstance(p, dict):
            total += p["q8"].nbytes + p["s"].nbytes
        else:
            total += p.nbytes
    return total


def to_spill(pages: Sequence[object]) -> Tuple:
    """Normalize per-layer pages to the universal int8 spill layout
    (host copies). Already-quantized dicts pass through; fp pages are
    quantized with the same ``quantize_kv_pages`` the int8 pools use.
    NOTE: fp -> int8 is lossy; a host-tier restore into an fp pool is
    only TOKEN-exact when the serving pools are int8 themselves (the
    spill then round-trips bit-exact)."""
    out: List[dict] = []
    for p in pages:
        if isinstance(p, dict):
            out.append({"q8": np.asarray(p["q8"]),
                        "s": np.asarray(p["s"])})
        else:
            q = quantize_kv_pages(jnp.asarray(p))
            out.append({"q8": np.asarray(q["q8"]),
                        "s": np.asarray(q["s"])})
    return tuple(out)


def spill_crc(k_spill: Sequence[dict], v_spill: Sequence[dict]) -> int:
    """CRC32 over every spill byte (q8 payloads + scales, k then v,
    layer order) — what the host tier verifies on every round trip so
    a corrupted page is a recompute, never wrong attention."""
    crc = 0
    for layer in tuple(k_spill) + tuple(v_spill):
        crc = zlib.crc32(np.ascontiguousarray(layer["q8"]), crc)
        crc = zlib.crc32(np.ascontiguousarray(
            layer["s"], dtype=np.float32), crc)
    return crc
