"""Cluster-wide KV store: global prefix index + host-RAM tier.

The tier between replica block pools and recompute:

* :mod:`~paddle_tpu.serving.kv_store.codec` — the ONE int8 page
  wire/spill codec (extracted from the engine/disagg duplicates).
* :mod:`~paddle_tpu.serving.kv_store.index` — cluster-global prefix
  index on the control-plane store, generation-fenced registration.
* :mod:`~paddle_tpu.serving.kv_store.host_tier` — capacity-bounded
  host-RAM spill tier with CRC-checked round trips.
* :mod:`~paddle_tpu.serving.kv_store.fetch` — router/engine glue:
  admission-time prefetch, async promote/demote pump.
"""
from . import codec
from .fetch import ClusterKVStore, KVStoreConfig
from .host_tier import HostEntry, HostTier
from .index import HOST_OWNER, GlobalPrefixIndex

__all__ = ["ClusterKVStore", "KVStoreConfig", "GlobalPrefixIndex",
           "HOST_OWNER", "HostTier", "HostEntry", "codec"]
