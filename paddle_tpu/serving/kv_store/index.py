"""Cluster-global prefix index: block-hash chains -> page locations.

The router already keys prefix affinity by the rolling
:func:`~paddle_tpu.serving.block_manager.hash_block_tokens` chain; this
index makes the SAME keys cluster-global state. Each chain hash maps to
the set of owners currently holding that block's KV pages:

* ``{replica_name: {"tier": "replica", "gen": lease_generation}}`` —
  the replica's paged prefix cache holds the block. Registration is
  **generation-fenced** exactly like the control-plane leases: the
  entry carries the lease generation the replica held when it
  registered, and a lookup only trusts it while the replica's lease is
  fresh AND its current generation still matches. A dead replica's
  entries are therefore invalidated by its lease expiry with NO
  cleanup write needed (``purge_owner`` is an optimization, not a
  correctness requirement).
* ``{"host": {"tier": "host"}}`` — the host-RAM tier
  (:class:`~paddle_tpu.serving.kv_store.host_tier.HostTier`) holds the
  block's int8 spill. Validity is presence in the tier (checked by the
  caller's validator), so a capacity eviction needs no fencing.

Entries live on the control plane's store (one JSON doc per chain hash
at ``{ns}/kvidx/{hash}``) through the TCPStore client surface only —
``set/try_get/delete`` — so a multi-host pool can move to the job
store unchanged. The read-modify-write on one doc is best-effort by
design: losing an entry in a write race is a cache miss (recompute),
never a correctness problem, because every lookup is re-validated and
every fetch falls back to recompute.
"""
from __future__ import annotations

import json
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ...distributed.control_plane import LocalStore, try_get
from ...distributed.control_plane import keyspace as _ks

__all__ = ["GlobalPrefixIndex", "HOST_OWNER"]

# the reserved owner key for host-tier entries (replica names are
# cluster replica names like "r0"; none of them may shadow this)
HOST_OWNER = "host"


class GlobalPrefixIndex:
    """Store-backed chain-hash -> owner map with fenced registration."""

    def __init__(self, store=None, namespace: str = "cluster"):
        self.store = store if store is not None else LocalStore()
        self.ns = str(namespace)
        self._lock = threading.Lock()
        # owner -> registered hashes, for purge without store listing
        # (TCPStore has no key scan); purely an eviction accelerator
        self._by_owner: Dict[str, set] = {}  # guarded by: _lock

    def _k(self, h: int) -> str:
        return _ks.kvidx(self.ns, int(h))

    # ------------------------------------------------------------- doc IO
    def _read(self, h: int) -> Dict[str, dict]:
        raw = try_get(self.store, self._k(h))
        if raw is None:
            return {}
        try:
            doc = json.loads(raw.decode())
            return doc if isinstance(doc, dict) else {}
        except Exception:
            return {}

    def _write(self, h: int, doc: Dict[str, dict]) -> None:
        # blessed low-level writer: per-entry lease generations are
        # attached one hop up (register() stores {"gen": ...} per
        # replica entry); this is the one doc-serialization point
        if doc:
            self.store.set(  # ptlint: disable=fence-discipline
                _ks.kvidx(self.ns, int(h)), json.dumps(doc).encode())
        else:
            try:
                self.store.delete(self._k(h))
            except Exception:
                pass

    # --------------------------------------------------------- mutation
    def register(self, h: int, owner: str,
                 gen: Optional[int] = None) -> None:
        """Record that ``owner`` holds the pages of chain hash ``h``.
        Replica owners pass their current lease generation; host-tier
        registration uses :meth:`register_host`."""
        doc = self._read(h)
        entry: dict = {"tier": "replica"}
        if gen is not None:
            entry["gen"] = int(gen)
        doc[str(owner)] = entry
        self._write(h, doc)
        with self._lock:
            self._by_owner.setdefault(str(owner), set()).add(int(h))

    def register_host(self, h: int) -> None:
        doc = self._read(h)
        doc[HOST_OWNER] = {"tier": "host"}
        self._write(h, doc)
        with self._lock:
            self._by_owner.setdefault(HOST_OWNER, set()).add(int(h))

    def unregister(self, h: int, owner: str) -> None:
        doc = self._read(h)
        if str(owner) in doc:
            del doc[str(owner)]
            self._write(h, doc)
        with self._lock:
            hs = self._by_owner.get(str(owner))
            if hs is not None:
                hs.discard(int(h))

    def purge_owner(self, owner: str) -> int:
        """Drop every entry ``owner`` registered (replica death/leave,
        host-tier teardown). Lookups were already safe without this —
        a dead replica's entries fail lease/generation validation — so
        this only keeps the index from accumulating tombstones."""
        with self._lock:
            hs = sorted(self._by_owner.pop(str(owner), ()))
        for h in hs:
            doc = self._read(h)
            if str(owner) in doc:
                del doc[str(owner)]
                self._write(h, doc)
        return len(hs)

    # ----------------------------------------------------------- lookup
    def owners(self, h: int) -> Dict[str, dict]:
        """Raw (unvalidated) owner entries of one chain hash."""
        return self._read(h)

    def lookup(self, chain: Sequence[int],
               valid: Callable[[int, str, dict], bool]) \
            -> Optional[Tuple[int, str, str]]:
        """Deepest chain position with a VALID owner. ``chain`` is the
        rolling hash chain of a prompt (``chain[i]`` covers blocks
        ``0..i``); ``valid(h, owner, entry)`` is the caller's liveness
        check (lease freshness + generation fencing for replicas,
        tier presence for the host). Returns ``(depth_blocks, owner,
        tier)`` — depth in whole blocks, 1-based — or None.

        Replica owners win ties at equal depth (their pages are
        already device-resident); the walk is deepest-first so one
        valid hit ends it."""
        for i in range(len(chain) - 1, -1, -1):
            doc = self._read(chain[i])
            if not doc:
                continue
            best: Optional[Tuple[str, str]] = None
            for owner, entry in sorted(doc.items()):
                if not valid(chain[i], owner, entry):
                    continue
                tier = str(entry.get("tier", "replica"))
                if tier == "replica":
                    best = (owner, tier)
                    break               # device-resident beats host
                if best is None:
                    best = (owner, tier)
            if best is not None:
                return i + 1, best[0], best[1]
        return None

    # --------------------------------------------------------- snapshot
    def num_entries(self) -> int:
        with self._lock:
            return len(set().union(*self._by_owner.values())
                       if self._by_owner else ())

    def snapshot(self) -> dict:
        with self._lock:
            per = {o: len(hs) for o, hs in sorted(self._by_owner.items())}
        return {"kind": "kv_prefix_index", "ns": self.ns,
                "entries": self.num_entries(), "by_owner": per}
