"""Host-RAM KV tier: capacity-bounded int8 page spill storage.

The second rung of the cluster cache hierarchy: when a replica's paged
prefix cache evicts an LRU block (pool pressure), the block's pages are
no longer discarded — they are quantized to the universal int8 spill
layout (:func:`~paddle_tpu.serving.kv_store.codec.to_spill`, the PR 12
handoff serialization) and parked here, keyed by the SAME rolling
chain hash the prefix caches and the global index use. A later request
anywhere in the cluster promotes them back into a device pool instead
of recomputing the prefill.

Properties:

* **Capacity-bounded** — ``PADDLE_TPU_KV_HOST_MB`` (ctor arg wins)
  caps payload bytes; inserting past the cap evicts LRU entries first
  (the evicted hashes are returned so the caller can unregister them
  from the global index).
* **CRC-checked round trips** — every entry stores the CRC32 of its
  spill bytes at insert; :meth:`get` re-computes and verifies, and a
  mismatch DROPS the entry and returns None — a corrupted page is a
  recompute upstream, never wrong attention.
* **Engine-agnostic** — entries are plain host numpy; nothing here
  imports jax, so the tier (and its tests) stay cheap.

One entry = one block's per-layer k/v spill pages. Promotion of a
multi-block prefix is the caller walking the chain shallow-to-deep and
concatenating contiguous hits (:meth:`ClusterKVStore._fetch_host`).
"""
from __future__ import annotations

import collections
import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from . import codec

from ...config import knobs

__all__ = ["HostTier", "HostEntry"]


class HostEntry:
    """One spilled block: per-layer int8 k/v pages + integrity CRC."""

    __slots__ = ("h", "k_spill", "v_spill", "crc", "nbytes", "tokens")

    def __init__(self, h: int, k_spill: Tuple, v_spill: Tuple,
                 crc: int, tokens: int):
        self.h = int(h)
        self.k_spill = k_spill
        self.v_spill = v_spill
        self.crc = int(crc)
        self.nbytes = codec.pages_nbytes(k_spill) + \
            codec.pages_nbytes(v_spill)
        self.tokens = int(tokens)


class HostTier:
    """LRU dict of spilled blocks under a byte budget (thread-safe)."""

    def __init__(self, capacity_mb: Optional[float] = None):
        mb = capacity_mb if capacity_mb is not None else \
            knobs.get_float("PADDLE_TPU_KV_HOST_MB")
        self.capacity_bytes = int(max(0.0, float(mb)) * 1024 * 1024)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[int, HostEntry]" = \
            collections.OrderedDict()  # guarded by: _lock (LRU order)
        self._bytes = 0  # guarded by: _lock
        self._crc_failures = 0  # guarded by: _lock

    # ---------------------------------------------------------- mutation
    def put(self, h: int, k_spill: Sequence[dict],
            v_spill: Sequence[dict], crc: Optional[int] = None,
            tokens: int = 0) -> List[int]:
        """Insert (or refresh) one block's spill under chain hash
        ``h``; evicts LRU entries to fit. Returns the evicted hashes
        (so the caller can unregister them from the global index). An
        entry larger than the whole budget is refused (returned as its
        own "eviction")."""
        if crc is None:
            crc = codec.spill_crc(k_spill, v_spill)
        ent = HostEntry(h, tuple(k_spill), tuple(v_spill), crc, tokens)
        evicted: List[int] = []
        with self._lock:
            old = self._entries.pop(ent.h, None)
            if old is not None:
                self._bytes -= old.nbytes
            if ent.nbytes > self.capacity_bytes:
                return [ent.h]          # refused: caller must not index
            while self._bytes + ent.nbytes > self.capacity_bytes \
                    and self._entries:
                ev_h, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                evicted.append(ev_h)
            self._entries[ent.h] = ent
            self._bytes += ent.nbytes
        return evicted

    def get(self, h: int) -> Optional[HostEntry]:
        """Fetch one block's spill (refreshes LRU). Verifies the CRC
        over the stored bytes; a mismatch drops the entry and returns
        None — the caller falls back to recompute."""
        with self._lock:
            ent = self._entries.get(int(h))
            if ent is None:
                return None
            self._entries.move_to_end(int(h))
        if codec.spill_crc(ent.k_spill, ent.v_spill) != ent.crc:
            with self._lock:
                cur = self._entries.pop(int(h), None)
                if cur is not None:
                    self._bytes -= cur.nbytes
                self._crc_failures += 1
            return None
        return ent

    def drop(self, h: int) -> bool:
        with self._lock:
            ent = self._entries.pop(int(h), None)
            if ent is None:
                return False
            self._bytes -= ent.nbytes
            return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # ------------------------------------------------------------ health
    def __contains__(self, h: int) -> bool:
        with self._lock:
            return int(h) in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def bytes_used(self) -> int:
        with self._lock:
            return self._bytes

    @property
    def crc_failures(self) -> int:
        with self._lock:
            return self._crc_failures

    def snapshot(self) -> Dict:
        with self._lock:
            return {"kind": "kv_host_tier",
                    "blocks": len(self._entries),
                    "bytes": self._bytes,
                    "capacity_bytes": self.capacity_bytes,
                    "crc_failures": self._crc_failures}
