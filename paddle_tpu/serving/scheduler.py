"""Slot-based continuous-batching scheduler.

The engine decodes with ONE jitted fixed-shape step over ``max_slots``
rows; requests come and go by flipping per-slot masks (position ``-1``
means "empty slot"), never by changing array shapes — so the decode
step compiles exactly once for the lifetime of the engine.

This module is pure bookkeeping (no jax): it decides *which* request
occupies *which* slot, when a waiting request is admitted (FCFS, gated
on block availability through :class:`BlockManager.can_allocate`), how
prompt prefill is broken into fixed-size chunks interleaved with decode
steps, and who gets preempted (evict-and-recompute: youngest running
request releases its pages and re-queues with ``prompt + generated`` as
its new prompt) when the pool runs dry mid-decode.  Keeping it
array-free lets the property tests drive thousands of randomized
admit/cancel/preempt/finish sequences without touching a device.
"""
from __future__ import annotations

import collections
import dataclasses
import itertools
from typing import Deque, Dict, List, Optional, Tuple

from .block_manager import BlockManager

__all__ = ["Request", "Scheduler", "PrefillChunk",
           "WAITING", "PREFILL", "RUNNING", "HANDOFF", "FINISHED",
           "CANCELLED"]

# request lifecycle states; preemption maps RUNNING/PREFILL -> WAITING.
# HANDOFF is the disaggregated-prefill terminal-on-this-engine state: the
# prompt KV is resident and the first token sampled, but decode happens
# on ANOTHER engine after the cluster layer exports the pages.
WAITING = "waiting"
PREFILL = "prefill"
RUNNING = "running"
HANDOFF = "handoff"
FINISHED = "finished"
CANCELLED = "cancelled"

_rid_counter = itertools.count()


@dataclasses.dataclass
class Request:
    """One inference request moving through the engine."""
    prompt: List[int]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    deadline: Optional[float] = None      # absolute time.monotonic()
    arrival: float = 0.0
    rid: int = dataclasses.field(
        default_factory=lambda: next(_rid_counter))
    state: str = WAITING
    slot: int = -1
    blocks: List[int] = dataclasses.field(default_factory=list)
    num_cached: int = 0          # prompt tokens restored from prefix cache
    prefilled: int = 0           # prompt tokens whose KV is resident
    generated: List[int] = dataclasses.field(default_factory=list)
    remaining: int = 0           # tokens still to emit (set on first add)
    preemptions: int = 0
    first_token_at: Optional[float] = None
    finish_reason: Optional[str] = None
    handoff: bool = False        # disagg: stop after prefill + 1st token
    handoff_token: Optional[int] = None  # the sampled 1st token
    # observability.request_log.RequestTimeline, attached by the engine
    # ONLY when telemetry is enabled — None keeps the scheduler's hot
    # paths at one attribute read on the disabled path, and the
    # scheduler stays clock-free (the timeline owns its clock)
    timeline: Optional[object] = None

    def __post_init__(self) -> None:
        if not self.prompt:
            raise ValueError("empty prompt")
        if self.max_new_tokens <= 0:
            raise ValueError("max_new_tokens must be > 0")
        self.remaining = self.max_new_tokens

    # position of the NEXT KV write during decode: the last generated
    # token sits at len(prompt) + len(generated) - 1
    def decode_pos(self) -> int:
        return len(self.prompt) + len(self.generated) - 1

    def total_len(self) -> int:
        return len(self.prompt) + len(self.generated)


@dataclasses.dataclass
class PrefillChunk:
    """One chunk of prompt tokens to run this step (at most one per
    scheduler step, interleaved with decode)."""
    req: Request
    start: int                   # first prompt index in the chunk
    tokens: List[int]
    last: bool                   # completes the prompt -> sample token


class Scheduler:
    """FCFS continuous-batching scheduler over a fixed slot grid."""

    def __init__(self, manager: BlockManager, max_slots: int,
                 prefill_chunk: int, max_seq_len: int):
        if max_slots <= 0:
            raise ValueError("max_slots must be > 0")
        if prefill_chunk <= 0:
            raise ValueError("prefill_chunk must be > 0")
        self.manager = manager
        self.max_slots = int(max_slots)
        self.prefill_chunk = int(prefill_chunk)
        self.max_seq_len = int(max_seq_len)
        self.waiting: Deque[Request] = collections.deque()
        self.slots: Dict[int, Request] = {}
        self._free_slots: List[int] = list(range(max_slots))[::-1]
        self.preemptions = 0

    # ------------------------------------------------------------ intake
    def add(self, req: Request) -> None:
        if req.total_len() + req.remaining > self.max_seq_len:
            raise ValueError(
                "request needs %d positions, engine max_seq_len is %d"
                % (req.total_len() + req.remaining, self.max_seq_len))
        req.state = WAITING
        self.waiting.append(req)

    def cancel(self, req: Request, reason: str = "cancelled") -> None:
        """Remove a request wherever it is and release its resources."""
        if req.state in (FINISHED, CANCELLED):
            return
        if req.state == WAITING:
            try:
                self.waiting.remove(req)
            except ValueError:
                pass
        else:
            self._release(req)
        req.state = CANCELLED
        req.finish_reason = reason

    def finish(self, req: Request, reason: str) -> None:
        """Normal completion: publish full prompt blocks to the prefix
        cache, then drop this request's references."""
        self.manager.register_prefix(req.prompt, req.blocks)
        self._release(req)
        req.state = FINISHED
        req.finish_reason = reason

    def _release(self, req: Request) -> None:
        if req.blocks:
            self.manager.free(req.blocks)
            req.blocks = []
        if req.slot >= 0:
            del self.slots[req.slot]
            self._free_slots.append(req.slot)
            req.slot = -1

    # -------------------------------------------------------- scheduling
    def running(self) -> List[Request]:
        return [r for r in self.slots.values() if r.state == RUNNING]

    def num_active(self) -> int:
        return len(self.slots)

    def admit(self) -> List[Request]:
        """FCFS admission: pop waiting requests into free slots while
        the pool can cover their prompt (+1 decode block) above the
        watermark.  Head-of-line blocking is intentional — skipping
        ahead would starve long prompts."""
        admitted: List[Request] = []
        while self.waiting and self._free_slots:
            req = self.waiting[0]
            cached_blocks, cached = self.manager.match_prefix(req.prompt)
            need_total = self.manager.blocks_for_tokens(
                len(req.prompt) + 1)
            need_new = need_total - len(cached_blocks)
            if not self.manager.can_allocate(need_new):
                self.manager.free(cached_blocks)   # undo the match refs
                break
            self.waiting.popleft()
            req.blocks = cached_blocks + self.manager.allocate(need_new)
            req.num_cached = cached
            req.prefilled = cached
            req.slot = self._free_slots.pop()
            self.slots[req.slot] = req
            req.state = PREFILL
            if req.timeline is not None:
                req.timeline.mark_admitted()
            admitted.append(req)
        return admitted

    def place_running(self, req: Request, blocks: List[int]) -> None:
        """Seat an externally-prefilled request (disaggregated handoff)
        straight into a decode slot: its KV pages were imported by the
        engine, so it skips WAITING/PREFILL entirely."""
        if not self._free_slots:
            raise RuntimeError("no free slot for adopted request")
        req.blocks = list(blocks)
        req.prefilled = len(req.prompt)
        req.slot = self._free_slots.pop()
        self.slots[req.slot] = req
        req.state = RUNNING

    def next_prefill(self) -> Optional[PrefillChunk]:
        """The oldest slot still prefilling gets one chunk this step."""
        cands = [r for r in self.slots.values() if r.state == PREFILL]
        if not cands:
            return None
        req = min(cands, key=lambda r: r.arrival)
        start = req.prefilled
        n = min(self.prefill_chunk, len(req.prompt) - start)
        return PrefillChunk(req, start,
                            req.prompt[start:start + n],
                            last=start + n == len(req.prompt))

    def next_prefills(self, token_budget: int) -> List[PrefillChunk]:
        """Ragged-step prefill packing: oldest-first PREFILL slots each
        take as many prompt tokens as still fit ``token_budget``.  The
        budget bounds per-step latency globally, so there is no
        per-request chunk cap — several short prompts can finish their
        whole prefill in one step, riding alongside the decode rows."""
        chunks: List[PrefillChunk] = []
        left = int(token_budget)
        cands = sorted((r for r in self.slots.values()
                        if r.state == PREFILL),
                       key=lambda r: r.arrival)
        for req in cands:
            if left <= 0:
                break
            start = req.prefilled
            n = min(left, len(req.prompt) - start)
            if n <= 0:
                continue
            chunks.append(PrefillChunk(req, start,
                                       req.prompt[start:start + n],
                                       last=start + n == len(req.prompt)))
            left -= n
        return chunks

    def ensure_decode_blocks(self) -> List[Request]:
        """Before a decode step, make sure every RUNNING request owns
        the page its next KV write lands in; preempt
        (evict-and-recompute) youngest-first when the pool is dry.
        Returns the list of preempted requests."""
        preempted: List[Request] = []
        for req in sorted(self.running(), key=lambda r: r.arrival):
            if req.state != RUNNING:     # already preempted this pass
                continue
            need_block = req.decode_pos() // self.manager.block_size
            while need_block >= len(req.blocks):
                if self.manager.num_free() > 0:
                    req.blocks.extend(self.manager.allocate(1))
                    continue
                victim = self._pick_victim(exclude=req)
                if victim is None:
                    victim = req          # nobody younger: evict self
                self._preempt(victim)
                preempted.append(victim)
                if victim is req:
                    break
        return preempted

    def _pick_victim(self, exclude: Request) -> Optional[Request]:
        cands = [r for r in self.slots.values()
                 if r is not exclude and r.state in (RUNNING, PREFILL)]
        if not cands:
            return None
        return max(cands, key=lambda r: r.arrival)   # youngest

    def _preempt(self, req: Request) -> None:
        """Evict-and-recompute: fold generated tokens into the prompt,
        release pages + slot, and re-queue at the FCFS position its
        arrival time dictates (front of line among waiting)."""
        self._release(req)
        req.prompt = req.prompt + req.generated
        req.generated = []
        req.prefilled = 0
        req.num_cached = 0
        req.preemptions += 1
        self.preemptions += 1
        if req.timeline is not None:
            req.timeline.mark_preempted()
        req.state = WAITING
        # keep the waiting deque sorted by arrival (FCFS overall)
        idx = 0
        for idx, w in enumerate(self.waiting):      # noqa: B007
            if w.arrival > req.arrival:
                break
        else:
            idx = len(self.waiting)
        self.waiting.insert(idx, req)

    # ------------------------------------------------------------ checks
    def assert_consistent(self) -> None:
        """Slot grid and block refs line up (property-test hook)."""
        assert len(self.slots) + len(self._free_slots) == self.max_slots
        assert set(self.slots) | set(self._free_slots) == \
            set(range(self.max_slots))
        for s, r in self.slots.items():
            assert r.slot == s
            assert r.state in (PREFILL, RUNNING, HANDOFF)
        for r in self.waiting:
            assert r.state == WAITING
            assert not r.blocks and r.slot == -1
