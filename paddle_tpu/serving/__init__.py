"""Continuous-batching serving engine (paged KV + slot scheduler).

Quick start::

    import paddle_tpu as pt
    eng = pt.serving.ServingEngine(model, max_slots=4, block_size=16)
    eng.start()
    rid = eng.submit(prompt_ids, max_new_tokens=32)
    for tok in eng.stream(rid):
        ...
    eng.shutdown()
"""
from .block_manager import BlockManager, hash_block_tokens  # noqa: F401
from .engine import (EngineConfig, EngineStats, KVHandoff,  # noqa: F401
                     RequestDescriptor, RequestError, ServingEngine)
from .scheduler import (CANCELLED, FINISHED, HANDOFF, PREFILL,  # noqa: F401
                        RUNNING, WAITING, PrefillChunk, Request,
                        Scheduler)
from . import cluster  # noqa: E402,F401  (after engine: cluster uses it)
from . import kv_store  # noqa: E402,F401
from .kv_store import ClusterKVStore, KVStoreConfig  # noqa: F401

__all__ = ["ServingEngine", "EngineConfig", "RequestError",
           "BlockManager", "Scheduler", "Request", "PrefillChunk",
           "EngineStats", "RequestDescriptor", "KVHandoff", "cluster",
           "kv_store", "ClusterKVStore", "KVStoreConfig"]
