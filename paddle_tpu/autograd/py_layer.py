"""PyLayer: user-defined autograd ops (reference:
python/paddle/autograd/py_layer.py).

The user's ``backward`` staticmethod is wired straight into the tape as a
custom GradNode — no jax.vjp involved, mirroring the reference's
PyLayer GradNode (fluid/eager/pylayer/py_layer_node.h)."""
from __future__ import annotations

from typing import Any

from ..core.autograd import GradNode, is_grad_enabled
from ..core.tensor import Tensor


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.container = None

    def save_for_backward(self, *tensors):
        from . import _current_saved_tensors_hooks

        # the unpack hook is captured at PACK time (reference semantics:
        # saved_tensors_hooks.py — backward may run after the context exits)
        pack, self._unpack = _current_saved_tensors_hooks()
        self._saved = [pack(t.detach()) if isinstance(t, Tensor) else t
                       for t in tensors]

    def saved_tensor(self):
        unpack = getattr(self, "_unpack", lambda t: t)
        return [unpack(t) for t in self._saved]

    # paddle also exposes mark_not_inplace etc.; no-ops here
    def mark_not_inplace(self, *args):
        pass

    def mark_non_differentiable(self, *args):
        self._non_diff = args

    def set_materialize_grads(self, value: bool):
        self._materialize = value


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError(
            "PyLayer subclasses are not instantiated; call .apply(...)")


class PyLayer(metaclass=PyLayerMeta):
    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        need_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_inputs)

        outputs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outputs, (tuple, list))
        outs = (outputs,) if single else tuple(outputs)

        if not need_grad:
            return outputs

        diff_inputs = [t for t in tensor_inputs if not t.stop_gradient]

        def vjp_fn(cotangents):
            if not isinstance(cotangents, (tuple, list)):
                cotangents = (cotangents,)
            grads = cls.backward(
                ctx, *[Tensor(c) if c is not None else None
                       for c in cotangents])
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            garrs = [g._data if isinstance(g, Tensor) else g for g in grads]
            # align with diff inputs: the user returns one grad per
            # *tensor input* in order; keep only the differentiable ones
            aligned = []
            gi = 0
            for t in tensor_inputs:
                g = garrs[gi] if gi < len(garrs) else None
                gi += 1
                if not t.stop_gradient:
                    aligned.append(g)
            return tuple(aligned)

        node = GradNode(
            vjp_fn=vjp_fn,
            inputs=diff_inputs,
            out_meta=[(tuple(o.shape), o._data.dtype) for o in outs
                      if isinstance(o, Tensor)],
            name=cls.__name__,
        )
        wrapped = []
        idx = 0
        for o in outs:
            if isinstance(o, Tensor):
                w = Tensor(o._data, stop_gradient=False, grad_node=node,
                           out_index=idx)
                idx += 1
                wrapped.append(w)
            else:
                wrapped.append(o)
        return wrapped[0] if single else tuple(wrapped)


# vjp_fn signature note: core.autograd.backward calls node.vjp_fn(cotangent)
# (single output) or node.vjp_fn(tuple) (multi) — PyLayer's vjp_fn above
# normalizes both.
