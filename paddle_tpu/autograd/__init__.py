"""paddle_tpu.autograd (reference: python/paddle/autograd/)."""
from __future__ import annotations

from ..core.autograd import backward, grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import hessian, jacobian, jvp, vjp  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "jacobian",
           "hessian", "jvp", "vjp", "saved_tensors_hooks"]

_hooks_stack = []


def _current_saved_tensors_hooks():
    if _hooks_stack:
        return _hooks_stack[-1]
    ident = lambda t: t
    return ident, ident


class saved_tensors_hooks:
    """Context manager installing pack/unpack hooks on tensors saved for
    backward (reference: python/paddle/autograd/saved_tensors_hooks.py).

    On this tape the hook applies at the PyLayer ``save_for_backward`` /
    ``saved_tensor`` boundary — the jnp-op residuals live inside jax.vjp
    closures, which XLA already rematerializes/spills optimally, so the
    reference's main use case (offloading custom-op activations) maps to
    exactly this surface.
    """

    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _hooks_stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _hooks_stack.pop()
        return False
