"""paddle_tpu.autograd (reference: python/paddle/autograd/)."""
from __future__ import annotations

from ..core.autograd import backward, grad, is_grad_enabled, no_grad, set_grad_enabled  # noqa: F401
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import hessian, jacobian, jvp, vjp  # noqa: F401

__all__ = ["backward", "grad", "no_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "jacobian",
           "hessian", "jvp", "vjp"]
