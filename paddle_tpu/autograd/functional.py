"""Functional autodiff: jacobian/hessian/jvp/vjp over jax transforms
(reference: python/paddle/autograd/functional.py — but here jax.jacobian &
co. do the work natively)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import no_grad
from ..core.tensor import Tensor


def _wrap_fn(func):
    """Wrap a user fn taking/returning Tensors into one over arrays."""

    def inner(*arrays):
        with no_grad():
            outs = func(*[Tensor(a) for a in arrays])
        if isinstance(outs, (tuple, list)):
            return tuple(o._data for o in outs)
        return outs._data

    return inner


def jacobian(func, xs, create_graph=False, allow_unused=False):
    single = isinstance(xs, Tensor)
    arrays = [xs._data] if single else [x._data for x in xs]
    jac = jax.jacobian(_wrap_fn(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if single:
        j = jac[0] if isinstance(jac, tuple) else jac
        return Tensor(j)
    return jax.tree_util.tree_map(Tensor, jac)


def hessian(func, xs, create_graph=False, allow_unused=False):
    single = isinstance(xs, Tensor)
    arrays = [xs._data] if single else [x._data for x in xs]
    hess = jax.hessian(_wrap_fn(func), argnums=tuple(range(len(arrays))))(
        *arrays)
    if single:
        h = hess
        while isinstance(h, tuple):
            h = h[0]
        return Tensor(h)
    return jax.tree_util.tree_map(Tensor, hess)


def jvp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    arrays = (xs._data,) if single else tuple(x._data for x in xs)
    if v is None:
        tangents = tuple(jnp.ones_like(a) for a in arrays)
    else:
        vs = (v,) if isinstance(v, Tensor) else tuple(v)
        tangents = tuple(t._data for t in vs)
    out, tangent_out = jax.jvp(_wrap_fn(func), arrays, tangents)
    return jax.tree_util.tree_map(Tensor, out), \
        jax.tree_util.tree_map(Tensor, tangent_out)


def vjp(func, xs, v=None):
    single = isinstance(xs, Tensor)
    arrays = (xs._data,) if single else tuple(x._data for x in xs)
    out, vjp_fn = jax.vjp(_wrap_fn(func), *arrays)
    if v is None:
        cot = jax.tree_util.tree_map(jnp.ones_like, out)
    else:
        vs = v if not isinstance(v, Tensor) else v
        cot = jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, vs)
    grads = vjp_fn(cot)
    grads_t = jax.tree_util.tree_map(Tensor, grads)
    out_t = jax.tree_util.tree_map(Tensor, out)
    if single:
        return out_t, grads_t[0]
    return out_t, list(grads_t)
