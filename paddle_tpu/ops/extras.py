"""Long-tail tensor ops (reference: the remainder of the
python/paddle/tensor/ surface — math.py/manipulation.py entries not covered
by the core modules: diagonal, logcumsumexp, quantile, mode, trapezoid,
renorm, frexp/ldexp, complex helpers, special functions, isin, vdot,
baddbmm, masked_scatter, unfold)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy import special as jsp

from ._helpers import as_tensor, binary, run_op, unary, unwrap

__all__ = ["diagonal", "logcumsumexp", "quantile", "nanquantile", "mode",
           "trapezoid", "cumulative_trapezoid", "renorm", "frexp", "ldexp",
           "polar", "as_complex", "as_real", "gammaln", "gammainc",
           "gammaincc", "i0", "i0e", "i1", "i1e", "sinc", "isin", "vdot",
           "baddbmm", "masked_scatter", "unfold", "logit", "polygamma"]


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return run_op(lambda a: jnp.diagonal(a, offset, axis1, axis2),
                  [as_tensor(x)], name="diagonal")


def logcumsumexp(x, axis=None, dtype=None, name=None):
    def fn(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)

    return run_op(fn, [as_tensor(x)], name="logcumsumexp")


def quantile(x, q, axis=None, keepdim=False, interpolation="linear",
             name=None):
    return run_op(lambda a: jnp.quantile(
        a, jnp.asarray(q), axis=axis, keepdims=keepdim,
        method=interpolation), [as_tensor(x)], name="quantile")


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return run_op(lambda a: jnp.nanquantile(
        a, jnp.asarray(q), axis=axis, keepdims=keepdim),
        [as_tensor(x)], name="nanquantile")


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along axis; returns (values, indices)."""
    t = as_tensor(x)

    def fn(a):
        sorted_a = jnp.sort(a, axis=axis)
        ax = axis if axis >= 0 else a.ndim + axis
        n = a.shape[ax]
        shape = [1] * a.ndim
        shape[ax] = n
        arange = jnp.arange(n).reshape(shape)
        # run-length on sorted values: position minus run-start index
        same = jnp.concatenate(
            [jnp.zeros_like(jnp.take(sorted_a, jnp.array([0]), axis=ax),
                            dtype=jnp.int32),
             (jnp.diff(sorted_a, axis=ax) == 0).astype(jnp.int32)],
            axis=ax)
        start_marker = jnp.where(same == 1, 0, arange)
        run_start = jax.lax.cummax(start_marker, axis=ax)
        run_len = arange - run_start + 1
        best = jnp.argmax(run_len, axis=ax, keepdims=True)
        vals = jnp.take_along_axis(sorted_a, best, axis=ax)
        if not keepdim:
            vals = jnp.squeeze(vals, axis=ax)
        return vals

    vals = run_op(fn, [t], name="mode")
    # indices: first occurrence of the modal value in the original order
    import numpy as _np

    idx = run_op(lambda a, v: jnp.argmax(
        a == (v if keepdim else jnp.expand_dims(v, axis)), axis=axis),
        [t, vals], name="mode_idx")
    return vals, idx


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    ts = [as_tensor(y)]
    if x is not None:
        ts.append(as_tensor(x))

        def fn(ya, xa):
            return jax.scipy.integrate.trapezoid(ya, xa, axis=axis)
    else:
        step = 1.0 if dx is None else dx

        def fn(ya):
            return jax.scipy.integrate.trapezoid(ya, dx=step, axis=axis)

    return run_op(fn, ts, name="trapezoid")


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    ts = [as_tensor(y)]
    step = 1.0 if dx is None else dx

    def fn(ya, *rest):
        ya_m = jnp.moveaxis(ya, axis, -1)
        if rest:
            xa = jnp.moveaxis(rest[0], axis, -1)
            d = jnp.diff(xa, axis=-1)
        else:
            d = step
        avg = (ya_m[..., 1:] + ya_m[..., :-1]) / 2.0
        out = jnp.cumsum(avg * d, axis=-1)
        return jnp.moveaxis(out, -1, axis)

    if x is not None:
        ts.append(as_tensor(x))
    return run_op(fn, ts, name="cumulative_trapezoid")


def renorm(x, p, axis, max_norm, name=None):
    def fn(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)

    return run_op(fn, [as_tensor(x)], name="renorm")


def frexp(x, name=None):
    t = as_tensor(x)
    # mantissa differentiable; exponent is integer (non-diff output is
    # fine: run_op only differentiates float cotangents of float outputs)
    m = run_op(lambda a: jnp.frexp(a)[0], [t], name="frexp")
    from ..core.tensor import Tensor

    e = Tensor(jnp.frexp(unwrap(t))[1])
    return m, e


def ldexp(x, y, name=None):
    return binary(lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)), x, y,
                  "ldexp")


def polar(abs, angle, name=None):
    return binary(lambda r, t: (r * jnp.cos(t)).astype(jnp.complex64)
                  + 1j * (r * jnp.sin(t)).astype(jnp.complex64),
                  abs, angle, "polar")


def as_complex(x, name=None):
    return run_op(lambda a: jax.lax.complex(a[..., 0], a[..., 1]),
                  [as_tensor(x)], name="as_complex")


def as_real(x, name=None):
    return run_op(lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1),
                  [as_tensor(x)], name="as_real")


def gammaln(x, name=None):
    return unary(jsp.gammaln, x, "gammaln")


def gammainc(x, y, name=None):
    return binary(jsp.gammainc, x, y, "gammainc")


def gammaincc(x, y, name=None):
    return binary(jsp.gammaincc, x, y, "gammaincc")


def i0(x, name=None):
    return unary(jsp.i0, x, "i0")


def i0e(x, name=None):
    return unary(jsp.i0e, x, "i0e")


def i1(x, name=None):
    return unary(jsp.i1, x, "i1")


def i1e(x, name=None):
    return unary(jsp.i1e, x, "i1e")


def sinc(x, name=None):
    return unary(jnp.sinc, x, "sinc")


def logit(x, eps=None, name=None):
    def fn(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1 - eps)
        return jnp.log(a) - jnp.log1p(-a)

    return run_op(fn, [as_tensor(x)], name="logit")


def polygamma(x, n, name=None):
    return run_op(lambda a: jsp.polygamma(n, a), [as_tensor(x)],
                  name="polygamma")


def isin(x, test_x, assume_unique=False, invert=False, name=None):
    from ..core.tensor import Tensor

    a = unwrap(as_tensor(x))
    b = unwrap(as_tensor(test_x))
    return Tensor(jnp.isin(a, b, invert=invert))


def vdot(x, y, name=None):
    return binary(lambda a, b: jnp.vdot(a, b), x, y, "vdot")


def baddbmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return run_op(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                  [as_tensor(input), as_tensor(x), as_tensor(y)],
                  name="baddbmm")


def masked_scatter(x, mask, value, name=None):
    mask_t = as_tensor(mask)
    value_t = as_tensor(value)
    if not isinstance(unwrap(mask_t), jax.core.Tracer):
        needed = int(jnp.sum(unwrap(mask_t).astype(jnp.int32)))
        if value_t.size < needed:
            raise ValueError(
                f"masked_scatter: value has {value_t.size} elements but "
                f"mask selects {needed}")

    def fn(a, m, v):
        flat_v = v.reshape(-1)
        m_b = m.astype(bool)
        # position of each True among the mask order
        pos = jnp.cumsum(m_b.reshape(-1)) - 1
        take = jnp.clip(pos, 0, flat_v.shape[0] - 1)
        cand = flat_v[take].reshape(a.shape)
        return jnp.where(m_b, cand, a)

    return run_op(fn, [as_tensor(x), mask_t, value_t],
                  name="masked_scatter")


def unfold(x, axis, size, step, name=None):
    """Sliding windows along ``axis`` (paddle.Tensor.unfold semantics):
    output adds a trailing window dim of length ``size``."""
    def _reorder(win, ndim, ax):
        # win: [n, size, rest...] where rest = dims except `ax`;
        # target: n back at position ax, window size last
        perm = []
        rest = list(range(2, win.ndim))
        ri = 0
        for d in range(ndim):
            if d == ax:
                perm.append(0)
            else:
                perm.append(rest[ri])
                ri += 1
        perm.append(1)
        return jnp.transpose(win, perm)

    def fn(a):
        ax = axis if axis >= 0 else a.ndim + axis
        n = (a.shape[ax] - size) // step + 1
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
        moved = jnp.moveaxis(a, ax, 0)
        win = moved[idx]                 # [n, size, rest...]
        return _reorder(win, a.ndim, ax)

    return run_op(fn, [as_tensor(x)], name="unfold")
