from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import run_op
from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor


def as_tensor(x) -> Tensor:
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def unwrap(x):
    if isinstance(x, Tensor):
        return x._data
    return x


def axis_arg(axis):
    """Normalize paddle-style axis arg (None | int | list | Tensor)."""
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = axis.tolist()
        return tuple(a) if isinstance(a, list) else int(a)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def shape_arg(shape):
    """Normalize paddle-style shape arg (list of ints, possibly Tensors)."""
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.tolist())
    if isinstance(shape, (list, tuple)):
        return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)
    return (int(shape),)


def unary(fn, x, name, attrs=None):
    return run_op(fn, [as_tensor(x)], name=name, attrs=attrs)


def binary(fn, x, y, name, attrs=None):
    return run_op(fn, [as_tensor(x), as_tensor(y)], name=name, attrs=attrs)


__all__ = ["as_tensor", "unwrap", "axis_arg", "shape_arg", "unary", "binary",
           "run_op", "to_jax_dtype", "Tensor", "jnp"]
