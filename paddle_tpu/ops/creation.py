"""Tensor creation ops (reference: python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dtype import int64_canonical, to_jax_dtype
from ..core.tensor import Tensor, to_tensor
from ._helpers import as_tensor, shape_arg, unwrap

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "diag",
    "diagflat",
    "tril",
    "triu",
    "meshgrid",
    "assign",
    "clone",
    "numel",
    "tolist",
]


def _dt(dtype, default="float32"):
    return to_jax_dtype(dtype if dtype is not None else default)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(shape_arg(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(shape_arg(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    fv = unwrap(fill_value)
    if dtype is None:
        return Tensor(jnp.full(shape_arg(shape), fv))
    return Tensor(jnp.full(shape_arg(shape), fv, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.zeros_like(x._data, dtype=to_jax_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.ones_like(x._data, dtype=to_jax_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    x = as_tensor(x)
    return Tensor(jnp.full_like(x._data, unwrap(fill_value), dtype=to_jax_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    start = unwrap(start)
    end = unwrap(end)
    step = unwrap(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(
            isinstance(v, (int, jnp.integer)) for v in (start, end, step)
        ) else "float32"
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(unwrap(start), unwrap(stop), int(unwrap(num)),
                               base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    x = as_tensor(x)
    from ..core.autograd import run_op

    if x.ndim == 1 and padding_value != 0:
        def fn(a):
            d = jnp.diag(a, k=offset)
            mask = jnp.eye(d.shape[0], d.shape[1], k=offset, dtype=bool)
            return jnp.where(mask, d, padding_value)

        return run_op(fn, [x], name="diag")
    return run_op(lambda a: jnp.diag(a, k=offset), [x], name="diag")


def diagflat(x, offset=0, name=None):
    from ..core.autograd import run_op

    return run_op(lambda a: jnp.diagflat(a, k=offset), [as_tensor(x)], name="diagflat")


def tril(x, diagonal=0, name=None):
    from ..core.autograd import run_op

    return run_op(lambda a: jnp.tril(a, k=diagonal), [as_tensor(x)], name="tril")


def triu(x, diagonal=0, name=None):
    from ..core.autograd import run_op

    return run_op(lambda a: jnp.triu(a, k=diagonal), [as_tensor(x)], name="triu")


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    arrays = [unwrap(as_tensor(a)) for a in args]
    outs = jnp.meshgrid(*arrays, indexing="ij")
    return [Tensor(o) for o in outs]


def assign(x, output=None):
    x = as_tensor(x)
    if output is not None:
        output._data = x._data
        return output
    return Tensor(x._data)


def clone(x, name=None):
    return as_tensor(x).clone()


def numel(x, name=None):
    return Tensor(jnp.asarray(as_tensor(x).size, dtype=int64_canonical()))


def tolist(x):
    return as_tensor(x).tolist()
