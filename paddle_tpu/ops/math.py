"""Math + reduction ops (reference: python/paddle/tensor/math.py, stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.dtype import int64_canonical
import jax.scipy.special as jsp

from ._helpers import as_tensor, axis_arg, binary, run_op, unary, unwrap

__all__ = [
    # elementwise unary
    "abs", "sign", "sqrt", "rsqrt", "square", "exp", "expm1", "log", "log2",
    "log10", "log1p", "reciprocal", "floor", "ceil", "round", "trunc", "frac",
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "tanh",
    "asinh", "acosh", "atanh", "erf", "erfinv", "neg", "digamma", "lgamma",
    "angle", "conj", "real", "imag",
    # elementwise binary
    "add", "subtract", "multiply", "divide", "floor_divide", "mod", "remainder",
    "pow", "maximum", "minimum", "fmax", "fmin", "atan2", "hypot",
    "logaddexp", "heaviside", "nextafter", "copysign", "gcd", "lcm",
    # ternary / other
    "clip", "lerp", "addmm", "scale", "stanh", "multiplex", "nan_to_num",
    # reductions
    "sum", "mean", "max", "min", "prod", "std", "var", "median", "nanmedian",
    "nansum", "nanmean", "amax", "amin", "logsumexp", "all", "any", "count_nonzero",
    # cumulative
    "cumsum", "cumprod", "cummax", "cummin", "diff",
    # misc
    "isnan", "isinf", "isfinite", "inner", "outer", "trace", "kron",
    "increment", "accuracy",
]


def _u(fn, op_name):
    # attrs={} marks these as attr-FREE by construction, which is what
    # lets attr-free decomposition rules fire on them (attrs=None means
    # "unknown closure attrs" and blocks decomposition)
    def op(x, name=None):
        return unary(fn, x, op_name, attrs={})

    op.__name__ = op_name
    return op


abs = _u(jnp.abs, "abs")
sign = _u(jnp.sign, "sign")
sqrt = _u(jnp.sqrt, "sqrt")
rsqrt = _u(lambda x: 1.0 / jnp.sqrt(x), "rsqrt")
square = _u(jnp.square, "square")
exp = _u(jnp.exp, "exp")
expm1 = _u(jnp.expm1, "expm1")
log = _u(jnp.log, "log")
log2 = _u(jnp.log2, "log2")
log10 = _u(jnp.log10, "log10")
log1p = _u(jnp.log1p, "log1p")
reciprocal = _u(jnp.reciprocal, "reciprocal")
floor = _u(jnp.floor, "floor")
ceil = _u(jnp.ceil, "ceil")
round = _u(jnp.round, "round")
trunc = _u(jnp.trunc, "trunc")
frac = _u(lambda x: x - jnp.trunc(x), "frac")
sin = _u(jnp.sin, "sin")
cos = _u(jnp.cos, "cos")
tan = _u(jnp.tan, "tan")
asin = _u(jnp.arcsin, "asin")
acos = _u(jnp.arccos, "acos")
atan = _u(jnp.arctan, "atan")
sinh = _u(jnp.sinh, "sinh")
cosh = _u(jnp.cosh, "cosh")
tanh = _u(jnp.tanh, "tanh")
asinh = _u(jnp.arcsinh, "asinh")
acosh = _u(jnp.arccosh, "acosh")
atanh = _u(jnp.arctanh, "atanh")
erf = _u(jsp.erf, "erf")
erfinv = _u(jsp.erfinv, "erfinv")
neg = _u(jnp.negative, "neg")
digamma = _u(jsp.digamma, "digamma")
lgamma = _u(jsp.gammaln, "lgamma")
angle = _u(jnp.angle, "angle")
conj = _u(jnp.conj, "conj")
real = _u(jnp.real, "real")
imag = _u(jnp.imag, "imag")


def _b(fn, op_name):
    def op(x, y, name=None):
        return binary(fn, x, y, op_name)

    op.__name__ = op_name
    return op


add = _b(jnp.add, "add")
subtract = _b(jnp.subtract, "subtract")
multiply = _b(jnp.multiply, "multiply")
divide = _b(jnp.true_divide, "divide")
floor_divide = _b(jnp.floor_divide, "floor_divide")
mod = _b(jnp.mod, "mod")
remainder = mod
pow = _b(jnp.power, "pow")
maximum = _b(jnp.maximum, "maximum")
minimum = _b(jnp.minimum, "minimum")
fmax = _b(jnp.fmax, "fmax")
fmin = _b(jnp.fmin, "fmin")
atan2 = _b(jnp.arctan2, "atan2")
hypot = _b(jnp.hypot, "hypot")
logaddexp = _b(jnp.logaddexp, "logaddexp")
heaviside = _b(jnp.heaviside, "heaviside")
nextafter = _b(jnp.nextafter, "nextafter")
copysign = _b(jnp.copysign, "copysign")
gcd = _b(jnp.gcd, "gcd")
lcm = _b(jnp.lcm, "lcm")


def clip(x, min=None, max=None, name=None):
    mn = unwrap(min) if min is not None else None
    mx = unwrap(max) if max is not None else None
    return unary(lambda a: jnp.clip(a, mn, mx), x, "clip",
                 attrs={"min": mn, "max": mx})


def lerp(x, y, weight, name=None):
    w = unwrap(weight)
    return run_op(lambda a, b: a + w * (b - a), [as_tensor(x), as_tensor(y)], name="lerp")


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return run_op(
        lambda i, a, b: beta * i + alpha * (a @ b),
        [as_tensor(input), as_tensor(x), as_tensor(y)],
        name="addmm",
    )


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = unwrap(scale), unwrap(bias)
    sc_attrs = {"scale": s, "bias": b,
                "bias_after_scale": bias_after_scale}
    if bias_after_scale:
        out = unary(lambda a: a * s + b, x, "scale", attrs=sc_attrs)
    else:
        out = unary(lambda a: (a + b) * s, x, "scale", attrs=sc_attrs)
    return out


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return unary(lambda a: scale_b * jnp.tanh(scale_a * a), x, "stanh")


def multiplex(inputs, index, name=None):
    idx = unwrap(as_tensor(index)).reshape(-1)
    ts = [as_tensor(t) for t in inputs]
    return run_op(
        lambda *arrs: jnp.stack(arrs, 0)[idx, jnp.arange(arrs[0].shape[0])],
        ts,
        name="multiplex",
    )


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return unary(lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf),
                 x, "nan_to_num")


# ------------------------------------------------------------------ reductions
def _red(fn, op_name, bool_out=False):
    def op(x, axis=None, keepdim=False, name=None):
        ax = axis_arg(axis)
        return unary(lambda a: fn(a, axis=ax, keepdims=keepdim), x, op_name,
                     attrs={"axis": ax, "keepdim": keepdim})

    op.__name__ = op_name
    return op


sum = _red(jnp.sum, "sum")
mean = _red(jnp.mean, "mean")
prod = _red(jnp.prod, "prod")
amax = _red(jnp.max, "amax")
amin = _red(jnp.min, "amin")
nansum = _red(jnp.nansum, "nansum")
nanmean = _red(jnp.nanmean, "nanmean")
all = _red(jnp.all, "all")
any = _red(jnp.any, "any")


def max(x, axis=None, keepdim=False, name=None):
    return unary(lambda a: jnp.max(a, axis=axis_arg(axis), keepdims=keepdim), x, "max")


def min(x, axis=None, keepdim=False, name=None):
    return unary(lambda a: jnp.min(a, axis=axis_arg(axis), keepdims=keepdim), x, "min")


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return unary(lambda a: jnp.std(a, axis=axis_arg(axis), ddof=ddof,
                                   keepdims=keepdim), x, "std",
                 attrs={"axis": axis_arg(axis), "ddof": ddof,
                        "keepdim": keepdim})


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ddof = 1 if unbiased else 0
    return unary(lambda a: jnp.var(a, axis=axis_arg(axis), ddof=ddof,
                                   keepdims=keepdim), x, "var",
                 attrs={"axis": axis_arg(axis), "ddof": ddof,
                        "keepdim": keepdim})


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return unary(lambda a: jnp.median(a, axis=axis_arg(axis), keepdims=keepdim),
                 x, "median")


def nanmedian(x, axis=None, keepdim=False, name=None):
    return unary(lambda a: jnp.nanmedian(a, axis=axis_arg(axis), keepdims=keepdim),
                 x, "nanmedian")


def logsumexp(x, axis=None, keepdim=False, name=None):
    return unary(lambda a: jsp.logsumexp(a, axis=axis_arg(axis), keepdims=keepdim),
                 x, "logsumexp")


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return unary(lambda a: jnp.count_nonzero(a, axis=axis_arg(axis),
                                             keepdims=keepdim), x, "count_nonzero")


# ------------------------------------------------------------------ cumulative
def cumsum(x, axis=None, dtype=None, name=None):
    ax = axis_arg(axis)
    return unary(lambda a: jnp.cumsum(a.reshape(-1) if ax is None else a,
                                      axis=0 if ax is None else ax), x, "cumsum")


def cumprod(x, dim=None, dtype=None, name=None):
    ax = axis_arg(dim)
    return unary(lambda a: jnp.cumprod(a.reshape(-1) if ax is None else a,
                                       axis=0 if ax is None else ax), x, "cumprod")


def _cum_extreme(x, axis, is_max, name):
    """Cumulative max/min returning (values, running argindex), via ONE pair
    associative scan — O(log n) depth, TPU-friendly (no serial loop)."""
    from ..core.tensor import Tensor
    import jax.lax as lax

    x = as_tensor(x)
    ax = axis_arg(axis)
    xx = x if ax is not None else x.reshape([-1])
    ax0 = ax if ax is not None else 0
    n = xx._data.shape[ax0]
    idx_shape = [1] * xx._data.ndim
    idx_shape[ax0] = n

    def combine(l, r):
        lv, li = l
        rv, ri = r
        keep_l = lv > rv if is_max else lv < rv
        return jnp.where(keep_l, lv, rv), jnp.where(keep_l, li, ri)

    def fn(a):
        idx0 = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32).reshape(idx_shape), a.shape)
        vals, idx = lax.associative_scan(combine, (a, idx0), axis=ax0)
        return vals, idx.astype(int64_canonical())

    out, idx = run_op(fn, [xx], name=name)
    return out, idx.detach()


def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, True, "cummax")


def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, False, "cummin")


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = unwrap(prepend) if prepend is not None else None
    app = unwrap(append) if append is not None else None
    return unary(lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
                 x, "diff")


# ------------------------------------------------------------------ predicates
isnan = _u(jnp.isnan, "isnan")
isinf = _u(jnp.isinf, "isinf")
isfinite = _u(jnp.isfinite, "isfinite")


def inner(x, y, name=None):
    return binary(jnp.inner, x, y, "inner")


def outer(x, y, name=None):
    return binary(lambda a, b: jnp.outer(a, b), x, y, "outer")


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return unary(lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2),
                 x, "trace")


def kron(x, y, name=None):
    return binary(jnp.kron, x, y, "kron")


def increment(x, value=1.0, name=None):
    x._data = x._data + value
    return x


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy metric (reference: python/paddle/static/nn/metric.py)."""
    from ..core.tensor import Tensor

    inp = unwrap(as_tensor(input))
    lab = unwrap(as_tensor(label)).reshape(-1)
    topk_idx = jnp.argsort(-inp, axis=-1)[:, :k]
    correct_mask = (topk_idx == lab[:, None]).any(axis=-1)
    return Tensor(correct_mask.mean(dtype=jnp.float32))
