"""Linear algebra ops (reference: python/paddle/tensor/linalg.py).

matmul is THE op on TPU — it is the one that lands on the MXU. Everything here
lowers to jnp/lax dot_general so XLA can tile it onto the systolic array.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import as_tensor, axis_arg, binary, run_op, unary, unwrap

__all__ = [
    "matmul", "bmm", "mm", "mv", "dot", "t", "norm", "dist", "cross",
    "histogramdd", "einsum", "multi_dot", "matrix_power", "cov", "corrcoef",
    "cholesky", "qr", "svd", "pinv", "inv", "solve", "triangular_solve",
    "lstsq", "eig", "eigh", "eigvals", "eigvalsh", "det", "slogdet",
    "matrix_rank", "lu", "cholesky_solve", "matrix_transpose", "cdist",
    "householder_product", "pca_lowrank", "vander", "cond",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return binary(fn, x, y, "matmul")


def bmm(x, y, name=None):
    return binary(jnp.matmul, x, y, "bmm")


mm = matmul


def mv(x, vec, name=None):
    return binary(jnp.matmul, x, vec, "mv")


def dot(x, y, name=None):
    def fn(a, b):
        if a.ndim == 2:
            return jnp.sum(a * b, axis=-1)
        return jnp.dot(a, b)

    return binary(fn, x, y, "dot")


def t(input, name=None):
    return unary(lambda a: a.T, input, "t")


def matrix_transpose(x, name=None):
    return unary(lambda a: jnp.swapaxes(a, -1, -2), x, "matrix_transpose")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    ax = axis_arg(axis)

    def fn(a):
        if p is None or p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(a * a))
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p == "nuc":
            return jnp.sum(jnp.linalg.svd(a, compute_uv=False), axis=-1)
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return unary(fn, x, "norm")


def dist(x, y, p=2, name=None):
    return run_op(lambda a, b: _pnorm(a - b, p), [as_tensor(x), as_tensor(y)],
                  name="dist")


def _pnorm(d, p):
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None

    def fn(a, b):
        if ax is None:
            # first axis with dim 3 (paddle semantics)
            axis_ = next(i for i, s in enumerate(a.shape) if s == 3)
        else:
            axis_ = ax
        return jnp.cross(a, b, axis=axis_)

    return binary(fn, x, y, "cross")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    import numpy as np

    a = np.asarray(unwrap(as_tensor(x)))
    w = np.asarray(unwrap(as_tensor(weights))) if weights is not None else None
    hist, edges = np.histogramdd(a, bins=bins, range=ranges, density=density,
                                 weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


def einsum(equation, *operands):
    ts = [as_tensor(o) for o in operands]
    return run_op(lambda *arrs: jnp.einsum(equation, *arrs), ts, name="einsum")


def multi_dot(x, name=None):
    ts = [as_tensor(o) for o in x]
    return run_op(lambda *arrs: jnp.linalg.multi_dot(arrs), ts, name="multi_dot")


def matrix_power(x, n, name=None):
    return unary(lambda a: jnp.linalg.matrix_power(a, n), x, "matrix_power")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = unwrap(as_tensor(fweights)) if fweights is not None else None
    aw = unwrap(as_tensor(aweights)) if aweights is not None else None
    return unary(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), x, "cov")


def corrcoef(x, rowvar=True, name=None):
    return unary(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, "corrcoef")


def cholesky(x, upper=False, name=None):
    def fn(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l

    return unary(fn, x, "cholesky")


def qr(x, mode="reduced", name=None):
    x = as_tensor(x)
    outs = run_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), [x], name="qr")
    return outs


def svd(x, full_matrices=False, name=None):
    x = as_tensor(x)
    return run_op(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        [x], name="svd",
    )


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return unary(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                 x, "pinv")


def inv(x, name=None):
    return unary(jnp.linalg.inv, x, "inv")


def solve(x, y, name=None):
    return binary(jnp.linalg.solve, x, y, "solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax.scipy.linalg as jsl

    def fn(a, b):
        return jsl.solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0,
                                    unit_diagonal=unitriangular)

    return binary(fn, x, y, "triangular_solve")


def cholesky_solve(x, y, upper=False, name=None):
    import jax.scipy.linalg as jsl

    def fn(b, l):
        return jsl.cho_solve((l, not upper), b)

    return binary(fn, x, y, "cholesky_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = as_tensor(x), as_tensor(y)
    sol, res, rank, sv = jnp.linalg.lstsq(x._data, y._data, rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv))


def eig(x, name=None):
    import numpy as np

    a = np.asarray(unwrap(as_tensor(x)))
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    x = as_tensor(x)
    return run_op(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), [x], name="eigh")


def eigvals(x, name=None):
    import numpy as np

    a = np.asarray(unwrap(as_tensor(x)))
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def eigvalsh(x, UPLO="L", name=None):
    return unary(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x, "eigvalsh")


def det(x, name=None):
    return unary(jnp.linalg.det, x, "det")


def slogdet(x, name=None):
    x = as_tensor(x)
    return run_op(lambda a: tuple(jnp.linalg.slogdet(a)), [x], name="slogdet")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.linalg.matrix_rank(x._data, rtol=tol))


def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl

    x = as_tensor(x)
    lu_, piv = jsl.lu_factor(x._data)
    if get_infos:
        info = jnp.zeros((), dtype=jnp.int32)
        return Tensor(lu_), Tensor(piv.astype(jnp.int32) + 1), Tensor(info)
    return Tensor(lu_), Tensor(piv.astype(jnp.int32) + 1)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def fn(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(d * d, axis=-1), 0))
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)

    return binary(fn, x, y, "cdist")


def householder_product(x, tau, name=None):
    def fn(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() \
            if a.ndim > 2 else q
        for i in range(t_.shape[-1]):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0)
            ti = t_[..., i]
            h = jnp.eye(m, dtype=a.dtype) - ti[..., None, None] * (
                v[..., :, None] * v[..., None, :]
            )
            q = q @ h
        return q[..., :, :n]

    return run_op(fn, [as_tensor(x), as_tensor(tau)], name="householder_product")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = as_tensor(x)
    a = x._data
    qq = q if q is not None else min(6, a.shape[-2], a.shape[-1])
    if center:
        a = a - a.mean(axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return Tensor(u[..., :qq]), Tensor(s[..., :qq]), \
        Tensor(jnp.swapaxes(vt, -1, -2)[..., :qq])


def vander(x, n=None, increasing=False, name=None):
    return unary(lambda a: jnp.vander(a, N=n, increasing=increasing), x, "vander")


def cond(x, p=None, name=None):
    """Condition number (reference: paddle.linalg.cond)."""
    def fn(a):
        if p is None or p == 2:
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., 0] / s[..., -1]
        return jnp.linalg.norm(a, ord=p, axis=(-2, -1)) * \
            jnp.linalg.norm(jnp.linalg.inv(a), ord=p, axis=(-2, -1))

    return run_op(fn, [as_tensor(x)], name="cond")
