"""Linear algebra ops (reference: python/paddle/tensor/linalg.py).

matmul is THE op on TPU — it is the one that lands on the MXU. Everything here
lowers to jnp/lax dot_general so XLA can tile it onto the systolic array.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import as_tensor, axis_arg, binary, run_op, unary, unwrap

__all__ = [
    "matmul", "bmm", "mm", "mv", "dot", "t", "norm", "dist", "cross",
    "histogramdd", "einsum", "multi_dot", "matrix_power", "cov", "corrcoef",
    "cholesky", "qr", "svd", "pinv", "inv", "solve", "triangular_solve",
    "lstsq", "eig", "eigh", "eigvals", "eigvalsh", "det", "slogdet",
    "matrix_rank", "lu", "cholesky_solve", "matrix_transpose", "cdist",
    "householder_product", "pca_lowrank", "vander", "cond",
    "vector_norm", "matrix_norm", "cholesky_inverse", "matrix_exp",
    "lu_unpack", "ormqr", "svd_lowrank", "fp8_fp8_half_gemm_fused",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return binary(fn, x, y, "matmul")


def bmm(x, y, name=None):
    return binary(jnp.matmul, x, y, "bmm")


mm = matmul


def mv(x, vec, name=None):
    return binary(jnp.matmul, x, vec, "mv")


def dot(x, y, name=None):
    def fn(a, b):
        if a.ndim == 2:
            return jnp.sum(a * b, axis=-1)
        return jnp.dot(a, b)

    return binary(fn, x, y, "dot")


def t(input, name=None):
    return unary(lambda a: a.T, input, "t")


def matrix_transpose(x, name=None):
    return unary(lambda a: jnp.swapaxes(a, -1, -2), x, "matrix_transpose")


def norm(x, p=None, axis=None, keepdim=False, name=None):
    ax = axis_arg(axis)

    def fn(a):
        if p is None or p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(a * a))
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p == "nuc":
            return jnp.sum(jnp.linalg.svd(a, compute_uv=False), axis=-1)
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return unary(fn, x, "norm")


def dist(x, y, p=2, name=None):
    return run_op(lambda a, b: _pnorm(a - b, p), [as_tensor(x), as_tensor(y)],
                  name="dist")


def _pnorm(d, p):
    if p == float("inf"):
        return jnp.max(jnp.abs(d))
    if p == float("-inf"):
        return jnp.min(jnp.abs(d))
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    return jnp.sum(jnp.abs(d) ** p) ** (1.0 / p)


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else None

    def fn(a, b):
        if ax is None:
            # first axis with dim 3 (paddle semantics)
            axis_ = next(i for i, s in enumerate(a.shape) if s == 3)
        else:
            axis_ = ax
        return jnp.cross(a, b, axis=axis_)

    return binary(fn, x, y, "cross")


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    import numpy as np

    a = np.asarray(unwrap(as_tensor(x)))
    w = np.asarray(unwrap(as_tensor(weights))) if weights is not None else None
    hist, edges = np.histogramdd(a, bins=bins, range=ranges, density=density,
                                 weights=w)
    return Tensor(jnp.asarray(hist)), [Tensor(jnp.asarray(e)) for e in edges]


def einsum(equation, *operands):
    ts = [as_tensor(o) for o in operands]
    return run_op(lambda *arrs: jnp.einsum(equation, *arrs), ts, name="einsum")


def multi_dot(x, name=None):
    ts = [as_tensor(o) for o in x]
    return run_op(lambda *arrs: jnp.linalg.multi_dot(arrs), ts, name="multi_dot")


def matrix_power(x, n, name=None):
    return unary(lambda a: jnp.linalg.matrix_power(a, n), x, "matrix_power")


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    fw = unwrap(as_tensor(fweights)) if fweights is not None else None
    aw = unwrap(as_tensor(aweights)) if aweights is not None else None
    return unary(lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0,
                                   fweights=fw, aweights=aw), x, "cov")


def corrcoef(x, rowvar=True, name=None):
    return unary(lambda a: jnp.corrcoef(a, rowvar=rowvar), x, "corrcoef")


def cholesky(x, upper=False, name=None):
    def fn(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l

    return unary(fn, x, "cholesky")


def qr(x, mode="reduced", name=None):
    x = as_tensor(x)
    outs = run_op(lambda a: tuple(jnp.linalg.qr(a, mode=mode)), [x], name="qr")
    return outs


def svd(x, full_matrices=False, name=None):
    x = as_tensor(x)
    return run_op(
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        [x], name="svd",
    )


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return unary(lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian),
                 x, "pinv")


def inv(x, name=None):
    return unary(jnp.linalg.inv, x, "inv")


def solve(x, y, name=None):
    return binary(jnp.linalg.solve, x, y, "solve")


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    import jax.scipy.linalg as jsl

    def fn(a, b):
        return jsl.solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0,
                                    unit_diagonal=unitriangular)

    return binary(fn, x, y, "triangular_solve")


def cholesky_solve(x, y, upper=False, name=None):
    import jax.scipy.linalg as jsl

    def fn(b, l):
        return jsl.cho_solve((l, not upper), b)

    return binary(fn, x, y, "cholesky_solve")


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = as_tensor(x), as_tensor(y)
    sol, res, rank, sv = jnp.linalg.lstsq(x._data, y._data, rcond=rcond)
    return (Tensor(sol), Tensor(res), Tensor(rank), Tensor(sv))


def eig(x, name=None):
    import numpy as np

    a = np.asarray(unwrap(as_tensor(x)))
    w, v = np.linalg.eig(a)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigh(x, UPLO="L", name=None):
    x = as_tensor(x)
    return run_op(lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), [x], name="eigh")


def eigvals(x, name=None):
    import numpy as np

    a = np.asarray(unwrap(as_tensor(x)))
    return Tensor(jnp.asarray(np.linalg.eigvals(a)))


def eigvalsh(x, UPLO="L", name=None):
    return unary(lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x, "eigvalsh")


def det(x, name=None):
    return unary(jnp.linalg.det, x, "det")


def slogdet(x, name=None):
    x = as_tensor(x)
    return run_op(lambda a: tuple(jnp.linalg.slogdet(a)), [x], name="slogdet")


def matrix_rank(x, tol=None, hermitian=False, name=None):
    x = as_tensor(x)
    return Tensor(jnp.linalg.matrix_rank(x._data, rtol=tol))


def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl

    x = as_tensor(x)
    lu_, piv = jsl.lu_factor(x._data)
    if get_infos:
        info = jnp.zeros((), dtype=jnp.int32)
        return Tensor(lu_), Tensor(piv.astype(jnp.int32) + 1), Tensor(info)
    return Tensor(lu_), Tensor(piv.astype(jnp.int32) + 1)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    def fn(a, b):
        d = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.maximum(jnp.sum(d * d, axis=-1), 0))
        return jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)

    return binary(fn, x, y, "cdist")


def householder_product(x, tau, name=None):
    def fn(a, t_):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        q = jnp.broadcast_to(q, a.shape[:-2] + (m, m)).copy() \
            if a.ndim > 2 else q
        for i in range(t_.shape[-1]):
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0)
            ti = t_[..., i]
            h = jnp.eye(m, dtype=a.dtype) - ti[..., None, None] * (
                v[..., :, None] * v[..., None, :]
            )
            q = q @ h
        return q[..., :, :n]

    return run_op(fn, [as_tensor(x), as_tensor(tau)], name="householder_product")


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    x = as_tensor(x)
    a = x._data
    qq = q if q is not None else min(6, a.shape[-2], a.shape[-1])
    if center:
        a = a - a.mean(axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return Tensor(u[..., :qq]), Tensor(s[..., :qq]), \
        Tensor(jnp.swapaxes(vt, -1, -2)[..., :qq])


def vander(x, n=None, increasing=False, name=None):
    return unary(lambda a: jnp.vander(a, N=n, increasing=increasing), x, "vander")


def cond(x, p=None, name=None):
    """Condition number (reference: paddle.linalg.cond)."""
    def fn(a):
        if p is None or p == 2:
            s = jnp.linalg.svd(a, compute_uv=False)
            return s[..., 0] / s[..., -1]
        return jnp.linalg.norm(a, ord=p, axis=(-2, -1)) * \
            jnp.linalg.norm(jnp.linalg.inv(a), ord=p, axis=(-2, -1))

    return run_op(fn, [as_tensor(x)], name="cond")


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    """reference: python/paddle/tensor/linalg.py vector_norm — always
    treats the input as (a batch of) vectors, flattening when axis=None."""
    ax = axis_arg(axis)

    def fn(a):
        # axis=None + keepdim must keep the input rank (all-ones shape),
        # so reduce over every axis instead of flattening
        v = a.reshape(-1) if ax is None and not keepdim else a
        axx = (tuple(range(a.ndim)) if keepdim else None) \
            if ax is None else ax
        if p == float("inf"):
            return jnp.max(jnp.abs(v), axis=axx, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(v), axis=axx, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(a.dtype), axis=axx,
                           keepdims=keepdim)
        return jnp.sum(jnp.abs(v) ** p, axis=axx,
                       keepdims=keepdim) ** (1.0 / p)

    return unary(fn, x, "vector_norm")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    """reference: python/paddle/tensor/linalg.py matrix_norm — operator
    norms over the trailing matrix axes (fro/nuc/±1/±2/±inf)."""
    ax = tuple(axis)

    def fn(a):
        mv_ = jnp.moveaxis(a, ax, (-2, -1))
        if p == "fro":
            out = jnp.sqrt(jnp.sum(mv_ * mv_, axis=(-2, -1)))
        elif p == "nuc":
            out = jnp.sum(jnp.linalg.svd(mv_, compute_uv=False), axis=-1)
        elif p in (2, 2.0):
            out = jnp.max(jnp.linalg.svd(mv_, compute_uv=False), axis=-1)
        elif p in (-2, -2.0):
            out = jnp.min(jnp.linalg.svd(mv_, compute_uv=False), axis=-1)
        elif p in (1, 1.0):
            out = jnp.max(jnp.sum(jnp.abs(mv_), axis=-2), axis=-1)
        elif p in (-1, -1.0):
            out = jnp.min(jnp.sum(jnp.abs(mv_), axis=-2), axis=-1)
        elif p == float("inf"):
            out = jnp.max(jnp.sum(jnp.abs(mv_), axis=-1), axis=-1)
        elif p == float("-inf"):
            out = jnp.min(jnp.sum(jnp.abs(mv_), axis=-1), axis=-1)
        else:
            raise ValueError(f"matrix_norm: unsupported p={p!r}")
        if keepdim:
            out = jnp.expand_dims(out, ax)
        return out

    return unary(fn, x, "matrix_norm")


def cholesky_inverse(x, upper=False, name=None):
    """reference: python/paddle/tensor/linalg.py cholesky_inverse —
    inverse of A given its Cholesky factor, via two triangular solves."""
    def fn(L):
        import jax.scipy.linalg as jsl

        eye = jnp.eye(L.shape[-1], dtype=L.dtype)
        li = jsl.solve_triangular(L, eye, lower=not upper)
        return (jnp.swapaxes(li, -1, -2) @ li if not upper
                else li @ jnp.swapaxes(li, -1, -2))

    return unary(fn, x, "cholesky_inverse")


def matrix_exp(x, name=None):
    """reference: python/paddle/tensor/linalg.py matrix_exp:5205."""
    import jax.scipy.linalg as jsl

    return unary(jsl.expm, x, "matrix_exp")


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """reference: python/paddle/tensor/linalg.py lu_unpack — split packed
    LU into P (from 1-based pivot swaps), unit-lower L and upper U.
    Canonical implementation (ops/more.py re-exports it); handles any
    leading batch dims. Pivots ride run_op as a real input (not a baked
    closure constant), so static capture feeds them."""
    def fn(lu_, piv):
        import jax.lax as lax

        m, n = lu_.shape[-2], lu_.shape[-1]
        k = min(m, n)
        L = jnp.tril(lu_[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_.dtype)
        U = jnp.triu(lu_[..., :k, :])
        # pivots (1-based sequential swaps) -> permutation, vectorized
        # over any leading batch dims
        piv0 = piv.astype(jnp.int32) - 1

        def perm_of(p0):
            def body(i, pm):
                j = p0[i]
                pi, pj = pm[i], pm[j]
                pm = pm.at[i].set(pj)
                return pm.at[j].set(pi)

            return lax.fori_loop(0, p0.shape[0], body, jnp.arange(m))

        perm = jnp.vectorize(perm_of, signature="(k)->(m)")(piv0)
        P = jnp.swapaxes(jax.nn.one_hot(perm, m, dtype=lu_.dtype), -1, -2)
        return P, L, U

    return run_op(fn, [as_tensor(x), as_tensor(y)], name="lu_unpack")


def ormqr(x, tau, other, left=True, transpose=False, name=None):
    """reference: python/paddle/tensor/linalg.py ormqr — multiply `other`
    by Q from the householder factors WITHOUT forming Q: apply each
    reflector H_i = I - tau_i v_i v_i^T in sequence (rank-1 updates)."""
    def fn(a, t_, c):
        m = a.shape[-2]
        k = t_.shape[-1]
        idxs = range(k)
        # Q = H_0 H_1 ... H_{k-1}. Left-apply Q  -> reflectors in reverse;
        # left-apply Q^T -> forward; right-apply mirrors that.
        order = idxs if (left and transpose) or (not left and not transpose) \
            else reversed(idxs)
        for i in order:
            v = a[..., :, i]
            v = jnp.where(jnp.arange(m) < i, 0.0, v)
            v = v.at[..., i].set(1.0)
            ti = t_[..., i]
            if left:
                c = c - ti * v[..., :, None] * jnp.einsum(
                    "...m,...mk->...k", v, c)[..., None, :]
            else:
                c = c - ti * jnp.einsum(
                    "...km,...m->...k", c, v)[..., :, None] * v[..., None, :]
        return c

    return run_op(fn, [as_tensor(x), as_tensor(tau), as_tensor(other)],
                  name="ormqr")


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """reference: python/paddle/tensor/linalg.py svd_lowrank — randomized
    low-rank SVD (Halko et al. 2011): power-iterated range finder + small
    exact SVD. Matmul-dominated => MXU-friendly."""
    from ..core import random as _rng
    import jax

    x = as_tensor(x)

    def fn(a):
        m, n = a.shape[-2], a.shape[-1]
        qq = min(q, m, n)
        ar = a if M is None else a - M
        omega = jax.random.normal(_rng.next_key(), a.shape[:-2] + (n, qq),
                                  dtype=a.dtype)
        y = ar @ omega
        for _ in range(niter):
            y = ar @ (jnp.swapaxes(ar, -1, -2) @ y)
        Q, _ = jnp.linalg.qr(y)
        b = jnp.swapaxes(Q, -1, -2) @ ar
        u, s, vh = jnp.linalg.svd(b, full_matrices=False)
        return Q @ u, s, jnp.swapaxes(vh, -1, -2)

    u, s, v = fn(x._data)
    return Tensor(u), Tensor(s), Tensor(v)


def fp8_fp8_half_gemm_fused(x, y, transpose_x=False, transpose_y=False,
                            bias=None, scale=1.0, output_dtype="bfloat16",
                            activation_type="identity", name=None):
    """reference: python/paddle/linalg.py fp8_fp8_half_gemm_fused (CUDA
    cublasLt fp8 gemm). TPU-native: cast to float8_e4m3fn and let XLA emit
    the native low-precision matmul, accumulating in the requested half
    dtype; bias/activation fuse into the epilogue."""
    from ..core.dtype import to_jax_dtype

    out_dt = to_jax_dtype(output_dtype)

    def fn(a, b, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2)
        a8 = a.astype(jnp.float8_e4m3fn)
        b8 = b.astype(jnp.float8_e4m3fn)
        out = jnp.matmul(a8, b8, preferred_element_type=jnp.float32)
        out = (out * scale).astype(out_dt)
        if rest:
            out = out + rest[0].astype(out_dt)
        if activation_type in ("gelu",):
            import jax.nn as jnn

            out = jnn.gelu(out)
        elif activation_type in ("relu",):
            out = jnp.maximum(out, 0)
        return out

    args = [as_tensor(x), as_tensor(y)]
    if bias is not None:
        args.append(as_tensor(bias))
    return run_op(fn, args, name="fp8_gemm")
