"""Random sampling ops (reference: python/paddle/tensor/random.py).

Functional JAX RNG under a stateful facade: every call splits the global key
(:mod:`paddle_tpu.core.random`), or folds a traced key inside jit.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core import random as _rng
from ..core.dtype import int64_canonical, to_jax_dtype
from ..core.tensor import Tensor
from ._helpers import as_tensor, shape_arg, unwrap

__all__ = [
    "bernoulli_", "log_normal_", "geometric_",
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "uniform_", "normal", "normal_", "standard_normal", "poisson",
    "bernoulli", "multinomial", "exponential_", "rand_like", "randn_like",
    "binomial", "log_normal", "cauchy_",
]


def _dt(dtype, default="float32", index=False):
    if index:
        # index-typed param (randint/randperm/randint_like): narrow without
        # consulting the strict flag — see core/dtype.py index_dtype
        from ..core.dtype import index_dtype
        return index_dtype(dtype if dtype is not None else default)
    return to_jax_dtype(dtype if dtype is not None else default)


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(_rng.next_key(), shape_arg(shape),
                                     dtype=_dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(_rng.next_key(), shape_arg(shape),
                                    dtype=_dt(dtype)))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_rng.next_key(), shape_arg(shape),
                                     int(low), int(high),
                                     dtype=_dt(dtype, "int64", index=True)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = as_tensor(x)
    if high is None:
        low, high = 0, low
    dt = _dt(dtype, None, index=True) or x._data.dtype
    return Tensor(jax.random.randint(_rng.next_key(), tuple(x.shape),
                                     int(low), int(high)).astype(dt))


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(_rng.next_key(), int(n))
                  .astype(_dt(dtype, "int64", index=True)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else _rng.next_key()
    return Tensor(jax.random.uniform(key, shape_arg(shape), dtype=_dt(dtype),
                                     minval=float(unwrap(min)),
                                     maxval=float(unwrap(max))))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    out = uniform(x.shape, dtype=x.dtype, min=min, max=max, seed=seed)
    x._data = out._data
    x._grad_node = None
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = unwrap(as_tensor(mean))
        s = unwrap(as_tensor(std))
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(m + s * jax.random.normal(_rng.next_key(), shp))
    shp = shape_arg(shape) if shape is not None else ()
    return Tensor(mean + std * jax.random.normal(_rng.next_key(), shp))


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = (mean + std * jax.random.normal(_rng.next_key(), tuple(x.shape))
               ).astype(x._data.dtype)
    x._grad_node = None
    return x


def poisson(x, name=None):
    lam = unwrap(as_tensor(x))
    return Tensor(jax.random.poisson(_rng.next_key(), lam).astype(lam.dtype))


def bernoulli(x, name=None):
    p = unwrap(as_tensor(x))
    return Tensor(jax.random.bernoulli(_rng.next_key(), p).astype(p.dtype))


def binomial(count, prob, name=None):
    n = unwrap(as_tensor(count))
    p = unwrap(as_tensor(prob))
    return Tensor(jax.random.binomial(_rng.next_key(), n, p).astype(int64_canonical()))


def multinomial(x, num_samples=1, replacement=False, name=None):
    probs = unwrap(as_tensor(x))
    key = _rng.next_key()
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1,
                                     shape=(num_samples,) + logits.shape[:-1])
        out = jnp.moveaxis(out, 0, -1) if logits.ndim > 1 else out
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, logits.shape)
        out = jnp.argsort(-(logits + g), axis=-1)
        out = out[..., :num_samples]
    return Tensor(out.astype(int64_canonical()))


def exponential_(x, lam=1.0, name=None):
    x._data = (jax.random.exponential(_rng.next_key(), tuple(x.shape)) / lam
               ).astype(x._data.dtype)
    x._grad_node = None
    return x


def bernoulli_(x, p=0.5, name=None):
    """In-place Bernoulli fill (reference: tensor/random.py bernoulli_)."""
    x._data = jax.random.bernoulli(
        _rng.next_key(), p, tuple(x.shape)).astype(x._data.dtype)
    x._grad_node = None
    return x


def log_normal_(x, mean=1.0, std=2.0, name=None):
    """In-place log-normal fill (reference: tensor/random.py)."""
    x._data = jnp.exp(mean + std * jax.random.normal(
        _rng.next_key(), tuple(x.shape))).astype(x._data.dtype)
    x._grad_node = None
    return x


def geometric_(x, probs=0.5, name=None):
    """In-place geometric fill (reference: tensor/random.py geometric_):
    number of Bernoulli(p) trials until the first success."""
    u = jax.random.uniform(_rng.next_key(), tuple(x.shape),
                           minval=1e-7, maxval=1.0)
    x._data = jnp.ceil(jnp.log(u) / jnp.log1p(-probs)).astype(
        x._data.dtype)
    x._grad_node = None
    return x


def rand_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return rand(x.shape, dtype=dtype or x.dtype)


def randn_like(x, dtype=None, name=None):
    x = as_tensor(x)
    return randn(x.shape, dtype=dtype or x.dtype)


def log_normal(mean=1.0, std=2.0, shape=None, name=None):
    return Tensor(jnp.exp(unwrap(normal(mean, std, shape))))


def cauchy_(x, loc=0, scale=1, name=None):
    u = jax.random.uniform(_rng.next_key(), tuple(x.shape))
    x._data = (loc + scale * jnp.tan(jnp.pi * (u - 0.5))).astype(x._data.dtype)
    x._grad_node = None
    return x
