"""Shape / layout / indexing ops (reference: python/paddle/tensor/manipulation.py,
search.py)."""
from __future__ import annotations

import builtins

import jax.numpy as jnp

from ..core.dtype import index_dtype, int64_canonical, to_jax_dtype
from ..core.tensor import Tensor
from ._helpers import as_tensor, axis_arg, run_op, shape_arg, unary, unwrap

__all__ = [
    "reshape", "reshape_", "transpose", "concat", "split", "chunk", "stack",
    "unstack", "squeeze", "unsqueeze", "flatten", "flip", "roll", "rot90",
    "tile", "expand", "expand_as", "broadcast_to", "broadcast_tensors",
    "gather", "gather_nd", "scatter", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put",
    "masked_select", "masked_fill", "where", "take_along_axis",
    "put_along_axis", "slice", "strided_slice", "unbind", "repeat_interleave",
    "topk", "sort", "argsort", "argmax", "argmin", "unique",
    "unique_consecutive", "nonzero", "cast", "shape", "shard_index",
    "moveaxis", "swapaxes", "as_strided", "view", "view_as", "tensordot",
    "searchsorted", "bucketize", "pad", "one_hot", "crop", "tril_indices",
    "triu_indices", "bincount", "histogram", "flatten_",
]


def reshape(x, shape, name=None):
    shp = shape_arg(shape) if not isinstance(shape, (list, tuple)) or not any(
        isinstance(s, Tensor) for s in shape
    ) else tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)
    return unary(lambda a: a.reshape(shp), x, "reshape")


def reshape_(x, shape, name=None):
    from .inplace import inplace_rebind
    return inplace_rebind(x, lambda alias: reshape(alias, shape))


def transpose(x, perm=None, name=None):
    if perm is not None:
        perm = tuple(int(p) for p in perm)
    return unary(lambda a: jnp.transpose(a, perm), x, "transpose")


def moveaxis(x, source, destination, name=None):
    return unary(lambda a: jnp.moveaxis(a, source, destination), x, "moveaxis")


def swapaxes(x, axis0, axis1, name=None):
    return unary(lambda a: jnp.swapaxes(a, axis0, axis1), x, "swapaxes")


def concat(x, axis=0, name=None):
    ts = [as_tensor(t) for t in x]
    ax = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)
    return run_op(lambda *arrs: jnp.concatenate(arrs, axis=ax), ts,
                  name="concat", attrs={"axis": ax})


def split(x, num_or_sections, axis=0, name=None):
    x = as_tensor(x)
    ax = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {ax} size {dim} is not divisible by "
                f"num_or_sections={num_or_sections}; pass explicit section "
                "sizes instead")
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) if not isinstance(s, Tensor) else int(s.item())
                 for s in num_or_sections]
        n_neg = builtins.sum(1 for s in sizes if s < 0)
        if n_neg:
            rem = dim - builtins.sum(s for s in sizes if s >= 0)
            sizes = [rem if s < 0 else s for s in sizes]
    offsets = []
    acc = 0
    for s in sizes[:-1]:
        acc += s
        offsets.append(acc)
    outs = run_op(
        lambda a: tuple(jnp.split(a, offsets, axis=ax)), [x], name="split"
    )
    return list(outs) if isinstance(outs, tuple) else [outs]


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def stack(x, axis=0, name=None):
    ts = [as_tensor(t) for t in x]
    return run_op(lambda *arrs: jnp.stack(arrs, axis=axis), ts,
                  name="stack", attrs={"axis": axis})


def unstack(x, axis=0, num=None, name=None):
    x = as_tensor(x)
    n = num if num is not None else x.shape[axis]
    outs = run_op(
        lambda a: tuple(jnp.squeeze(s, axis=axis)
                        for s in jnp.split(a, n, axis=axis)),
        [x], name="unstack",
    )
    return list(outs) if isinstance(outs, tuple) else [outs]


def squeeze(x, axis=None, name=None):
    ax = axis_arg(axis)
    if isinstance(ax, int):
        ax = (ax,)

    def fn(a):
        if ax is None:
            return jnp.squeeze(a)
        real_ax = tuple(i for i in ax if a.shape[i if i >= 0 else a.ndim + i] == 1)
        return jnp.squeeze(a, axis=real_ax) if real_ax else a

    return unary(fn, x, "squeeze", attrs={"axis": ax})


def unsqueeze(x, axis, name=None):
    ax = axis_arg(axis)
    if isinstance(ax, int):
        ax = (ax,)
    return unary(lambda a: jnp.expand_dims(a, ax), x, "unsqueeze",
                 attrs={"axis": ax})


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = as_tensor(x)
    nd = x.ndim
    s = start_axis if start_axis >= 0 else nd + start_axis
    e = stop_axis if stop_axis >= 0 else nd + stop_axis

    def fn(a):
        shp = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return a.reshape(shp)

    return unary(fn, x, "flatten", attrs={"start": s, "stop": e})


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    from .inplace import inplace_rebind
    return inplace_rebind(x, lambda alias: flatten(alias, start_axis, stop_axis))


def flip(x, axis, name=None):
    ax = axis_arg(axis)
    return unary(lambda a: jnp.flip(a, axis=ax), x, "flip")


def roll(x, shifts, axis=None, name=None):
    ax = axis_arg(axis)
    sh = shifts if not isinstance(shifts, Tensor) else tuple(shifts.tolist())
    return unary(lambda a: jnp.roll(a, sh, axis=ax), x, "roll")


def rot90(x, k=1, axes=(0, 1), name=None):
    return unary(lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x, "rot90")


def tile(x, repeat_times, name=None):
    rt = shape_arg(repeat_times)
    return unary(lambda a: jnp.tile(a, rt), x, "tile")


def expand(x, shape, name=None):
    shp = shape_arg(shape)
    x = as_tensor(x)

    def fn(a):
        tgt = list(shp)
        nd = len(tgt)
        src = (1,) * (nd - a.ndim) + a.shape
        for i, s in enumerate(tgt):
            if s == -1:
                tgt[i] = src[i]
        return jnp.broadcast_to(a.reshape(src), tuple(tgt))

    return unary(fn, x, "expand")


def expand_as(x, y, name=None):
    return expand(x, as_tensor(y).shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    ts = [as_tensor(t) for t in inputs]
    shp = jnp.broadcast_shapes(*[tuple(t.shape) for t in ts])
    return [expand(t, shp) for t in ts]


def gather(x, index, axis=0, name=None):
    idx = unwrap(as_tensor(index)).reshape(-1)
    ax = int(unwrap(axis)) if isinstance(axis, Tensor) else int(axis)
    return unary(lambda a: jnp.take(a, idx, axis=ax), x, "gather")


def gather_nd(x, index, name=None):
    idx = unwrap(as_tensor(index))

    def fn(a):
        last = idx.shape[-1]
        ii = tuple(idx[..., i] for i in range(last))
        return a[ii]

    return unary(fn, x, "gather_nd")


def scatter(x, index, updates, overwrite=True, name=None):
    idx = unwrap(as_tensor(index)).reshape(-1)

    def fn(a, u):
        if overwrite:
            return a.at[idx].set(u)
        # paddle scatter(overwrite=False): zero the rows then add
        zeroed = a.at[idx].set(jnp.zeros_like(u))
        return zeroed.at[idx].add(u)

    return run_op(fn, [as_tensor(x), as_tensor(updates)], name="scatter")


def scatter_nd(index, updates, shape, name=None):
    idx = unwrap(as_tensor(index))
    shp = shape_arg(shape)

    def fn(u):
        z = jnp.zeros(shp, dtype=u.dtype)
        last = idx.shape[-1]
        ii = tuple(idx[..., i] for i in range(last))
        return z.at[ii].add(u)

    return unary(fn, as_tensor(updates), "scatter_nd")


def scatter_nd_add(x, index, updates, name=None):
    idx = unwrap(as_tensor(index))

    def fn(a, u):
        last = idx.shape[-1]
        ii = tuple(idx[..., i] for i in range(last))
        return a.at[ii].add(u)

    return run_op(fn, [as_tensor(x), as_tensor(updates)], name="scatter_nd_add")


def index_select(x, index, axis=0, name=None):
    idx = unwrap(as_tensor(index)).reshape(-1)
    return unary(lambda a: jnp.take(a, idx, axis=axis), x, "index_select")


def index_sample(x, index, name=None):
    idx = unwrap(as_tensor(index))
    return unary(
        lambda a: jnp.take_along_axis(a, idx, axis=1), x, "index_sample"
    )


def index_add(x, index, axis, value, name=None):
    idx = unwrap(as_tensor(index)).reshape(-1)

    def fn(a, v):
        am = jnp.moveaxis(a, axis, 0)
        vm = jnp.moveaxis(v, axis, 0)
        return jnp.moveaxis(am.at[idx].add(vm), 0, axis)

    return run_op(fn, [as_tensor(x), as_tensor(value)], name="index_add")


def index_put(x, indices, value, accumulate=False, name=None):
    ii = tuple(unwrap(as_tensor(i)) for i in indices)

    def fn(a, v):
        return a.at[ii].add(v) if accumulate else a.at[ii].set(v)

    return run_op(fn, [as_tensor(x), as_tensor(value)], name="index_put")


def masked_select(x, mask, name=None):
    # dynamic output shape: host-sync (eager only, like reference CPU sync)
    x, m = as_tensor(x), unwrap(as_tensor(mask))
    import numpy as np

    data = np.asarray(x._data)[np.asarray(m)]
    return Tensor(jnp.asarray(data))


def masked_fill(x, mask, value, name=None):
    m = unwrap(as_tensor(mask))
    v = unwrap(value)
    return unary(lambda a: jnp.where(m, jnp.asarray(v, dtype=a.dtype), a),
                 x, "masked_fill")


def where(condition, x=None, y=None, name=None):
    cond = unwrap(as_tensor(condition))
    if x is None and y is None:
        return nonzero(Tensor(cond), as_tuple=True)
    return run_op(lambda a, b: jnp.where(cond, a, b),
                  [as_tensor(x), as_tensor(y)], name="where")


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    idx = unwrap(as_tensor(indices))
    return unary(lambda a: jnp.take_along_axis(a, idx, axis=axis), arr,
                 "take_along_axis")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    idx = unwrap(as_tensor(indices))

    def fn(a, v):
        vb = jnp.broadcast_to(v, idx.shape)
        if reduce == "assign":
            return jnp.put_along_axis(a, idx, vb, axis=axis, inplace=False)
        ax = axis % a.ndim
        grids = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        sel = tuple(idx if d == ax else grids[d] for d in range(a.ndim))
        if reduce == "add":
            return a.at[sel].add(vb)
        if reduce in ("mul", "multiply"):
            return a.at[sel].multiply(vb)
        raise ValueError(f"unsupported reduce {reduce}")

    return run_op(fn, [as_tensor(arr), as_tensor(values)], name="put_along_axis")


def slice(input, axes, starts, ends, name=None):
    x = as_tensor(input)
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]

    def fn(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e in zip(axes, starts, ends):
            sl[ax] = builtins.slice(s, e)
        return a[tuple(sl)]

    return unary(fn, x, "slice")


def strided_slice(x, axes, starts, ends, strides, name=None):
    def fn(a):
        sl = [builtins.slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            sl[ax] = builtins.slice(s, e, st)
        return a[tuple(sl)]

    return unary(fn, as_tensor(x), "strided_slice")


def unbind(input, axis=0, name=None):
    return unstack(input, axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    r = unwrap(repeats) if isinstance(repeats, Tensor) else repeats
    return unary(lambda a: jnp.repeat(a, r, axis=axis), x, "repeat_interleave")


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = as_tensor(x)
    kk = int(unwrap(k)) if isinstance(k, Tensor) else int(k)
    # one argsort; vals gathers through the tape (grad scatters to the
    # selected positions), idx stays off-tape as integer output
    src = x._data if largest else -x._data
    idx_arr = jnp.take(jnp.argsort(-src, axis=axis), jnp.arange(kk),
                       axis=axis)
    vals = run_op(lambda a: jnp.take_along_axis(a, idx_arr, axis=axis),
                  [x], name="topk")
    return vals, Tensor(idx_arr.astype(int64_canonical()))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def fn(a):
        s = jnp.sort(a, axis=axis, stable=stable)
        return jnp.flip(s, axis=axis) if descending else s

    return unary(fn, x, "sort")


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    x = as_tensor(x)
    s = jnp.argsort(x._data, axis=axis, stable=stable)
    if descending:
        s = jnp.flip(s, axis=axis)
    return Tensor(s.astype(int64_canonical()))


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)
    ax = axis_arg(axis)
    out = jnp.argmax(x._data, axis=ax, keepdims=keepdim if ax is not None else False)
    return Tensor(out.astype(index_dtype(dtype)))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    x = as_tensor(x)
    ax = axis_arg(axis)
    out = jnp.argmin(x._data, axis=ax, keepdims=keepdim if ax is not None else False)
    return Tensor(out.astype(index_dtype(dtype)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    # dynamic shape -> host computation (eager only)
    import numpy as np

    a = np.asarray(as_tensor(x)._data)
    res = np.unique(a, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    import numpy as np

    a = np.asarray(as_tensor(x)._data)
    if axis is None:
        a = a.reshape(-1)
        ax = 0
    else:
        ax = axis
    n = a.shape[ax]
    if n == 0:
        outs = (Tensor(jnp.asarray(a)),)
    else:
        am = np.moveaxis(a, ax, 0).reshape(n, -1)
        neq = (am[1:] != am[:-1]).any(axis=1)
        keep = np.concatenate([[True], neq])
        out = np.compress(keep, a, axis=ax)
        outs = (Tensor(jnp.asarray(out)),)
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs += (Tensor(jnp.asarray(inv.astype(np.int64))),)
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, n))
            outs += (Tensor(jnp.asarray(counts.astype(np.int64))),)
    return outs if len(outs) > 1 else outs[0]


def nonzero(x, as_tuple=False, name=None):
    import numpy as np

    a = np.asarray(as_tensor(x)._data)
    nz = np.nonzero(a)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.astype(np.int64))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def cast(x, dtype):
    return as_tensor(x).astype(dtype)


def shape(input):
    return Tensor(jnp.asarray(as_tensor(input).shape, dtype=jnp.int32))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    shard_size = (index_num + nshards - 1) // nshards

    def fn(a):
        sid = a // shard_size
        local = a % shard_size
        return jnp.where(sid == shard_id, local, ignore_value)

    return unary(fn, as_tensor(input), "shard_index")


def as_strided(x, shape, stride, offset=0, name=None):
    import numpy as np

    a = np.asarray(as_tensor(x)._data).reshape(-1)
    itemsize = a.itemsize
    out = np.lib.stride_tricks.as_strided(
        a[offset:], shape=tuple(shape), strides=tuple(s * itemsize for s in stride)
    )
    return Tensor(jnp.asarray(out.copy()))


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return as_tensor(x).astype(shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, as_tensor(other).shape)


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    return run_op(lambda a, b: jnp.tensordot(a, b, axes=ax),
                  [as_tensor(x), as_tensor(y)], name="tensordot")


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    ss = unwrap(as_tensor(sorted_sequence))
    v = unwrap(as_tensor(values))
    side = "right" if right else "left"
    if ss.ndim == 1:
        out = jnp.searchsorted(ss, v, side=side)
    else:
        import jax

        out = jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
            ss.reshape(-1, ss.shape[-1]), v.reshape(-1, v.shape[-1])
        ).reshape(v.shape)
    return Tensor(out.astype(jnp.int32 if out_int32 else int64_canonical()))


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def pad(x, pad, mode="constant", value=0.0, data_format="NCDHW", name=None):
    x = as_tensor(x)
    if isinstance(pad, int):
        # int padding pads every spatial dim on both sides (reference
        # nn/functional/common.py pad)
        p = [pad] * (2 * builtins.max(x.ndim - 2, 1))
    else:
        p = shape_arg(pad) if not isinstance(pad, (list, tuple)) else [
            int(unwrap(v)) for v in pad
        ]

    def fn(a):
        nd = a.ndim
        if len(p) == 2 * nd:
            width = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # paddle NCHW-style: pad applies to trailing spatial dims, given
            # as [left, right, top, bottom, ...] over last len(p)//2 dims
            # (reversed order: last dim first)
            k = len(p) // 2
            width = [(0, 0)] * (nd - k) + [
                (p[2 * (k - 1 - i)], p[2 * (k - 1 - i) + 1]) for i in range(k)
            ]
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, width, mode=jmode, constant_values=value)
        return jnp.pad(a, width, mode=jmode)

    return unary(fn, x, "pad")


def one_hot(x, num_classes, name=None):
    import jax.nn

    return unary(lambda a: jax.nn.one_hot(a, num_classes, dtype=jnp.float32),
                 as_tensor(x), "one_hot")


def crop(x, shape=None, offsets=None, name=None):
    x = as_tensor(x)
    shp = shape_arg(shape)
    offs = [0] * x.ndim if offsets is None else [int(unwrap(o)) for o in offsets]

    def fn(a):
        sl = tuple(builtins.slice(o, o + (s if s != -1 else a.shape[i] - o))
                   for i, (o, s) in enumerate(zip(offs, shp)))
        return a[sl]

    return unary(fn, x, "crop")


def tril_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(index_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    col = col if col is not None else row
    r, c = jnp.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r, c]).astype(index_dtype(dtype)))


def bincount(x, weights=None, minlength=0, name=None):
    w = unwrap(as_tensor(weights)) if weights is not None else None
    a = unwrap(as_tensor(x))
    import numpy as np

    out = np.bincount(np.asarray(a), weights=np.asarray(w) if w is not None else None,
                      minlength=minlength)
    return Tensor(jnp.asarray(out))


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    import numpy as np

    a = np.asarray(unwrap(as_tensor(input)))
    rng = None if (min == 0 and max == 0) else (min, max)
    hist, _ = np.histogram(a, bins=bins, range=rng,
                           weights=np.asarray(unwrap(as_tensor(weight)))
                           if weight is not None else None, density=density)
    return Tensor(jnp.asarray(hist))
