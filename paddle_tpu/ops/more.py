"""Round-2 op-surface expansion (reference: python/paddle/tensor/
{math,manipulation,creation,linalg,logic,search,attribute,einsum}.py —
the long tail VERDICT r1 flagged: stack/split variants, *_scatter views,
signal/attribute helpers, matrix functions, sampling)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy import special as jsp

from ..core import random as _rng
from ..core.tensor import Tensor
from ._helpers import as_tensor, run_op, unwrap

__all__ = [
    "add_n", "atleast_1d", "atleast_2d", "atleast_3d", "bitwise_invert",
    "block_diag", "broadcast_shape", "cartesian_prod", "cholesky_inverse",
    "column_stack", "combinations", "complex", "deg2rad", "rad2deg",
    "diag_embed", "diagonal_scatter", "dsplit", "hsplit", "vsplit",
    "tensor_split", "dstack", "hstack", "vstack", "row_stack",
    "fill_constant", "fill_diagonal_tensor", "gaussian",
    "histogram_bin_edges", "index_fill", "inverse", "is_complex",
    "is_floating_point", "is_integer", "isneginf", "isposinf", "isreal",
    "kthvalue", "lu_unpack", "matrix_exp", "matrix_norm", "multigammaln",
    "positive", "rank", "reduce_as", "select_scatter", "sgn", "signbit",
    "slice_scatter", "standard_gamma", "svd_lowrank", "take",
    "top_p_sampling", "unflatten", "vector_norm", "create_tensor",
    "sigmoid",
]


# --------------------------------------------------------------- stacking
def add_n(inputs, name=None):
    """reference: math.py add_n — elementwise sum of a tensor list."""
    ts = [as_tensor(t) for t in (inputs if isinstance(inputs, (list, tuple))
                                 else [inputs])]
    return run_op(lambda *arrs: sum(arrs[1:], arrs[0]), ts, name="add_n")


def _atleast(nd):
    def op(*inputs, name=None):
        outs = []
        for t in inputs:
            fn = {1: jnp.atleast_1d, 2: jnp.atleast_2d,
                  3: jnp.atleast_3d}[nd]
            outs.append(run_op(fn, [as_tensor(t)], name=f"atleast_{nd}d"))
        return outs[0] if len(outs) == 1 else outs
    return op


atleast_1d = _atleast(1)
atleast_2d = _atleast(2)
atleast_3d = _atleast(3)


def block_diag(inputs, name=None):
    ts = [as_tensor(t) for t in inputs]
    return run_op(lambda *arrs: jax.scipy.linalg.block_diag(*arrs), ts,
                  name="block_diag")


def column_stack(x, name=None):
    ts = [as_tensor(t) for t in x]
    return run_op(lambda *arrs: jnp.column_stack(arrs), ts,
                  name="column_stack")


def dstack(x, name=None):
    ts = [as_tensor(t) for t in x]
    return run_op(lambda *arrs: jnp.dstack(arrs), ts, name="dstack")


def hstack(x, name=None):
    ts = [as_tensor(t) for t in x]
    return run_op(lambda *arrs: jnp.hstack(arrs), ts, name="hstack")


def vstack(x, name=None):
    ts = [as_tensor(t) for t in x]
    return run_op(lambda *arrs: jnp.vstack(arrs), ts, name="vstack")


row_stack = vstack


# ---------------------------------------------------------------- splits
def _split_along(x, indices_or_sections, axis, name):
    t = as_tensor(x)
    n = t.shape[axis] if axis < t.ndim else 0
    if isinstance(indices_or_sections, int):
        k = indices_or_sections
        # tensor_split semantics: first n % k pieces get one extra element
        base, extra = divmod(n, k)
        sizes = [base + (1 if i < extra else 0) for i in range(k)]
        cuts = []
        acc = 0
        for s in sizes[:-1]:
            acc += s
            cuts.append(acc)
    else:
        cuts = list(indices_or_sections)
    pieces = len(cuts) + 1
    outs = run_op(lambda a: tuple(jnp.split(a, cuts, axis=axis)),
                  [t], name=name)
    return list(outs) if isinstance(outs, (list, tuple)) else [outs]


def tensor_split(x, num_or_indices, axis=0, name=None):
    return _split_along(x, num_or_indices, axis, "tensor_split")


def hsplit(x, num_or_indices, name=None):
    t = as_tensor(x)
    axis = 0 if t.ndim == 1 else 1
    return _split_along(t, num_or_indices, axis, "hsplit")


def vsplit(x, num_or_indices, name=None):
    return _split_along(x, num_or_indices, 0, "vsplit")


def dsplit(x, num_or_indices, name=None):
    return _split_along(x, num_or_indices, 2, "dsplit")


def unflatten(x, axis, shape, name=None):
    t = as_tensor(x)
    shape = [int(s) for s in (unwrap(as_tensor(shape)).tolist()
                              if not isinstance(shape, (list, tuple))
                              else shape)]

    def fn(a):
        ax = axis % a.ndim
        new = list(a.shape[:ax]) + list(shape) + list(a.shape[ax + 1:])
        return a.reshape(new)

    return run_op(fn, [t], name="unflatten")


# ------------------------------------------------------- scatter-on-view
def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    """reference: manipulation.py diagonal_scatter."""

    def fn(a, b):
        ax1, ax2 = axis1 % a.ndim, axis2 % a.ndim
        n, m = a.shape[ax1], a.shape[ax2]
        i = jnp.arange(max(n, m))
        if offset >= 0:
            ii = i[: min(n, m - offset)]
            jj = ii + offset
        else:
            jj = i[: min(m, n + offset)]
            ii = jj - offset
        # move target axes to front for a functional scatter
        perm = [ax1, ax2] + [d for d in range(a.ndim)
                             if d not in (ax1, ax2)]
        inv = [perm.index(d) for d in range(a.ndim)]
        at = jnp.transpose(a, perm)
        bt = jnp.moveaxis(b, -1, 0) if b.ndim == a.ndim - 1 else b
        at = at.at[ii, jj].set(bt)
        return jnp.transpose(at, inv)

    return run_op(fn, [as_tensor(x), as_tensor(y)],
                  name="diagonal_scatter")


def select_scatter(x, values, axis, index, name=None):
    def fn(a, v):
        idx = [slice(None)] * a.ndim
        idx[axis % a.ndim] = index
        return a.at[tuple(idx)].set(v)

    return run_op(fn, [as_tensor(x), as_tensor(values)],
                  name="select_scatter")


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def fn(a, v):
        idx = [slice(None)] * a.ndim
        for ax, st, en, sd in zip(axes, starts, ends, strides):
            idx[ax % a.ndim] = slice(int(st), int(en), int(sd))
        return a.at[tuple(idx)].set(v)

    return run_op(fn, [as_tensor(x), as_tensor(value)],
                  name="slice_scatter")


def index_fill(x, index, axis, value, name=None):
    idx = unwrap(as_tensor(index)).astype(jnp.int32)

    def fn(a):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[idx].set(value)
        return jnp.moveaxis(moved, 0, axis)

    return run_op(fn, [as_tensor(x)], name="index_fill")


def fill_diagonal_tensor(x, y, offset=0, dim1=0, dim2=1, name=None):
    return diagonal_scatter(x, y, offset=offset, axis1=dim1, axis2=dim2,
                            name=name)


def take(x, index, mode="raise", name=None):
    """reference: math.py take — flat-index gather with wrap/clip modes.

    mode="raise" validates indices eagerly (out-of-range raises); under
    tracing, where raising is impossible, it degrades to clamp.
    """
    idx = unwrap(as_tensor(index)).astype(jnp.int32)
    xt = as_tensor(x)
    if mode == "raise" and not isinstance(idx, jax.core.Tracer):
        n = int(np.prod(xt.shape)) if xt.shape else 1
        # reduce on device; only one boolean scalar crosses to host
        if bool(((idx < -n) | (idx >= n)).any()):
            raise IndexError(
                f"take(mode='raise'): index out of range for input with "
                f"{n} elements")

    def fn(a):
        flat = a.reshape(-1)
        n = flat.shape[0]
        ii = idx
        if mode == "wrap":
            ii = ((ii % n) + n) % n
        elif mode == "clip":
            ii = jnp.clip(ii, 0, n - 1)
        else:
            ii = jnp.where(ii < 0, ii + n, ii)
        return flat[ii.reshape(-1)].reshape(idx.shape)

    return run_op(fn, [as_tensor(x)], name="take")


# ----------------------------------------------------------- attributes
def is_complex(x):
    return jnp.issubdtype(unwrap(as_tensor(x)).dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(unwrap(as_tensor(x)).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(unwrap(as_tensor(x)).dtype, jnp.integer)


def rank(x):
    return Tensor(jnp.asarray(as_tensor(x).ndim, jnp.int32))


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def isneginf(x, name=None):
    return run_op(jnp.isneginf, [as_tensor(x)], name="isneginf")


def isposinf(x, name=None):
    return run_op(jnp.isposinf, [as_tensor(x)], name="isposinf")


def isreal(x, name=None):
    return run_op(jnp.isreal, [as_tensor(x)], name="isreal")


def signbit(x, name=None):
    return run_op(jnp.signbit, [as_tensor(x)], name="signbit")


def sgn(x, name=None):
    """Complex-aware sign (reference: math.py sgn)."""

    def fn(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)

    return run_op(fn, [as_tensor(x)], name="sgn")


def positive(x, name=None):
    return run_op(lambda a: +a, [as_tensor(x)], name="positive")


def bitwise_invert(x, out=None, name=None):
    return run_op(jnp.invert, [as_tensor(x)], name="bitwise_invert")


def sigmoid(x, name=None):
    # re-export: single implementation lives in nn/functional/activation.py
    from ..nn.functional.activation import sigmoid as _sigmoid

    return _sigmoid(x, name=name)


# ------------------------------------------------------------- math misc
def deg2rad(x, name=None):
    return run_op(jnp.deg2rad, [as_tensor(x)], name="deg2rad")


def rad2deg(x, name=None):
    return run_op(jnp.rad2deg, [as_tensor(x)], name="rad2deg")


def multigammaln(x, p, name=None):
    return run_op(lambda a: jsp.multigammaln(a, int(p)), [as_tensor(x)],
                  name="multigammaln")


def complex(real, imag, name=None):
    return run_op(jax.lax.complex, [as_tensor(real), as_tensor(imag)],
                  name="complex")


def reduce_as(x, target, name=None):
    """Sum x down to target's shape (reference: math.py reduce_as)."""
    tgt_shape = tuple(as_tensor(target).shape)

    def fn(a):
        extra = a.ndim - len(tgt_shape)
        out = jnp.sum(a, axis=tuple(range(extra))) if extra else a
        axes = tuple(i for i, (s, t) in enumerate(zip(out.shape, tgt_shape))
                     if s != t and t == 1)
        if axes:
            out = jnp.sum(out, axis=axes, keepdims=True)
        return out

    return run_op(fn, [as_tensor(x)], name="reduce_as")


def histogram_bin_edges(x, bins=100, min=0, max=0, name=None):
    def fn(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else \
            (a.min(), a.max())
        return jnp.linspace(lo, hi, bins + 1).astype(jnp.float32)

    return run_op(fn, [as_tensor(x)], name="histogram_bin_edges")


def combinations(x, r=2, with_replacement=False, name=None):
    import itertools

    n = as_tensor(x).shape[0]
    gen = itertools.combinations_with_replacement(range(n), r) \
        if with_replacement else itertools.combinations(range(n), r)
    idx = jnp.asarray(list(gen), jnp.int32).reshape(-1, r)

    def fn(a):
        return a[idx]

    return run_op(fn, [as_tensor(x)], name="combinations")


def cartesian_prod(x, name=None):
    ts = [as_tensor(t) for t in x]

    def fn(*arrs):
        grids = jnp.meshgrid(*arrs, indexing="ij")
        return jnp.stack([g.reshape(-1) for g in grids], axis=-1)

    return run_op(fn, ts, name="cartesian_prod")


# ---------------------------------------------------------------- linalg
def inverse(x, name=None):
    return run_op(jnp.linalg.inv, [as_tensor(x)], name="inverse")


def cholesky_inverse(x, upper=False, name=None):
    def fn(a):
        full = (a @ a.T) if not upper else (a.T @ a)
        return jnp.linalg.inv(full)

    return run_op(fn, [as_tensor(x)], name="cholesky_inverse")


def matrix_exp(x, name=None):
    return run_op(jax.scipy.linalg.expm, [as_tensor(x)], name="matrix_exp")


def matrix_norm(x, p="fro", axis=(-2, -1), keepdim=False, name=None):
    def fn(a):
        return jnp.linalg.norm(a, ord=p, axis=tuple(axis),
                               keepdims=keepdim)

    return run_op(fn, [as_tensor(x)], name="matrix_norm")


# canonical implementation lives in ops/linalg.py (single copy — the
# star-import order makes this module's binding win at top level)
from .linalg import vector_norm  # noqa: F401,E402


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def fn(a):
        n = a.shape[-1] + abs(offset)
        base = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        i = jnp.arange(a.shape[-1])
        r = i + max(-offset, 0)
        c = i + max(offset, 0)
        out = base.at[..., r, c].set(a)
        # move the two new axes into position
        d1 = dim1 % (out.ndim)
        d2 = dim2 % (out.ndim)
        cur1, cur2 = out.ndim - 2, out.ndim - 1
        out = jnp.moveaxis(out, (cur1, cur2), (d1, d2))
        return out

    return run_op(fn, [as_tensor(input)], name="diag_embed")


# canonical implementation lives in ops/linalg.py (single copy)
from .linalg import lu_unpack  # noqa: F401,E402


def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference: linalg.py svd_lowrank)."""
    key = _rng.next_key()

    def fn(a):
        m, n = a.shape[-2], a.shape[-1]
        qq = min(q, m, n)
        omega = jax.random.normal(key, a.shape[:-2] + (n, qq), a.dtype)
        y = a @ omega
        for _ in range(niter):
            y = a @ (a.swapaxes(-1, -2) @ y)
        Q, _ = jnp.linalg.qr(y)
        B = Q.swapaxes(-1, -2) @ a
        u, s, vh = jnp.linalg.svd(B, full_matrices=False)
        return Q @ u, s, vh.swapaxes(-1, -2)

    return run_op(fn, [as_tensor(x)], name="svd_lowrank")


# -------------------------------------------------------------- creation
def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    from ..core.dtype import to_jax_dtype

    return Tensor(jnp.full(tuple(int(s) for s in shape), value,
                           to_jax_dtype(dtype)))


def create_tensor(dtype, name=None, persistable=False):
    from ..core.dtype import to_jax_dtype

    return Tensor(jnp.zeros((), to_jax_dtype(dtype)))


def gaussian(shape, mean=0.0, std=1.0, seed=0, dtype="float32", name=None):
    from ..core.dtype import to_jax_dtype

    key = _rng.next_key() if not seed else jax.random.PRNGKey(seed)
    jdt = to_jax_dtype(dtype)
    return Tensor(mean + std * jax.random.normal(
        key, tuple(int(s) for s in shape), jdt))


def standard_gamma(x, name=None):
    key = _rng.next_key()

    def fn(a):
        return jax.random.gamma(key, a)

    return run_op(fn, [as_tensor(x)], name="standard_gamma")


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def fn(a):
        ax = axis % a.ndim
        vals = jnp.sort(a, axis=ax)
        idxs = jnp.argsort(a, axis=ax)
        v = jnp.take(vals, k - 1, axis=ax)
        i = jnp.take(idxs, k - 1, axis=ax)
        if keepdim:
            v = jnp.expand_dims(v, ax)
            i = jnp.expand_dims(i, ax)
        return v, i.astype(jnp.int32)

    return run_op(fn, [as_tensor(x)], name="kthvalue")


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over the last axis (reference: math.py
    top_p_sampling; serving-path op). Returns (values, indices)."""
    key = _rng.next_key() if seed is None else jax.random.PRNGKey(seed)
    p_arr = unwrap(as_tensor(ps))

    def fn(logits):
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = (cum - sorted_p) < p_arr[..., None]
        filt = jnp.where(keep, sorted_p, 0.0)
        filt = filt / jnp.maximum(filt.sum(-1, keepdims=True), 1e-9)
        draw = jax.random.categorical(key, jnp.log(
            jnp.maximum(filt, 1e-30)), axis=-1)
        idx = jnp.take_along_axis(sort_idx, draw[..., None], axis=-1)
        val = jnp.take_along_axis(logits, idx, axis=-1)
        return val, idx.astype(jnp.int32)

    return run_op(fn, [as_tensor(x)], name="top_p_sampling")
