"""Remaining reference top-level surface: aliases, dlpack interop,
CUDA-compat shims, printing/flops utilities (reference:
python/paddle/__init__.py public list; utils/dlpack.py; flops at
hapi/dynamic_flops.py; device compat paddle/device/cuda).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ._helpers import as_tensor, run_op, unwrap

__all__ = [
    "floor_mod", "less", "reverse", "pdist", "batch",
    "to_dlpack", "from_dlpack", "flops", "set_printoptions",
    "create_parameter", "check_shape", "disable_signal_handler",
    "CUDAPlace", "CUDAPinnedPlace", "get_cuda_rng_state",
    "set_cuda_rng_state", "LazyGuard",
]


def floor_mod(x, y, name=None):
    """Alias of mod (reference: math.py floor_mod = mod)."""
    from .math import mod

    return mod(x, y, name=name)


def less(x, y, name=None):
    """Alias of less_than (reference: logic.py less)."""
    from .logic import less_than

    return less_than(x, y, name=name)


def reverse(x, axis, name=None):
    """Alias of flip (reference BC name)."""
    from .manipulation import flip

    return flip(x, axis=axis, name=name)


def pdist(x, p=2.0, name=None):
    """Pairwise distances between rows, condensed form (reference:
    linalg.py pdist)."""

    def fn(a):
        n = a.shape[0]
        d = a[:, None, :] - a[None, :, :]
        if p == 2.0:
            full = jnp.sqrt(jnp.maximum((d * d).sum(-1), 0.0))
        else:
            full = (jnp.abs(d) ** p).sum(-1) ** (1.0 / p)
        iu = jnp.triu_indices(n, k=1)
        return full[iu]

    return run_op(fn, [as_tensor(x)], name="pdist")


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference: python/paddle/reader): groups
    an item reader into batches."""

    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def to_dlpack(x):
    """reference: utils/dlpack.py to_dlpack — hand out the jax array
    itself, which carries __dlpack__/__dlpack_device__ (the modern
    capsule-provider protocol consumers expect)."""
    return unwrap(as_tensor(x))


def from_dlpack(ext):
    """reference: utils/dlpack.py from_dlpack — accepts any object with
    the __dlpack__ protocol (torch/np/jax arrays, to_dlpack results)."""
    return Tensor(jnp.from_dlpack(ext))


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Rough FLOPs count for a Layer (reference: hapi/dynamic_flops.py):
    2*m*n*k per Linear/matmul-style layer + conv kernel products, counted
    from a traced forward's parameters. A per-layer estimate, not an HLO
    cost model."""
    total = 0
    spatial = int(np.prod(input_size[2:])) if input_size is not None \
        and len(input_size) > 2 else 1
    for _, layer in net.named_sublayers(include_self=True):
        w = getattr(layer, "weight", None)
        if w is None or not hasattr(w, "shape"):
            continue
        shp = tuple(w.shape)
        if len(shp) == 2:           # linear: 2*m*n
            total += 2 * int(np.prod(shp))
        elif len(shp) >= 3:         # conv: 2*O*I*k... per output position
            total += 2 * int(np.prod(shp)) * spatial
    mult = int(np.prod(input_size[:1])) if input_size else 1
    return total * max(mult, 1)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: tensor/to_string.py set_printoptions — Tensor repr uses
    numpy formatting, so delegate."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference: tensor/creation.py create_parameter."""
    from ..nn.initializer import Constant, XavierNormal
    from ..nn.layer.layers import Parameter

    init = default_initializer or (Constant(0.0) if is_bias
                                   else XavierNormal())
    data = init(shape, dtype)
    p = Parameter(data if isinstance(data, jnp.ndarray) else
                  jnp.asarray(data))
    p.name = name
    return p


def check_shape(x):
    """reference: static nn.control_flow check utility — no-op shape
    assert helper kept for API parity."""
    return as_tensor(x).shape


def disable_signal_handler():
    """reference: pybind disable_signal_handler — jax installs no
    conflicting handlers; kept for API parity."""


class CUDAPlace:
    """Compat shim: CUDA places map to the TPU/host device space
    (reference paddle.CUDAPlace). Construction is allowed so configs
    parse; device selection routes through paddle_tpu.device."""

    def __init__(self, device_id=0):
        self.device_id = device_id

    def __repr__(self):
        return f"CUDAPlace({self.device_id})"


class CUDAPinnedPlace:
    def __repr__(self):
        return "CUDAPinnedPlace()"


def get_cuda_rng_state():
    """Compat: the framework RNG state (reference
    get_cuda_rng_state)."""
    from ..core import random as _rng

    return _rng.get_rng_state()


def set_cuda_rng_state(state):
    from ..core import random as _rng

    return _rng.set_rng_state(state)


class LazyGuard:
    """reference: base/framework LazyGuard — lazy parameter init context.
    Eager jax init is cheap; the guard is a no-op context manager kept
    for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
