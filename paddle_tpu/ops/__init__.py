"""Functional op surface (the analog of the reference's generated
``paddle._C_ops`` + ``python/paddle/tensor/*`` layers, reference:
paddle/phi/ops/yaml/ops.yaml — 470 forward ops — and python/paddle/tensor/).

Every op is a thin differentiable wrapper over a pure jax function, recorded
on the eager tape by :func:`paddle_tpu.core.autograd.run_op`. The same ops work
unchanged under jit tracing (inputs are tracers), which is how to_static works.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .random_ops import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .more import *  # noqa: F401,F403
from .inplace import *  # noqa: F401,F403
from .compat import *  # noqa: F401,F403

from . import (compat, creation, extras, inplace, linalg,  # noqa: F401
               logic, manipulation, math, more, random_ops)

__all__ = (
    creation.__all__
    + math.__all__
    + manipulation.__all__
    + linalg.__all__
    + logic.__all__
    + random_ops.__all__
    + extras.__all__
    + more.__all__
    + inplace.__all__
    + compat.__all__
)
