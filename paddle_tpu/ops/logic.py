"""Logical / comparison ops (reference: python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor
from ._helpers import as_tensor, unwrap

__all__ = [
    "logical_and", "logical_or", "logical_not", "logical_xor",
    "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
    "bitwise_left_shift", "bitwise_right_shift",
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "equal_all", "allclose", "isclose", "is_empty",
]


def _b(fn, name):
    def op(x, y, out=None, name=None):
        return Tensor(fn(unwrap(as_tensor(x)), unwrap(as_tensor(y))))

    op.__name__ = name
    return op


def _u(fn, name):
    def op(x, out=None, name=None):
        return Tensor(fn(unwrap(as_tensor(x))))

    op.__name__ = name
    return op


logical_and = _b(jnp.logical_and, "logical_and")
logical_or = _b(jnp.logical_or, "logical_or")
logical_xor = _b(jnp.logical_xor, "logical_xor")
logical_not = _u(jnp.logical_not, "logical_not")
bitwise_and = _b(jnp.bitwise_and, "bitwise_and")
bitwise_or = _b(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _b(jnp.bitwise_xor, "bitwise_xor")
bitwise_not = _u(jnp.bitwise_not, "bitwise_not")
bitwise_left_shift = _b(jnp.left_shift, "bitwise_left_shift")
bitwise_right_shift = _b(jnp.right_shift, "bitwise_right_shift")
equal = _b(jnp.equal, "equal")
not_equal = _b(jnp.not_equal, "not_equal")
greater_than = _b(jnp.greater, "greater_than")
greater_equal = _b(jnp.greater_equal, "greater_equal")
less_than = _b(jnp.less, "less_than")
less_equal = _b(jnp.less_equal, "less_equal")


def equal_all(x, y, name=None):
    a, b = unwrap(as_tensor(x)), unwrap(as_tensor(y))
    if a.shape != b.shape:
        return Tensor(jnp.asarray(False))
    return Tensor(jnp.all(a == b))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.allclose(unwrap(as_tensor(x)), unwrap(as_tensor(y)),
                               rtol=rtol, atol=atol, equal_nan=equal_nan))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return Tensor(jnp.isclose(unwrap(as_tensor(x)), unwrap(as_tensor(y)),
                              rtol=rtol, atol=atol, equal_nan=equal_nan))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(as_tensor(x).size == 0))
