"""paddle.sparse.nn (reference: python/paddle/sparse/nn/__init__.py —
ReLU/ReLU6/LeakyReLU/Softmax/BatchNorm/SyncBatchNorm/Conv2D/Conv3D/
SubmConv2D/SubmConv3D/MaxPool3D over phi/kernels/sparse/).

TPU-native sparse conv: the classic rulebook formulation
(gather -> GEMM -> scatter-add). The rulebook (which input nnz pairs with
which output site under each kernel offset) is integer bookkeeping built
host-side per step — the FLOPs all live in one [pairs, Cin] x [Cin, Cout]
matmul per kernel offset, which is exactly MXU-shaped work. Submanifold
conv fixes the output sites to the input sites (SubmConv*), standard conv
enumerates the dilated neighborhood.
"""
from __future__ import annotations

import math
from typing import Sequence

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ...core.tensor import Tensor
from ...nn.layer.layers import Layer
from .. import SparseCooTensor, SparseCsrTensor, _as_bcoo

__all__ = ["ReLU", "ReLU6", "LeakyReLU", "Softmax", "BatchNorm",
           "SyncBatchNorm", "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
           "MaxPool3D"]


# ----------------------------------------------------------- activations
class _ValueAct(Layer):
    def forward(self, x):
        bc = _as_bcoo(x)
        out = SparseCooTensor(jsparse.BCOO((self._fn(bc.data), bc.indices),
                                           shape=bc.shape))
        return (out.to_sparse_csr() if isinstance(x, SparseCsrTensor)
                else out)


class ReLU(_ValueAct):
    def _fn(self, d):
        return jnp.maximum(d, 0)


class ReLU6(_ValueAct):
    def _fn(self, d):
        return jnp.clip(d, 0, 6)


class LeakyReLU(_ValueAct):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self.negative_slope = float(negative_slope)

    def _fn(self, d):
        return jnp.where(d >= 0, d, self.negative_slope * d)


class Softmax(Layer):
    """Softmax over the non-zero entries of each row (reference:
    sparse/nn/layer/activation.py Softmax — CSR, axis=-1 only)."""

    def __init__(self, axis=-1):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse Softmax only supports axis=-1")

    def forward(self, x):
        csr = x if isinstance(x, SparseCsrTensor) else x.to_sparse_csr()
        crows = np.asarray(csr._crows)
        vals = csr._values
        out_vals = jnp.zeros_like(vals)
        # per-row softmax over the stored values; rows are ragged so this
        # builds a segment id vector and uses segment ops (one pass)
        seg = np.repeat(np.arange(len(crows) - 1), np.diff(crows))
        seg_j = jnp.asarray(seg, dtype=jnp.int32)
        n_rows = len(crows) - 1
        import jax

        mx = jax.ops.segment_max(vals, seg_j, num_segments=n_rows)
        ex = jnp.exp(vals - mx[seg_j])
        den = jax.ops.segment_sum(ex, seg_j, num_segments=n_rows)
        out_vals = ex / den[seg_j]
        out = SparseCsrTensor(csr._crows, csr._cols, out_vals, csr.shape)
        return out if isinstance(x, SparseCsrTensor) else out.to_sparse_coo()


# ----------------------------------------------------------- batch norm
class BatchNorm(Layer):
    """BatchNorm over the channel (last) axis of a COO tensor's values
    (reference: sparse/nn/layer/norm.py BatchNorm — NDHWC)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = float(momentum)
        self._epsilon = float(epsilon)
        self.weight = self.create_parameter(
            [num_features], default_initializer=lambda s, dt=None: jnp.ones(s))
        self.bias = self.create_parameter(
            [num_features], default_initializer=lambda s, dt=None: jnp.zeros(s))
        self._mean = jnp.zeros((num_features,))
        self._variance = jnp.ones((num_features,))
        self._use_global_stats = use_global_stats

    def forward(self, x):
        bc = _as_bcoo(x)
        vals = bc.data  # [nnz, C]
        if self.training and not self._use_global_stats:
            mean = jnp.mean(vals, axis=0)
            var = jnp.var(vals, axis=0)
            self._mean = (self._momentum * self._mean
                          + (1 - self._momentum) * mean)
            self._variance = (self._momentum * self._variance
                              + (1 - self._momentum) * var)
        else:
            mean, var = self._mean, self._variance
        normed = (vals - mean) / jnp.sqrt(var + self._epsilon)
        out_vals = normed * self.weight._data + self.bias._data
        return SparseCooTensor(jsparse.BCOO((out_vals, bc.indices),
                                            shape=bc.shape))


class SyncBatchNorm(BatchNorm):
    """Cross-replica BatchNorm: under pmap/shard_map the mean/var reduce
    with a psum (reference: sparse/nn/layer/norm.py SyncBatchNorm); on a
    single device it equals BatchNorm."""

    def forward(self, x):
        import jax

        bc = _as_bcoo(x)
        vals = bc.data
        if self.training:
            mean = jnp.mean(vals, axis=0)
            var = jnp.var(vals, axis=0)
            try:
                axis_env = jax.core.thread_local_state.trace_state  # noqa
            except Exception:
                axis_env = None
            # inside a collective context, all-reduce the statistics
            # single-device fallback: NameError ("unbound axis name") is
            # raised at TRACE time on every rank identically when there
            # is no sync_bn axis, so ranks cannot diverge here
            try:
                mean = jax.lax.pmean(mean, axis_name="sync_bn")  # ptlint: disable=collective-consistency
                var = jax.lax.pmean(var, axis_name="sync_bn")  # ptlint: disable=collective-consistency
            except NameError:
                pass
        else:
            mean, var = self._mean, self._variance
        normed = (vals - mean) / jnp.sqrt(var + self._epsilon)
        out_vals = normed * self.weight._data + self.bias._data
        return SparseCooTensor(jsparse.BCOO((out_vals, bc.indices),
                                            shape=bc.shape))


# ----------------------------------------------------------- convolution
def _tupled(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(s) for s in v)
    return (int(v),) * n


def _build_rulebook(coords, spatial, ksize, stride, padding, dilation,
                    subm):
    """Rulebook for an ndim sparse conv.

    coords: [nnz, 1+ndim] int array (batch + spatial), already unique.
    Returns (out_coords [m,1+ndim], rules: list over kernel offsets of
    (in_idx, out_idx) integer arrays).
    """
    ndim = len(spatial)
    offsets = np.stack(np.meshgrid(*[np.arange(k) for k in ksize],
                                   indexing="ij"), -1).reshape(-1, ndim)
    in_map = {tuple(c): i for i, c in enumerate(coords.tolist())}
    if subm:
        out_coords = coords
        out_map = in_map
        out_spatial = list(spatial)
    else:
        out_spatial = [(spatial[d] + 2 * padding[d]
                        - dilation[d] * (ksize[d] - 1) - 1) // stride[d] + 1
                       for d in range(ndim)]
        out_map = {}
        out_list = []
        for c in coords.tolist():
            b = c[0]
            for off in offsets:
                oc = []
                ok = True
                for d in range(ndim):
                    num = c[1 + d] + padding[d] - off[d] * dilation[d]
                    if num % stride[d]:
                        ok = False
                        break
                    o = num // stride[d]
                    if o < 0 or o >= out_spatial[d]:
                        ok = False
                        break
                    oc.append(o)
                if ok:
                    key = (b, *oc)
                    if key not in out_map:
                        out_map[key] = len(out_list)
                        out_list.append(key)
        out_coords = np.asarray(sorted(out_list), dtype=coords.dtype) \
            if out_list else np.zeros((0, 1 + ndim), coords.dtype)
        out_map = {tuple(c): i for i, c in enumerate(out_coords.tolist())}
    rules = []
    for off in offsets:
        ins, outs = [], []
        if subm:
            # center-aligned: out site o pulls in site o + (off - center)*dil
            for key, oi in out_map.items():
                ic = [key[0]]
                ok = True
                for d in range(ndim):
                    center = (ksize[d] - 1) // 2
                    i = key[1 + d] + (off[d] - center) * dilation[d]
                    if i < 0 or i >= spatial[d]:
                        ok = False
                        break
                    ic.append(i)
                if ok:
                    ii = in_map.get(tuple(ic))
                    if ii is not None:
                        ins.append(ii)
                        outs.append(oi)
        else:
            for key, ii in in_map.items():
                b = key[0]
                oc = [b]
                ok = True
                for d in range(ndim):
                    num = key[1 + d] + padding[d] - off[d] * dilation[d]
                    if num % stride[d]:
                        ok = False
                        break
                    o = num // stride[d]
                    if o < 0 or o >= out_spatial[d]:
                        ok = False
                        break
                    oc.append(o)
                if ok:
                    oi = out_map.get(tuple(oc))
                    if oi is not None:
                        ins.append(ii)
                        outs.append(oi)
        rules.append((np.asarray(ins, np.int32), np.asarray(outs, np.int32)))
    return out_coords, out_spatial, rules


class _SparseConv(Layer):
    _ndim = 3
    _subm = False

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None):
        super().__init__()
        n = self._ndim
        self._in = in_channels
        self._out = out_channels
        self._ksize = _tupled(kernel_size, n)
        self._stride = _tupled(stride, n)
        self._padding = _tupled(padding, n)
        self._dilation = _tupled(dilation, n)
        k = 1.0 / math.sqrt(in_channels * int(np.prod(self._ksize)))
        wshape = self._ksize + (in_channels, out_channels)
        import jax

        from ...core import random as _rng

        self.weight = self.create_parameter(
            list(wshape),
            default_initializer=lambda s, dt=None: jax.random.uniform(
                _rng.next_key(), s, minval=-k, maxval=k))
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [out_channels],
                default_initializer=lambda s, dt=None: jnp.zeros(s))
        else:
            self.bias = None

    def forward(self, x):
        bc = jsparse.bcoo_sum_duplicates(_as_bcoo(x))
        coords = np.asarray(bc.indices)  # [nnz, 1+ndim] (+channel dense)
        spatial = bc.shape[1:1 + self._ndim]
        out_coords, out_spatial, rules = _build_rulebook(
            coords, spatial, self._ksize, self._stride, self._padding,
            self._dilation, self._subm)
        n_out = len(out_coords)
        vals = bc.data  # [nnz, Cin]
        w = self.weight._data.reshape(-1, self._in, self._out)
        out_vals = jnp.zeros((n_out, self._out), vals.dtype)
        for ki, (ins, outs) in enumerate(rules):
            if not len(ins):
                continue
            gathered = vals[jnp.asarray(ins)]          # [pairs, Cin]
            prod = gathered @ w[ki]                    # MXU GEMM
            out_vals = out_vals.at[jnp.asarray(outs)].add(prod)
        if self.bias is not None:
            out_vals = out_vals + self.bias._data
        out_shape = ((bc.shape[0],) + tuple(out_spatial) + (self._out,))
        return SparseCooTensor(jsparse.BCOO(
            (out_vals, jnp.asarray(out_coords.astype(np.int32))),
            shape=out_shape))


class Conv3D(_SparseConv):
    """Sparse 3D conv, NDHWC (reference: sparse/nn/layer/conv.py
    Conv3D)."""

    _ndim = 3
    _subm = False


class SubmConv3D(_SparseConv):
    """Submanifold sparse 3D conv — output sites == input sites
    (reference: sparse/nn/layer/conv.py SubmConv3D)."""

    _ndim = 3
    _subm = True


class Conv2D(_SparseConv):
    _ndim = 2
    _subm = False


class SubmConv2D(_SparseConv):
    _ndim = 2
    _subm = True


class MaxPool3D(Layer):
    """Sparse max pooling over NDHWC COO input (reference:
    sparse/nn/layer/pooling.py MaxPool3D)."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NDHWC", name=None):
        super().__init__()
        self._ksize = _tupled(kernel_size, 3)
        self._stride = _tupled(stride if stride is not None
                               else kernel_size, 3)
        self._padding = _tupled(padding, 3)

    def forward(self, x):
        bc = jsparse.bcoo_sum_duplicates(_as_bcoo(x))
        coords = np.asarray(bc.indices)
        spatial = bc.shape[1:4]
        out_coords, out_spatial, rules = _build_rulebook(
            coords, spatial, self._ksize, self._stride, self._padding,
            (1, 1, 1), False)
        n_out = len(out_coords)
        c = bc.shape[-1]
        vals = bc.data
        out_vals = jnp.full((n_out, c), -jnp.inf, vals.dtype)
        for ins, outs in rules:
            if not len(ins):
                continue
            out_vals = out_vals.at[jnp.asarray(outs)].max(
                vals[jnp.asarray(ins)])
        out_vals = jnp.where(jnp.isfinite(out_vals), out_vals, 0.0)
        out_shape = ((bc.shape[0],) + tuple(out_spatial) + (c,))
        return SparseCooTensor(jsparse.BCOO(
            (out_vals, jnp.asarray(out_coords.astype(np.int32))),
            shape=out_shape))
