"""Sparse tensors (reference: python/paddle/sparse/ over C++
phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h and the
phi/kernels/sparse/ op set).

TPU-native: COO is jax.experimental.sparse.BCOO — XLA's batched-COO format
with jit/grad support — wrapped in the eager Tensor-like SparseCooTensor.
CSR keeps (crows, cols, values) metadata and converts through BCOO for
compute; on TPU, XLA lowers sparse matmuls to gather/segment-sum, which is
the supported execution path (no cuSPARSE analog needed).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_same_shape", "add", "matmul", "masked_matmul",
           "relu", "abs", "neg", "sin", "tanh", "sqrt", "pow", "multiply",
           "transpose"]


class SparseCooTensor:
    """COO sparse tensor backed by BCOO (reference:
    phi/core/sparse_coo_tensor.h:37)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # ---------------------------------------------------------- metadata
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        from ..core.dtype import from_jax_dtype

        return from_jax_dtype(self._bcoo.dtype)

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # [sparse_ndim, nnz] (paddle)

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return _dense_to_csr(self.to_dense())

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype.name})")


class SparseCsrTensor:
    """CSR sparse tensor (reference: phi/core/sparse_csr_tensor.h)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(crows, dtype=jnp.int32)
        self._cols = jnp.asarray(cols, dtype=jnp.int32)
        self._values = jnp.asarray(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def nnz(self) -> int:
        return int(self._values.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return Tensor(self._values)

    def to_dense(self) -> Tensor:
        n_rows = self._shape[0]
        counts = self._crows[1:] - self._crows[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz)
        dense = jnp.zeros(self._shape, self._values.dtype)
        return Tensor(dense.at[rows, self._cols].set(self._values))

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        n_rows = self._shape[0]
        counts = self._crows[1:] - self._crows[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz)
        idx = jnp.stack([rows, self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=self._shape))

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})")


# ------------------------------------------------------------- creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """reference: python/paddle/sparse/creation.py sparse_coo_tensor;
    indices [sparse_ndim, nnz] (paddle layout)."""
    idx = np.asarray(indices if not isinstance(indices, Tensor)
                     else indices.numpy())
    vals = jnp.asarray(values if not isinstance(values, Tensor)
                       else values._data)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype

        vals = vals.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
        shape = shape + vals.shape[1:]
    return SparseCooTensor(
        jsparse.BCOO((vals, jnp.asarray(idx.T, dtype=jnp.int32)),
                     shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    """reference: python/paddle/sparse/creation.py sparse_csr_tensor."""
    return SparseCsrTensor(
        crows if not isinstance(crows, Tensor) else crows.numpy(),
        cols if not isinstance(cols, Tensor) else cols.numpy(),
        values if not isinstance(values, Tensor) else values._data, shape)


def _dense_to_csr(t: Tensor) -> SparseCsrTensor:
    arr = np.asarray(t._data)
    assert arr.ndim == 2
    mask = arr != 0
    counts = mask.sum(axis=1)
    crows = np.concatenate([[0], np.cumsum(counts)])
    rows, cols = np.nonzero(mask)
    return SparseCsrTensor(crows, cols, arr[rows, cols], arr.shape)


def _as_bcoo(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()._bcoo
    raise TypeError(f"expected sparse tensor, got {type(x)}")


# ------------------------------------------------------------- ops
def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def add(x, y):
    """sparse+sparse or sparse+dense (reference: sparse/binary.py add)."""
    if isinstance(y, Tensor):
        return Tensor(_as_bcoo(x).todense() + y._data)
    out = jsparse.bcoo_sum_duplicates(_bcoo_add(_as_bcoo(x), _as_bcoo(y)))
    return SparseCooTensor(out)


def _bcoo_add(a, b):
    data = jnp.concatenate([a.data, b.data])
    idx = jnp.concatenate([a.indices, b.indices])
    return jsparse.BCOO((data, idx), shape=a.shape)


def multiply(x, y):
    """elementwise multiply sparse*dense or sparse*sparse-same-pattern."""
    if isinstance(y, Tensor):
        bc = _as_bcoo(x)
        gathered = y._data[tuple(bc.indices[:, i]
                                 for i in range(bc.indices.shape[1]))]
        return SparseCooTensor(jsparse.BCOO((bc.data * gathered,
                                             bc.indices), shape=bc.shape))
    return SparseCooTensor(_as_bcoo(x) * _as_bcoo(y))


def matmul(x, y):
    """sparse @ dense -> dense (reference: sparse/matmul.py)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        out = _as_bcoo(x) @ (y._data if isinstance(y, Tensor) else y)
        return Tensor(out)
    return Tensor((x._data if isinstance(x, Tensor) else x) @ _as_bcoo(y))


def masked_matmul(x: Tensor, y: Tensor, mask):
    """dense@dense sampled at mask's sparsity (reference: SDDMM)."""
    bc = _as_bcoo(mask)
    rows = bc.indices[:, 0]
    cols = bc.indices[:, 1]
    vals = jnp.einsum("nd,nd->n", x._data[rows], y._data.T[cols])
    return SparseCooTensor(jsparse.BCOO((vals, bc.indices), shape=bc.shape))


def _unary(fn):
    def op(x):
        bc = _as_bcoo(x)
        return SparseCooTensor(jsparse.BCOO((fn(bc.data), bc.indices),
                                            shape=bc.shape))

    return op


relu = _unary(lambda d: jnp.maximum(d, 0))
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)


def pow(x, factor):
    return _unary(lambda d: jnp.power(d, factor))(x)


def transpose(x, perm):
    return SparseCooTensor(jsparse.bcoo_transpose(
        _as_bcoo(x), permutation=tuple(perm)))
