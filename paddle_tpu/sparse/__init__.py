"""Sparse tensors (reference: python/paddle/sparse/ over C++
phi/core/sparse_coo_tensor.h, sparse_csr_tensor.h and the
phi/kernels/sparse/ op set).

TPU-native: COO is jax.experimental.sparse.BCOO — XLA's batched-COO format
with jit/grad support — wrapped in the eager Tensor-like SparseCooTensor.
CSR keeps (crows, cols, values) metadata and converts through BCOO for
compute; on TPU, XLA lowers sparse matmuls to gather/segment-sum, which is
the supported execution path (no cuSPARSE analog needed).
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "SparseCsrTensor", "is_same_shape", "add", "matmul", "masked_matmul",
           "relu", "abs", "neg", "sin", "tanh", "sqrt", "pow", "multiply",
           "transpose"]


class SparseCooTensor:
    """COO sparse tensor backed by BCOO (reference:
    phi/core/sparse_coo_tensor.h:37)."""

    def __init__(self, bcoo: jsparse.BCOO):
        self._bcoo = bcoo

    # ---------------------------------------------------------- metadata
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        from ..core.dtype import from_jax_dtype

        return from_jax_dtype(self._bcoo.dtype)

    @property
    def nnz(self) -> int:
        return int(self._bcoo.nse)

    def indices(self) -> Tensor:
        return Tensor(self._bcoo.indices.T)  # [sparse_ndim, nnz] (paddle)

    def values(self) -> Tensor:
        return Tensor(self._bcoo.data)

    def to_dense(self) -> Tensor:
        return Tensor(self._bcoo.todense())

    def to_sparse_csr(self) -> "SparseCsrTensor":
        return _dense_to_csr(self.to_dense())

    def coalesce(self) -> "SparseCooTensor":
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz}, "
                f"dtype={self.dtype.name})")


class SparseCsrTensor:
    """CSR sparse tensor (reference: phi/core/sparse_csr_tensor.h)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(crows, dtype=jnp.int32)
        self._cols = jnp.asarray(cols, dtype=jnp.int32)
        self._values = jnp.asarray(values)
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def nnz(self) -> int:
        return int(self._values.shape[0])

    def crows(self) -> Tensor:
        return Tensor(self._crows)

    def cols(self) -> Tensor:
        return Tensor(self._cols)

    def values(self) -> Tensor:
        return Tensor(self._values)

    def to_dense(self) -> Tensor:
        n_rows = self._shape[0]
        counts = self._crows[1:] - self._crows[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz)
        dense = jnp.zeros(self._shape, self._values.dtype)
        return Tensor(dense.at[rows, self._cols].set(self._values))

    def to_sparse_coo(self, sparse_dim: int = 2) -> SparseCooTensor:
        n_rows = self._shape[0]
        counts = self._crows[1:] - self._crows[:-1]
        rows = jnp.repeat(jnp.arange(n_rows), counts,
                          total_repeat_length=self.nnz)
        idx = jnp.stack([rows, self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=self._shape))

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})")


# ------------------------------------------------------------- creation
def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      place=None, stop_gradient=True) -> SparseCooTensor:
    """reference: python/paddle/sparse/creation.py sparse_coo_tensor;
    indices [sparse_ndim, nnz] (paddle layout)."""
    idx = np.asarray(indices if not isinstance(indices, Tensor)
                     else indices.numpy())
    vals = jnp.asarray(values if not isinstance(values, Tensor)
                       else values._data)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype

        vals = vals.astype(to_jax_dtype(dtype))
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=1))
        shape = shape + vals.shape[1:]
    return SparseCooTensor(
        jsparse.BCOO((vals, jnp.asarray(idx.T, dtype=jnp.int32)),
                     shape=tuple(shape)))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      place=None, stop_gradient=True) -> SparseCsrTensor:
    """reference: python/paddle/sparse/creation.py sparse_csr_tensor."""
    return SparseCsrTensor(
        crows if not isinstance(crows, Tensor) else crows.numpy(),
        cols if not isinstance(cols, Tensor) else cols.numpy(),
        values if not isinstance(values, Tensor) else values._data, shape)


def _dense_to_csr(t: Tensor) -> SparseCsrTensor:
    arr = np.asarray(t._data)
    assert arr.ndim == 2
    mask = arr != 0
    counts = mask.sum(axis=1)
    crows = np.concatenate([[0], np.cumsum(counts)])
    rows, cols = np.nonzero(mask)
    return SparseCsrTensor(crows, cols, arr[rows, cols], arr.shape)


def _as_bcoo(x):
    if isinstance(x, SparseCooTensor):
        return x._bcoo
    if isinstance(x, SparseCsrTensor):
        return x.to_sparse_coo()._bcoo
    raise TypeError(f"expected sparse tensor, got {type(x)}")


# ------------------------------------------------------------- ops
def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


def add(x, y):
    """sparse+sparse or sparse+dense (reference: sparse/binary.py add)."""
    if isinstance(y, Tensor):
        return Tensor(_as_bcoo(x).todense() + y._data)
    out = jsparse.bcoo_sum_duplicates(_bcoo_add(_as_bcoo(x), _as_bcoo(y)))
    return SparseCooTensor(out)


def _bcoo_add(a, b):
    data = jnp.concatenate([a.data, b.data])
    idx = jnp.concatenate([a.indices, b.indices])
    return jsparse.BCOO((data, idx), shape=a.shape)


def multiply(x, y):
    """elementwise multiply sparse*dense or sparse*sparse-same-pattern."""
    if isinstance(y, Tensor):
        bc = _as_bcoo(x)
        gathered = y._data[tuple(bc.indices[:, i]
                                 for i in range(bc.indices.shape[1]))]
        return SparseCooTensor(jsparse.BCOO((bc.data * gathered,
                                             bc.indices), shape=bc.shape))
    return SparseCooTensor(_as_bcoo(x) * _as_bcoo(y))


def matmul(x, y):
    """sparse @ dense -> dense (reference: sparse/matmul.py)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        out = _as_bcoo(x) @ (y._data if isinstance(y, Tensor) else y)
        return Tensor(out)
    return Tensor((x._data if isinstance(x, Tensor) else x) @ _as_bcoo(y))


def masked_matmul(x: Tensor, y: Tensor, mask):
    """dense@dense sampled at mask's sparsity (reference: SDDMM)."""
    bc = _as_bcoo(mask)
    rows = bc.indices[:, 0]
    cols = bc.indices[:, 1]
    vals = jnp.einsum("nd,nd->n", x._data[rows], y._data.T[cols])
    return SparseCooTensor(jsparse.BCOO((vals, bc.indices), shape=bc.shape))


def _unary(fn):
    def op(x):
        bc = _as_bcoo(x)
        return SparseCooTensor(jsparse.BCOO((fn(bc.data), bc.indices),
                                            shape=bc.shape))

    return op


relu = _unary(lambda d: jnp.maximum(d, 0))
abs = _unary(jnp.abs)
neg = _unary(jnp.negative)
sin = _unary(jnp.sin)
tanh = _unary(jnp.tanh)
sqrt = _unary(jnp.sqrt)


def pow(x, factor):
    return _unary(lambda d: jnp.power(d, factor))(x)


def transpose(x, perm):
    return SparseCooTensor(jsparse.bcoo_transpose(
        _as_bcoo(x), permutation=tuple(perm)))


asin = _unary(jnp.arcsin)
asinh = _unary(jnp.arcsinh)
atan = _unary(jnp.arctan)
atanh = _unary(jnp.arctanh)
sinh = _unary(jnp.sinh)
tan = _unary(jnp.tan)
expm1 = _unary(jnp.expm1)
log1p = _unary(jnp.log1p)
square = _unary(jnp.square)
deg2rad = _unary(jnp.deg2rad)
rad2deg = _unary(jnp.rad2deg)
isnan = _unary(jnp.isnan)


def cast(x, index_dtype=None, value_dtype=None):
    """reference: sparse/unary.py cast — cast indices and/or values."""
    from ..core.dtype import to_jax_dtype

    bc = _as_bcoo(x)
    idx = bc.indices
    if index_dtype is not None:
        idx = idx.astype(to_jax_dtype(index_dtype))
    data = bc.data
    if value_dtype is not None:
        data = data.astype(to_jax_dtype(value_dtype))
    out = SparseCooTensor(jsparse.BCOO((data, idx), shape=bc.shape))
    return out if isinstance(x, SparseCooTensor) else out.to_sparse_csr()


def coalesce(x):
    """reference: sparse/unary.py coalesce — merge duplicate indices."""
    return SparseCooTensor(jsparse.bcoo_sum_duplicates(_as_bcoo(x)))


def subtract(x, y):
    """reference: sparse/binary.py subtract."""
    return add(x, neg(y) if isinstance(
        y, (SparseCooTensor, SparseCsrTensor)) else Tensor(-y._data))


def divide(x, y):
    """sparse / dense (or scalar) elementwise (reference binary.py)."""
    bc = _as_bcoo(x)
    if isinstance(y, Tensor):
        gathered = y._data[tuple(bc.indices[:, i]
                                 for i in range(bc.indices.shape[1]))]
        return SparseCooTensor(jsparse.BCOO((bc.data / gathered,
                                             bc.indices), shape=bc.shape))
    return SparseCooTensor(jsparse.BCOO((bc.data / y, bc.indices),
                                        shape=bc.shape))


def sum(x, axis=None, dtype=None, keepdim=False):
    """reference: sparse/unary.py sum — dense output like the reference
    (sum destroys sparsity along the reduced axes)."""
    dense = _as_bcoo(x).todense()
    out = jnp.sum(dense, axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..core.dtype import to_jax_dtype

        out = out.astype(to_jax_dtype(dtype))
    return Tensor(out)


def mv(x, vec):
    """sparse matrix @ dense vector (reference: sparse/matmul.py mv)."""
    v = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    return Tensor(_as_bcoo(x) @ v)


def addmm(input, x, y, beta=1.0, alpha=1.0):
    """beta*input + alpha*(x@y) with sparse x (reference: matmul.py
    addmm)."""
    prod = matmul(x, y)
    inp = input.to_dense() if isinstance(
        input, (SparseCooTensor, SparseCsrTensor)) else input
    return Tensor(beta * inp._data + alpha * prod._data)


def mask_as(x, mask):
    """Sample dense x at mask's sparsity pattern (reference:
    sparse/unary.py mask_as)."""
    bc = _as_bcoo(mask)
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    vals = xd[tuple(bc.indices[:, i] for i in range(bc.indices.shape[1]))]
    out = SparseCooTensor(jsparse.BCOO((vals, bc.indices), shape=bc.shape))
    return out if isinstance(mask, SparseCooTensor) else out.to_sparse_csr()


def reshape(x, shape):
    """reference: sparse/unary.py reshape — COO reshape via linearized
    index remap (pure integer arithmetic, stays sparse)."""
    bc = _as_bcoo(x)
    old_shape = bc.shape
    new_shape = []
    inferred = -1
    total = int(np.prod(old_shape))
    for i, s in enumerate(shape):
        if s == -1:
            inferred = i
            new_shape.append(1)
        else:
            new_shape.append(int(s))
    if inferred >= 0:
        new_shape[inferred] = total // int(np.prod(new_shape))
    lin = jnp.zeros(bc.indices.shape[0], dtype=bc.indices.dtype)
    for i, s in enumerate(old_shape):
        lin = lin * s + bc.indices[:, i]
    new_idx = []
    rem = lin
    for s in reversed(new_shape):
        new_idx.append(rem % s)
        rem = rem // s
    idx = jnp.stack(list(reversed(new_idx)), axis=1)
    out = SparseCooTensor(jsparse.BCOO((bc.data, idx),
                                       shape=tuple(new_shape)))
    return out if isinstance(x, SparseCooTensor) else out.to_sparse_csr()


def slice(x, axes, starts, ends):
    """reference: sparse/unary.py slice:1017 — slice a sparse tensor,
    keeping it sparse (index filter + shift)."""
    bc = jsparse.bcoo_sum_duplicates(_as_bcoo(x))
    shape = list(bc.shape)
    sel = jnp.ones(bc.indices.shape[0], dtype=bool)
    shifts = [0] * len(shape)
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax) % len(shape)
        st = int(st) if st >= 0 else int(st) + shape[ax]
        en = min(int(en) if en >= 0 else int(en) + shape[ax], shape[ax])
        sel = sel & (bc.indices[:, ax] >= st) & (bc.indices[:, ax] < en)
        shifts[ax] = st
        shape[ax] = en - st
    # dynamic nnz -> host filter (eager-only op, like reference CPU path)
    keep = np.nonzero(np.asarray(sel))[0]
    idx = np.asarray(bc.indices)[keep] - np.asarray(shifts, np.int32)
    data = np.asarray(bc.data)[keep]
    out = SparseCooTensor(jsparse.BCOO(
        (jnp.asarray(data), jnp.asarray(idx)), shape=tuple(shape)))
    return out if isinstance(x, SparseCooTensor) else out.to_sparse_csr()


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA on a sparse matrix (reference: sparse linalg
    pca_lowrank) — densify through matmuls only."""
    from ..ops.linalg import svd_lowrank

    dense = _as_bcoo(x).todense()
    m, n = dense.shape[-2], dense.shape[-1]
    qq = q if q is not None else min(6, m, n)
    t = Tensor(dense)
    if center:
        mean = jnp.mean(dense, axis=-2, keepdims=True)
        t = Tensor(dense - mean)
    u, s, v = svd_lowrank(t, q=qq, niter=niter)
    return u, s, v


__all__ += ["asin", "asinh", "atan", "atanh", "sinh", "tan", "expm1",
            "log1p", "square", "deg2rad", "rad2deg", "isnan", "cast",
            "coalesce", "subtract", "divide", "sum", "mv", "addmm",
            "mask_as", "reshape", "slice", "pca_lowrank"]

from . import nn  # noqa: F401,E402
