"""QuantConfig (reference: python/paddle/quantization/config.py)."""
from __future__ import annotations

from typing import Dict, Optional, Type

from .. import nn

__all__ = ["QuantConfig"]


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._layer_configs: Dict = {}
        self._type_configs: Dict[Type, Dict] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_configs[id(l)] = {"activation": activation,
                                          "weight": weight}
        return self

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = {"activation": activation,
                                     "weight": weight}
        return self

    def needs_quant(self, layer) -> bool:
        if id(layer) in self._layer_configs:
            return True
        if type(layer) in self._type_configs:
            return True
        return isinstance(layer, nn.Linear) and (
            self.activation is not None or self.weight is not None)
