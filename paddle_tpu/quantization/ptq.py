"""PTQ driver (reference: python/paddle/quantization/ptq.py): observe
activations on calibration data, then convert."""
from __future__ import annotations

from .. import nn
from .config import QuantConfig
from .layers import FakeQuantLinear, QuantedLinear
from .qat import _replace_linears

__all__ = ["PTQ"]


class PTQ:
    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        """Insert observers (fake-quant layers in eval mode observe via
        explicit calibrate())."""
        _replace_linears(model, self.config, FakeQuantLinear)
        return model

    def calibrate(self, model: nn.Layer, dataloader, max_batches=None):
        model.eval()
        fq_layers = [l for l in _walk(model)
                     if isinstance(l, FakeQuantLinear)]
        for l in fq_layers:
            l.train()  # enable observation
        for i, batch in enumerate(dataloader):
            if max_batches is not None and i >= max_batches:
                break
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            model(x)
        for l in fq_layers:
            l.eval()

    def convert(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        def walk(layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, FakeQuantLinear):
                    setattr(layer, name, QuantedLinear(sub))
                else:
                    walk(sub)

        walk(model)
        return model


def _walk(layer):
    yield layer
    for sub in layer._sub_layers.values():
        yield from _walk(sub)
