"""Quantized layer wrappers (reference: python/paddle/nn/quant/ and
quantization/imperative qat layers)."""
from __future__ import annotations

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from .functional import dequantize, fake_quant_dequant, quantize
from .observers import MovingAverageAbsmaxObserver

__all__ = ["FakeQuantLinear", "QuantedLinear"]


class FakeQuantLinear(nn.Layer):
    """QAT wrapper: fake-quant activations (moving-average scale) and
    weights (per-channel absmax) around the wrapped Linear."""

    def __init__(self, linear: nn.Layer, quant_bits: int = 8):
        super().__init__()
        self.inner = linear
        self.act_observer = MovingAverageAbsmaxObserver(quant_bits)

    def forward(self, x):
        if self.training:
            self.act_observer.observe(x)
        if self.act_observer._absmax > 0:
            x = fake_quant_dequant(x, scale=self.act_observer.scale())
        # else: observer never ran (pre-calibration eval) — quantizing
        # against the 1e-8 floor would zero every activation
        w = self.inner.weight
        # weight [in, out]: reduce axis 0 -> per-output-channel scales
        wq = fake_quant_dequant(w, axis=0)
        out = x @ wq
        if getattr(self.inner, "bias", None) is not None:
            out = out + self.inner.bias
        return out


class QuantedLinear(nn.Layer):
    """Converted inference layer: int8 weights + f32 scale; the matmul
    itself runs in the compute dtype after dequant (XLA folds the dequant
    into the matmul epilogue on TPU)."""

    def __init__(self, fq: FakeQuantLinear):
        super().__init__()
        w = fq.inner.weight
        # same per-output-channel scheme the QAT pass trained against
        qw, scale = quantize(w, axis=0)
        self.qweight = Tensor(qw._data)
        self.wscale = Tensor(scale._data)
        self.bias = getattr(fq.inner, "bias", None)
        # 0.0 = observer never calibrated -> activations stay float
        self.act_scale = fq.act_observer.scale() \
            if fq.act_observer._absmax > 0 else 0.0

    def forward(self, x):
        if self.act_scale:
            # simulate the int8 activation path the calibration fixed
            x = fake_quant_dequant(x, scale=self.act_scale)
        w = dequantize(self.qweight, self.wscale)
        out = x @ w
        if self.bias is not None:
            out = out + self.bias
        return out
