"""Quantize / dequantize / fake-quant primitives.

TPU-first: int8 symmetric per-tensor/per-channel; the fake-quant fwd uses a
straight-through estimator (round has zero grad; STE passes the cotangent
through unchanged), which is the same scheme the reference's
FakeQuanterWithAbsMax implements in CUDA."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import run_op
from ..core.tensor import Tensor

__all__ = ["quantize", "dequantize", "fake_quant_dequant"]


def _scale_of(arr, axis=None):
    amax = jnp.max(jnp.abs(arr)) if axis is None else \
        jnp.max(jnp.abs(arr), axis=axis, keepdims=True)
    return jnp.maximum(amax, 1e-8) / 127.0


def quantize(x: Tensor, scale=None, axis=None):
    arr = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    s = _scale_of(arr, axis) if scale is None else scale
    q = jnp.clip(jnp.round(arr / s), -128, 127).astype(jnp.int8)
    return Tensor(q), Tensor(jnp.asarray(s))


def dequantize(q: Tensor, scale: Tensor):
    return Tensor(q._data.astype(jnp.float32) * scale._data)


@jax.custom_vjp
def _fqd(arr, scale):
    return jnp.clip(jnp.round(arr / scale), -128, 127) * scale


def _fqd_fwd(arr, scale):
    return _fqd(arr, scale), None


def _fqd_bwd(res, g):
    return g, None  # straight-through estimator


_fqd.defvjp(_fqd_fwd, _fqd_bwd)


def fake_quant_dequant(x: Tensor, scale=None, axis=None) -> Tensor:
    """Simulated int8 round-trip with STE gradient."""
    t = x if isinstance(x, Tensor) else Tensor(x)
    if scale is None:
        scale = _scale_of(t._data, axis)
    elif isinstance(scale, Tensor):
        scale = scale._data
    return run_op(lambda a: _fqd(a, scale), [t], name="fake_quant")
