"""Quantization: QAT (fake-quant) + PTQ (observer/calibrate/convert)
(reference: python/paddle/quantization/ — QuantConfig config.py, QAT
qat.py, PTQ ptq.py, quanters/ fake quanters, observers/ absmax)."""
from .config import QuantConfig  # noqa: F401
from .layers import FakeQuantLinear, QuantedLinear  # noqa: F401
from .observers import AbsmaxObserver, MovingAverageAbsmaxObserver  # noqa: F401
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .functional import fake_quant_dequant, quantize, dequantize  # noqa: F401

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "MovingAverageAbsmaxObserver", "FakeQuantLinear", "QuantedLinear",
           "fake_quant_dequant", "quantize", "dequantize"]


class BaseObserver:
    """Abstract observer (reference: python/paddle/quantization/
    base_observer.py) — collects statistics during calibration and
    yields quant params."""

    def observe(self, x):
        raise NotImplementedError

    def cal_thresholds(self):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return 0

    __call__ = lambda self, x: self.observe(x)


class BaseQuanter:
    """Abstract fake-quanter (reference: base_quanter.py) — simulates
    quantization in forward (QAT) with straight-through gradients."""

    def forward(self, x):
        raise NotImplementedError

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return 0

    def bit_length(self):
        return 8

    __call__ = lambda self, x: self.forward(x)


def quanter(name):
    """Class decorator registering a custom quanter under ``name``
    (reference: python/paddle/quantization/factory.py quanter): the
    QuantConfig can then reference it symbolically."""
    registry = getattr(quanter, "_registry", None)
    if registry is None:
        registry = quanter._registry = {}

    def deco(cls):
        registry[name] = cls
        cls._quanter_name = name
        return cls

    return deco


__all__ += ["BaseObserver", "BaseQuanter", "quanter"]
