"""Quantization: QAT (fake-quant) + PTQ (observer/calibrate/convert)
(reference: python/paddle/quantization/ — QuantConfig config.py, QAT
qat.py, PTQ ptq.py, quanters/ fake quanters, observers/ absmax)."""
from .config import QuantConfig  # noqa: F401
from .layers import FakeQuantLinear, QuantedLinear  # noqa: F401
from .observers import AbsmaxObserver, MovingAverageAbsmaxObserver  # noqa: F401
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
from .functional import fake_quant_dequant, quantize, dequantize  # noqa: F401

__all__ = ["QuantConfig", "QAT", "PTQ", "AbsmaxObserver",
           "MovingAverageAbsmaxObserver", "FakeQuantLinear", "QuantedLinear",
           "fake_quant_dequant", "quantize", "dequantize"]
