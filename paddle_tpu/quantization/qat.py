"""QAT driver (reference: python/paddle/quantization/qat.py)."""
from __future__ import annotations

from .. import nn
from .config import QuantConfig
from .layers import FakeQuantLinear, QuantedLinear

__all__ = ["QAT"]


def _replace_linears(layer: nn.Layer, config: QuantConfig, wrap):
    for name, sub in list(layer._sub_layers.items()):
        if isinstance(sub, nn.Linear) and config.needs_quant(sub):
            # setattr, not _sub_layers[name]=: Layer.__setattr__ keeps both
            # the registry and the attribute in sync (a stale __dict__
            # entry would silently bypass quantization in forward)
            setattr(layer, name, wrap(sub))
        else:
            _replace_linears(sub, config, wrap)


class QAT:
    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        """Insert fake-quant wrappers around quantizable layers."""
        _replace_linears(model, self.config, FakeQuantLinear)
        return model

    def convert(self, model: nn.Layer, inplace: bool = True) -> nn.Layer:
        """Swap trained fake-quant layers for int8-weight inference
        layers."""

        def walk(layer):
            for name, sub in list(layer._sub_layers.items()):
                if isinstance(sub, FakeQuantLinear):
                    setattr(layer, name, QuantedLinear(sub))
                else:
                    walk(sub)

        walk(model)
        return model
