"""Observers (reference: python/paddle/quantization/observers/abs_max.py
and quanters moving-average absmax)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["AbsmaxObserver", "MovingAverageAbsmaxObserver"]


class AbsmaxObserver:
    def __init__(self, quant_bits: int = 8):
        self.quant_bits = quant_bits
        self._absmax = 0.0

    def observe(self, x: Tensor):
        cur = float(jnp.max(jnp.abs(x._data)))
        self._absmax = max(self._absmax, cur)

    __call__ = observe

    def scale(self) -> float:
        qmax = 2 ** (self.quant_bits - 1) - 1
        return max(self._absmax, 1e-8) / qmax


class MovingAverageAbsmaxObserver(AbsmaxObserver):
    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._initialized = False

    def observe(self, x: Tensor):
        cur = float(jnp.max(jnp.abs(x._data)))
        if not self._initialized:
            self._absmax = cur
            self._initialized = True
        else:
            self._absmax = (self.moving_rate * self._absmax
                            + (1 - self.moving_rate) * cur)

    __call__ = observe
