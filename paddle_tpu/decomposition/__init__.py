"""paddle.decomposition (reference: python/paddle/decomposition/ —
register.py rule registry, decomp.py decompose(program, ops); rule
bodies: paddle/fluid/primitive/decomp_rule/decomp_rule/composite.h).

The reference decomposes composite ops into a primitive set so backends
without the composite kernel (or the prim-based autodiff) can run them.
On XLA that role is largely moot — every op here already lowers to HLO
primitives — so this tier exists for (a) program-level rewrites that
want to see a smaller op vocabulary (custom passes, export), and (b)
reference-workflow compatibility. Rules rewrite the captured op-DAG
(static/graph.py) exactly like distributed/passes does.

Attr-aware rules (round 5, fixes the r4 soundness bug): ops record
their attributes (axis, epsilon, approximate, ...) on the OpNode
(`run_op(..., attrs={...})`), and every rule receives them as
keyword-only parameters — mirroring the reference's rule signature
(composite.h:337 `softmax_decomp(const Tensor& x, const int& axis)`).
Applicability is SOUND, not shape-coincident:

  * an op instance carrying an attr the rule does not accept keeps its
    original fn (the rule cannot model it);
  * an attr-dependent rule never fires on a node recorded without
    attrs (no guessing defaults);
  * the output avals must still match exactly (belt and braces).

Because a decomposed node is an ordinary pure-jnp OpNode, jax.vjp
differentiates straight through it — grad-through-decomposition needs
no separate VJP-rule tier (the reference needs
fluid/primitive/vjp_interface/ only because its primitives live in C++).
"""
from __future__ import annotations

import inspect
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..static import graph as _g

__all__ = ["register_decomp", "get_decomp_rule", "decompose"]

_RULES: Dict[str, Callable] = {}
_RULE_SIGS: Dict[str, tuple] = {}   # name -> (accepted, required, has_varkw)


def _rule_sig(name: str, rule: Callable):
    cached = _RULE_SIGS.get(name)
    if cached is not None:
        return cached
    sig = inspect.signature(rule)
    accepted = set()
    required = set()
    has_varkw = False
    for k, p in sig.parameters.items():
        if p.kind == p.KEYWORD_ONLY:
            accepted.add(k)
            if p.default is p.empty:
                required.add(k)
        elif p.kind == p.VAR_KEYWORD:
            has_varkw = True
    out = (accepted, required, has_varkw)
    _RULE_SIGS[name] = out
    return out


def register_decomp(op_name: str):
    """Register a decomposition rule for a recorded op name (reference:
    decomposition/register.py register_decomp). The rule is a pure array
    function ``rule(*arrays, **attrs)`` — op attributes arrive as
    keyword-only parameters and MUST be declared by the rule; undeclared
    attrs make the rule inapplicable to that op instance."""

    def deco(fn):
        _RULES[op_name] = fn
        _RULE_SIGS.pop(op_name, None)
        return fn

    return deco


def get_decomp_rule(op_name: str) -> Optional[Callable]:
    return _RULES.get(op_name)


def decompose(fetches: List, ops: Optional[List[str]] = None) -> List:
    """Rewrite the program producing ``fetches`` so every op in ``ops``
    (default: all ops with registered rules) runs its primitive
    decomposition (reference: decomposition/decomp.py decompose:194).
    Returns new fetch handles over the rewritten DAG."""
    from ..distributed.passes import _avals_of, rewrite_program

    wanted = set(ops) if ops is not None else set(_RULES)

    def keep(node, new_parents):
        return _g.OpNode(node.fn, new_parents, node.out_avals,
                         node.name, node.single, attrs=node.attrs)

    def transform(node, new_parents):
        rule = _RULES.get(node.name)
        if rule is None or node.name not in wanted:
            return keep(node, new_parents)
        accepted, required, has_varkw = _rule_sig(node.name, rule)
        attrs = node.attrs
        if attrs is None:
            # attrs=None means the op did NOT declare its attributes —
            # its closure may carry anything (threshold, axis, ...), so
            # no rule may fire. Attr-free ops declare attrs={} (the r4
            # bug was firing rules on exactly these undeclared nodes).
            return keep(node, new_parents)
        keys = set(attrs)
        if (not has_varkw and not keys <= accepted) \
                or not required <= keys:
            return keep(node, new_parents)
        call_attrs = dict(attrs)

        def fn(*arrays, _rule=rule, _attrs=call_attrs):
            return _rule(*arrays, **_attrs)

        # the rule must reproduce the op's exact output signature
        try:
            out = jax.eval_shape(fn, *_avals_of(new_parents))
            outs = (out,) if not isinstance(out, (tuple, list)) \
                else tuple(out)
            ok = len(outs) == len(node.out_avals) and all(
                tuple(a.shape) == tuple(b.shape) and a.dtype == b.dtype
                for a, b in zip(outs, node.out_avals))
        except Exception:
            ok = False
        if not ok:
            return keep(node, new_parents)
        return _g.OpNode(fn, new_parents, node.out_avals,
                         f"{node.name}_decomposed", node.single,
                         attrs=node.attrs)

    return rewrite_program(fetches, transform)


# ---------------------------------------------------------------------------
# Built-in rules — the transformer-vocabulary slice of the reference
# composite set (composite.h). Each rule re-expresses the op in jnp/lax
# primitives and mirrors the recorded fn's numerics exactly (same op
# order, same f32 upcasts), so decompose() is value-preserving even in
# bf16. Attr params are keyword-only, matching how op sites record them.
# ---------------------------------------------------------------------------

def _logistic(a):
    return jax.lax.logistic(a)


@register_decomp("softmax")
def _softmax_decomp(x, *, axis=-1, dtype=None):
    # composite.h softmax_decomp(x, axis): x - max -> exp -> normalize
    if dtype is not None:
        x = x.astype(dtype)
    mx = jax.lax.stop_gradient(jnp.max(x, axis=axis, keepdims=True))
    e = jnp.exp(x - mx)
    return e / jnp.sum(e, axis=axis, keepdims=True)


@register_decomp("log_softmax")
def _log_softmax_decomp(x, *, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    shifted = x - jax.lax.stop_gradient(
        jnp.max(x, axis=axis, keepdims=True))
    return shifted - jnp.log(
        jnp.sum(jnp.exp(shifted), axis=axis, keepdims=True))


@register_decomp("gelu")
def _gelu_decomp(x, *, approximate=False):
    # composite.h gelu_decomp carries the approximate flag; erf and tanh
    # forms are DIFFERENT functions — r4's rule silently swapped them.
    # Term order/factoring mirrors jax.nn.gelu exactly for bit equality.
    import numpy as _np

    if approximate:
        sqrt_2_over_pi = _np.sqrt(2 / _np.pi).astype(x.dtype)
        cdf = 0.5 * (1.0 + jnp.tanh(sqrt_2_over_pi
                                    * (x + 0.044715 * (x ** 3))))
        return x * cdf
    sqrt_half = _np.sqrt(0.5).astype(x.dtype)
    return jnp.asarray(0.5 * x * jax.lax.erfc(-x * sqrt_half),
                       dtype=x.dtype)


@register_decomp("silu")
def _silu_decomp(x):
    return x * _logistic(x)


@register_decomp("swish")
def _swish_decomp(x):
    return x * _logistic(x)


@register_decomp("sigmoid")
def _sigmoid_decomp(x):
    return _logistic(x)


@register_decomp("relu")
def _relu_decomp(x):
    return jnp.maximum(x, 0)


@register_decomp("relu6")
def _relu6_decomp(x):
    return jnp.minimum(jnp.maximum(x, 0), 6.0)


@register_decomp("leaky_relu")
def _leaky_relu_decomp(x, *, negative_slope=0.01):
    return jnp.where(x >= 0, x, negative_slope * x)


@register_decomp("elu")
def _elu_decomp(x, *, alpha=1.0):
    safe = jnp.where(x > 0, 0.0, x)
    return jnp.where(x > 0, x, alpha * jnp.expm1(safe))


@register_decomp("celu")
def _celu_decomp(x, *, alpha=1.0):
    return jnp.where(x > 0, x, alpha * jnp.expm1(x / alpha))


@register_decomp("selu")
def _selu_decomp(x, *, scale=1.0507009873554805,
                 alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@register_decomp("hardsigmoid")
def _hardsigmoid_decomp(x, *, slope=0.1666667, offset=0.5):
    return jnp.clip(slope * x + offset, 0.0, 1.0)


@register_decomp("hardswish")
def _hardswish_decomp(x):
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


@register_decomp("hardtanh")
def _hardtanh_decomp(x, *, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@register_decomp("softplus")
def _softplus_decomp(x, *, beta=1.0, threshold=20.0):
    return jnp.where(beta * x > threshold, x,
                     jnp.log1p(jnp.exp(beta * x)) / beta)


@register_decomp("log_sigmoid")
def _log_sigmoid_decomp(x):
    return -jnp.logaddexp(0.0, -x)


@register_decomp("mish")
def _mish_decomp(x):
    return x * jnp.tanh(jnp.logaddexp(x, 0.0))


@register_decomp("thresholded_relu")
def _thresholded_relu_decomp(x, *, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, value)


@register_decomp("glu")
def _glu_decomp(x, *, axis=-1):
    a1, a2 = jnp.split(x, 2, axis=axis)
    return a1 * _logistic(a2)


@register_decomp("swiglu")
def _swiglu_decomp(x, y=None):
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return x * _logistic(x) * y


@register_decomp("rsqrt")
def _rsqrt_decomp(x):
    return 1.0 / jnp.sqrt(x)


@register_decomp("reciprocal")
def _reciprocal_decomp(x):
    return 1.0 / x


@register_decomp("layer_norm")
def _layer_norm_decomp(x, *wb, axes, epsilon=1e-5, has_weight=False,
                       has_bias=False):
    # composite.h layer_norm_decomp: f32 compute, rsqrt(var + eps)
    af = x.astype(jnp.float32)
    mean = jnp.mean(af, axis=axes, keepdims=True)
    var = jnp.var(af, axis=axes, keepdims=True)
    out = (af - mean) / jnp.sqrt(var + epsilon)
    i = 0
    if has_weight:
        out = out * wb[i].astype(jnp.float32)
        i += 1
    if has_bias:
        out = out + wb[i].astype(jnp.float32)
    return out.astype(x.dtype)


@register_decomp("rms_norm")
def _rms_norm_decomp(x, *wb, axes, epsilon=1e-6, has_weight=False,
                     has_bias=False):
    af = x.astype(jnp.float32)
    ms = jnp.mean(af * af, axis=axes, keepdims=True)
    out = af * (1.0 / jnp.sqrt(ms + epsilon))
    i = 0
    if has_weight:
        out = out * wb[i].astype(jnp.float32)
        i += 1
    if has_bias:
        out = out + wb[i].astype(jnp.float32)
    return out.astype(x.dtype)


@register_decomp("dropout")
def _dropout_decomp(x, *, p, axis=None, mode="upscale_in_train", key=None):
    # composite.h dropout_decomp; the recorded rng key rides the attrs so
    # the decomposed program reproduces the SAME mask bit-for-bit
    if key is None:
        raise ValueError("dropout decomposition requires the recorded key")
    if axis is None:
        shape = x.shape
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


@register_decomp("mean")
def _mean_decomp(x, *, axis=None, keepdim=False):
    # composite.h mean_decomp: sum / numel-along-axes
    if axis is None:
        n = x.size
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        n = 1
        for a in axes:
            n *= x.shape[a]
    return jnp.sum(x, axis=axis, keepdims=keepdim) / jnp.asarray(
        n, x.dtype if jnp.issubdtype(x.dtype, jnp.floating)
        else jnp.float32)


@register_decomp("var")
def _var_decomp(x, *, axis=None, ddof=0, keepdim=False):
    if axis is None:
        n = x.size
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        n = 1
        for a in axes:
            n *= x.shape[a]
    mu = jnp.mean(x, axis=axis, keepdims=True)
    sq = (x - mu) * (x - mu)
    return jnp.sum(sq, axis=axis, keepdims=keepdim) / jnp.asarray(
        n - ddof, sq.dtype)


@register_decomp("std")
def _std_decomp(x, *, axis=None, ddof=0, keepdim=False):
    return jnp.sqrt(_var_decomp(x, axis=axis, ddof=ddof, keepdim=keepdim))


@register_decomp("stack")
def _stack_decomp(*xs, axis=0):
    # composite.h stack via unsqueeze + concat
    return jnp.concatenate([jnp.expand_dims(a, axis) for a in xs],
                           axis=axis)


@register_decomp("concat")
def _concat_decomp(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


@register_decomp("squeeze")
def _squeeze_decomp(x, *, axis=None):
    if axis is None:
        return x.reshape(tuple(s for s in x.shape if s != 1))
    real = tuple(i for i in axis
                 if x.shape[i if i >= 0 else x.ndim + i] == 1)
    if not real:
        return x
    drop = {i if i >= 0 else x.ndim + i for i in real}
    return x.reshape(tuple(s for i, s in enumerate(x.shape)
                           if i not in drop))


@register_decomp("unsqueeze")
def _unsqueeze_decomp(x, *, axis):
    out_nd = x.ndim + len(axis)
    norm = sorted(a if a >= 0 else out_nd + a for a in axis)
    shape = list(x.shape)
    for a in norm:
        shape.insert(a, 1)
    return x.reshape(tuple(shape))


@register_decomp("flatten")
def _flatten_decomp(x, *, start, stop):
    return x.reshape(x.shape[:start] + (-1,) + x.shape[stop + 1:])


@register_decomp("one_hot")
def _one_hot_decomp(x, *, num_classes):
    # composite.h one_hot via eq(unsqueeze(x), iota)
    classes = jnp.arange(num_classes, dtype=x.dtype if jnp.issubdtype(
        x.dtype, jnp.integer) else jnp.int32)
    return (x[..., None] == classes).astype(jnp.float32)


@register_decomp("clip")
def _clip_decomp(x, *, min=None, max=None):
    out = x
    if min is not None:
        out = jnp.maximum(out, min)
    if max is not None:
        out = jnp.minimum(out, max)
    return out


@register_decomp("scale")
def _scale_decomp(x, *, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def _reduce_rule(out, reduction):
    if reduction == "mean":
        return jnp.mean(out)
    if reduction == "sum":
        return jnp.sum(out)
    return out


@register_decomp("binary_cross_entropy")
def _bce_decomp(x, label, *w, reduction="mean", has_weight=False):
    a = jnp.clip(x, 1e-12, 1.0 - 1e-12)
    out = -(label * jnp.log(a) + (1 - label) * jnp.log(1 - a))
    if has_weight:
        out = out * w[0]
    return _reduce_rule(out, reduction)


@register_decomp("bce_with_logits")
def _bce_logits_decomp(x, label, *rest, reduction="mean",
                       has_weight=False, has_pos_weight=False):
    i = 0
    w = rest[i] if has_weight else None
    if has_weight:
        i += 1
    pw = rest[i] if has_pos_weight else None
    max_val = jnp.maximum(-x, 0)
    if pw is not None:
        log_w = (pw - 1) * label + 1
        out = (1 - label) * x + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(x))) + max_val)
    else:
        out = (1 - label) * x + jnp.log1p(jnp.exp(-jnp.abs(x))) + max_val
    if w is not None:
        out = out * w
    return _reduce_rule(out, reduction)


@register_decomp("mse_loss")
def _mse_decomp(x, label, *, reduction="mean"):
    return _reduce_rule((x - label) ** 2, reduction)
