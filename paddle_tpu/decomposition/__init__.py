"""paddle.decomposition (reference: python/paddle/decomposition/ —
register.py rule registry, decomp.py decompose(program, ops)).

The reference decomposes composite ops into a primitive set so backends
without the composite kernel (or the prim-based autodiff) can run them.
On XLA that role is largely moot — every op here already lowers to HLO
primitives — so this tier exists for (a) program-level rewrites that
want to see a smaller op vocabulary (custom passes, export), and (b)
reference-workflow compatibility. Rules rewrite the captured op-DAG
(static/graph.py) exactly like distributed/passes does: a registered
rule maps one recorded op name to a pure-jnp composition of primitive
ops, and ``decompose`` clones the program with matching nodes rewritten.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from ..static import graph as _g

__all__ = ["register_decomp", "get_decomp_rule", "decompose"]

_RULES: Dict[str, Callable] = {}


def register_decomp(op_name: str):
    """Register a decomposition rule for a recorded op name (reference:
    decomposition/register.py register_decomp). The rule is a pure
    array function replacing the op's fn with primitive jnp ops."""

    def deco(fn):
        _RULES[op_name] = fn
        return fn

    return deco


def get_decomp_rule(op_name: str) -> Optional[Callable]:
    return _RULES.get(op_name)


def decompose(fetches: List, ops: Optional[List[str]] = None) -> List:
    """Rewrite the program producing ``fetches`` so every op in ``ops``
    (default: all ops with registered rules) runs its primitive
    decomposition (reference: decomposition/decomp.py decompose:194).
    Returns new fetch handles over the rewritten DAG."""
    from ..distributed.passes import rewrite_program

    wanted = set(ops) if ops is not None else set(_RULES)

    from ..distributed.passes import _avals_of

    def transform(node, new_parents):
        rule = _RULES.get(node.name)
        if rule is None or node.name not in wanted:
            return _g.OpNode(node.fn, new_parents, node.out_avals,
                             node.name, node.single)
        # a rule only applies when it reproduces the recorded op's output
        # signature — an op instance whose closed-over attrs (axis, ...)
        # the generic rule doesn't model keeps its original fn
        try:
            out = jax.eval_shape(rule, *_avals_of(new_parents))
            outs = (out,) if not isinstance(out, (tuple, list)) \
                else tuple(out)
            ok = len(outs) == len(node.out_avals) and all(
                tuple(a.shape) == tuple(b.shape) and a.dtype == b.dtype
                for a, b in zip(outs, node.out_avals))
        except Exception:
            ok = False
        if not ok:
            return _g.OpNode(node.fn, new_parents, node.out_avals,
                             node.name, node.single)
        return _g.OpNode(rule, new_parents, node.out_avals,
                         f"{node.name}_decomposed", node.single)

    return rewrite_program(fetches, transform)


# ---- built-in rules for the classic composite set (reference
# decomposition/rules.py) ---------------------------------------------------

@register_decomp("softmax")
def _softmax_decomp(x, *rest):
    mx = jnp.max(x, axis=-1, keepdims=True)
    e = jnp.exp(x - mx)
    return e / jnp.sum(e, axis=-1, keepdims=True)


@register_decomp("log_softmax")
def _log_softmax_decomp(x, *rest):
    mx = jnp.max(x, axis=-1, keepdims=True)
    s = x - mx
    return s - jnp.log(jnp.sum(jnp.exp(s), axis=-1, keepdims=True))


@register_decomp("gelu")
def _gelu_decomp(x, *rest):
    # erf form (the reference's primitive gelu rule)
    return 0.5 * x * (1.0 + jax.lax.erf(x / jnp.sqrt(
        jnp.asarray(2.0, x.dtype))))


@register_decomp("silu")
def _silu_decomp(x, *rest):
    return x / (1.0 + jnp.exp(-x))


@register_decomp("mean")
def _mean_decomp(x, *rest):
    return jnp.sum(x) / x.size


@register_decomp("rsqrt")
def _rsqrt_decomp(x, *rest):
    return 1.0 / jnp.sqrt(x)
