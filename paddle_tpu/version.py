"""Version info (reference: python/paddle/version.py, generated)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
cuda_version = "False"     # no CUDA in the TPU build
cudnn_version = "False"
tpu = True
commit = "unknown"
with_pip_cuda_libraries = "OFF"


def show():
    print(f"paddle_tpu {full_version} (TPU-native; cuda: {cuda_version})")


def cuda():
    return cuda_version


def cudnn():
    return cudnn_version
