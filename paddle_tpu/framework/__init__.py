"""Framework-level utilities: default dtype, flags, ParamAttr, random
(reference: python/paddle/framework/, python/paddle/base/framework.py)."""
from __future__ import annotations

import threading

from ..core.dtype import convert_dtype
from .param_attr import ParamAttr  # noqa: F401

__all__ = ["set_default_dtype", "get_default_dtype", "set_flags", "get_flags",
           "ParamAttr", "seed"]


class _Defaults(threading.local):
    def __init__(self):
        self.dtype = convert_dtype("float32")


_defaults = _Defaults()


def set_default_dtype(d):
    _defaults.dtype = convert_dtype(d)


def get_default_dtype():
    return _defaults.dtype.name


# ------------------------------------------------------------------- flags
# The reference exposes ~185 runtime flags (paddle/common/flags.cc) settable
# via paddle.set_flags / env FLAGS_*. We keep the same surface with a simple
# registry; flags that map to JAX/XLA configs apply them on set.
import os

_FLAGS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_check_nan_inf_level": 0,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_embedding_deterministic": 0,
    "FLAGS_use_stride_kernel": True,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_log_memory_stats": False,
    "FLAGS_benchmark": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    # 64-bit dtype policy (core/dtype.py): False = documented narrowing
    # int64->int32 / float64->float32; True = raise instead of narrowing.
    "FLAGS_strict_dtype64": False,
}

# The remainder of the reference's exported-flag surface
# (paddle/common/flags.cc, ~185 PHI_DEFINE_EXPORTED_*). Grouped by
# relevance on TPU: "active" flags are read by this codebase; the rest are
# accepted (set_flags/get_flags/FLAGS_* env) so reference scripts that
# tune them keep running, and their values are visible to tooling.
_FLAGS.update({
    # numerics / debugging
    "FLAGS_low_precision_op_list": 0,
    "FLAGS_use_autotune": False,
    "FLAGS_use_fast_math": False,
    "FLAGS_sort_sum_gradient": False,
    "FLAGS_accuracy_check_atol_fp32": 1e-6,
    "FLAGS_accuracy_check_rtol_fp32": 1e-6,
    "FLAGS_accuracy_check_atol_fp16": 1e-3,
    "FLAGS_accuracy_check_rtol_fp16": 1e-3,
    "FLAGS_accuracy_check_atol_bf16": 1e-2,
    "FLAGS_accuracy_check_rtol_bf16": 1e-2,
    # executor / compiler (CINN role is played by XLA)
    "FLAGS_use_cinn": False,
    "FLAGS_allow_cinn_ops": "",
    "FLAGS_deny_cinn_ops": "",
    "FLAGS_enable_pir_api": True,
    "FLAGS_enable_pir_in_executor": True,
    "FLAGS_pir_apply_inplace_pass": 1,
    "FLAGS_jit_engine_type": "xla",
    "FLAGS_print_ir": False,
    "FLAGS_enable_cse_in_dy2st": False,
    # memory
    "FLAGS_fraction_of_cpu_memory_to_use": 1.0,
    "FLAGS_initial_cpu_memory_in_mb": 500,
    "FLAGS_alloc_fill_value": -1,
    "FLAGS_enable_record_memory": False,
    "FLAGS_use_shm_cache": False,
    "FLAGS_dataloader_use_file_descriptor": False,
    # distributed / comm
    "FLAGS_nccl_blocking_wait": False,
    "FLAGS_benchmark_nccl": False,
    "FLAGS_enable_async_trace": False,
    "FLAGS_async_trace_count": 5,
    "FLAGS_dynamic_static_unified_comm": True,
    "FLAGS_eager_communication_connection": False,
    "FLAGS_dist_threadpool_size": 0,
    "FLAGS_communicator_max_merge_var_num": 20,
    "FLAGS_communicator_send_queue_size": 20,
    "FLAGS_enable_auto_parallel_align_mode": False,
    # profiling / tracing
    "FLAGS_host_trace_level": 1,
    # threading
    "FLAGS_inner_op_parallelism": 0,
    "FLAGS_paddle_num_threads": 1,
    # conv/cudnn-era knobs accepted for script compat (no-op on TPU)
    "FLAGS_conv_workspace_size_limit": 512,
    "FLAGS_cudnn_exhaustive_search": False,
    "FLAGS_cudnn_exhaustive_search_times": -1,
    "FLAGS_cudnn_batchnorm_spatial_persistent": False,
    "FLAGS_conv2d_disable_cudnn": False,
    "FLAGS_enable_cudnn_frontend": False,
    "FLAGS_gemm_use_half_precision_compute_type": False,
    # dataloader / misc
    "FLAGS_set_to_1d": True,
    "FLAGS_search_cache_max_number": 1000000,
    "FLAGS_tensor_operants_mode": "eager",
    "FLAGS_use_mkldnn": False,
    "FLAGS_fused_multi_transformer_op_use_mbfmha": False,
    "FLAGS_multi_block_attention_min_partition_size": 512,
})
def _coerce_flag(default, raw: str):
    """Env values arrive as strings: coerce by the default's type so
    FLAGS_use_fast_math=0 means False, not the truthy string '0'."""
    if isinstance(default, bool):
        return raw.strip().lower() not in ("0", "false", "off", "")
    if isinstance(default, int) and not isinstance(default, bool):
        try:
            return int(raw)
        except ValueError:
            return default
    if isinstance(default, float):
        try:
            return float(raw)
        except ValueError:
            return default
    return raw


for _k in list(_FLAGS):
    if _k in os.environ:
        _FLAGS[_k] = _coerce_flag(_FLAGS[_k], os.environ[_k])


def set_flags(flags: dict):
    from ..amp import debugging as dbg

    for k, v in flags.items():
        _FLAGS[k] = v
        if k == "FLAGS_check_nan_inf":
            cfg = dbg.TensorCheckerConfig(enable=bool(v))
            if v:
                dbg.enable_tensor_checker(cfg)
            else:
                dbg.disable_tensor_checker()


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def seed(s):
    from ..core.random import seed as _seed

    _seed(s)
    import numpy as np

    np.random.seed(s % (2 ** 32))
    return s


from .io_utils import load, save  # noqa: F401,E402
