"""Framework-level utilities: default dtype, flags, ParamAttr, random
(reference: python/paddle/framework/, python/paddle/base/framework.py)."""
from __future__ import annotations

import threading

from ..core.dtype import convert_dtype
from .param_attr import ParamAttr  # noqa: F401

__all__ = ["set_default_dtype", "get_default_dtype", "set_flags", "get_flags",
           "ParamAttr", "seed"]


class _Defaults(threading.local):
    def __init__(self):
        self.dtype = convert_dtype("float32")


_defaults = _Defaults()


def set_default_dtype(d):
    _defaults.dtype = convert_dtype(d)


def get_default_dtype():
    return _defaults.dtype.name


# ------------------------------------------------------------------- flags
# The reference exposes ~185 runtime flags (paddle/common/flags.cc) settable
# via paddle.set_flags / env FLAGS_*. We keep the same surface with a simple
# registry; flags that map to JAX/XLA configs apply them on set.
import os

_FLAGS = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_check_nan_inf_level": 0,
    "FLAGS_cudnn_deterministic": False,
    "FLAGS_embedding_deterministic": 0,
    "FLAGS_use_stride_kernel": True,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_max_inplace_grad_add": 0,
    "FLAGS_log_memory_stats": False,
    "FLAGS_benchmark": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,
}
for _k in list(_FLAGS):
    if _k in os.environ:
        _FLAGS[_k] = os.environ[_k]


def set_flags(flags: dict):
    from ..amp import debugging as dbg

    for k, v in flags.items():
        _FLAGS[k] = v
        if k == "FLAGS_check_nan_inf":
            cfg = dbg.TensorCheckerConfig(enable=bool(v))
            if v:
                dbg.enable_tensor_checker(cfg)
            else:
                dbg.disable_tensor_checker()


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def seed(s):
    from ..core.random import seed as _seed

    _seed(s)
    import numpy as np

    np.random.seed(s % (2 ** 32))
    return s


from .io_utils import load, save  # noqa: F401,E402
