"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:773,1020).

Serialization: pickle container with tensors stored as numpy arrays
(bfloat16 saved as uint16 view + dtype tag so numpy-only readers work).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from ..core.tensor import Tensor

__all__ = ["save", "load"]

_BF16_TAG = "__bf16__"


def _pack(obj):
    if isinstance(obj, Tensor):
        arr = np.asarray(obj._data)
        if str(obj._data.dtype) == "bfloat16":
            return {_BF16_TAG: True,
                    "data": np.asarray(obj._data.view(np.uint16))
                    if hasattr(obj._data, "view") else arr.astype(np.float32)}
        return {"__tensor__": True, "data": arr,
                "stop_gradient": obj.stop_gradient}
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj):
    import jax.numpy as jnp

    if isinstance(obj, dict):
        if obj.get("__tensor__"):
            return Tensor(jnp.asarray(obj["data"]),
                          stop_gradient=obj.get("stop_gradient", True))
        if obj.get(_BF16_TAG):
            d = obj["data"]
            if d.dtype == np.uint16:
                return Tensor(jnp.asarray(d).view(jnp.bfloat16))
            return Tensor(jnp.asarray(d, dtype=jnp.bfloat16))
        return {k: _unpack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        return _unpack(pickle.load(f))
