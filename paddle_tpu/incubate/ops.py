"""paddle.incubate op surface (reference: python/paddle/incubate/
__init__.py — segment ops tensor/math.py:segment_*, graph ops
operators/graph_*.py, identity_loss, softmax_mask_fuse*).

TPU-native: segment reductions are jax.ops.segment_* (one XLA scatter),
graph sampling runs on host (dynamic shapes are eager-only, like the
reference's CPU fallback path), and the mask-fuse ops are plain fused
elementwise+softmax XLA programs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dtype import int64_canonical
from ..core.tensor import Tensor
from ..ops._helpers import as_tensor, run_op, unwrap

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "graph_send_recv", "graph_khop_sampler", "graph_reindex",
    "graph_sample_neighbors", "identity_loss", "softmax_mask_fuse",
    "softmax_mask_fuse_upper_triangle",
]


def _segment(op_name, jfn, data, segment_ids, fill=0.0):
    ids = unwrap(as_tensor(segment_ids)).astype(jnp.int32)
    n = int(jnp.max(ids)) + 1 if ids.size else 0

    def fn(a):
        out = jfn(a, ids, num_segments=n)
        return out

    return run_op(fn, [as_tensor(data)], name=op_name)


def segment_sum(data, segment_ids, name=None):
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    ids = unwrap(as_tensor(segment_ids)).astype(jnp.int32)
    n = int(jnp.max(ids)) + 1 if ids.size else 0

    def fn(a):
        s = jax.ops.segment_sum(a, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((a.shape[0],), a.dtype), ids,
                                  num_segments=n)
        cnt = jnp.maximum(cnt, 1.0)
        return s / cnt.reshape((n,) + (1,) * (a.ndim - 1))

    return run_op(fn, [as_tensor(data)], name="segment_mean")


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max", jax.ops.segment_max, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min", jax.ops.segment_min, data, segment_ids)


def graph_send_recv(x, src_index, dst_index, pool_type="sum",
                    out_size=None, name=None):
    """Gather x rows at src_index, scatter-reduce onto dst_index
    (reference: incubate/operators/graph_send_recv.py)."""
    src = unwrap(as_tensor(src_index)).astype(jnp.int32)
    dst = unwrap(as_tensor(dst_index)).astype(jnp.int32)
    x_t = as_tensor(x)
    n = int(out_size) if out_size is not None else x_t.shape[0]
    red = {"sum": jax.ops.segment_sum, "mean": None,
           "max": jax.ops.segment_max, "min": jax.ops.segment_min}
    if pool_type not in red:
        raise ValueError(f"pool_type must be one of {list(red)}")

    def fn(a):
        msgs = a[src]
        if pool_type == "mean":
            s = jax.ops.segment_sum(msgs, dst, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0],), a.dtype), dst, num_segments=n)
            return s / jnp.maximum(cnt, 1.0).reshape(
                (n,) + (1,) * (a.ndim - 1))
        out = red[pool_type](msgs, dst, num_segments=n)
        if pool_type in ("max", "min"):
            # empty segments come back ±inf; reference fills 0
            out = jnp.where(jnp.isfinite(out), out, 0.0)
        return out

    return run_op(fn, [x_t], name="graph_send_recv")


def graph_sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                           eids=None, return_eids=False, perm_buffer=None,
                           flag_perm_buffer=False, name=None):
    """Uniform neighbor sampling on a CSC graph (reference:
    incubate/operators/graph_sample_neighbors.py). Host-side: output is
    data-dependent-shaped, an eager-only op by design."""
    rowv = np.asarray(unwrap(as_tensor(row)))
    colptrv = np.asarray(unwrap(as_tensor(colptr)))
    nodes = np.asarray(unwrap(as_tensor(input_nodes))).reshape(-1)
    eidv = np.asarray(unwrap(as_tensor(eids))) if eids is not None else None
    rng = np.random.default_rng()
    out_neighbors, out_count, out_eids = [], [], []
    for nd in nodes:
        lo, hi = int(colptrv[nd]), int(colptrv[nd + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = np.arange(lo, hi)
        else:
            sel = lo + rng.choice(deg, size=sample_size, replace=False)
        out_neighbors.append(rowv[sel])
        out_count.append(len(sel))
        if return_eids and eidv is not None:
            out_eids.append(eidv[sel])
    neigh = (np.concatenate(out_neighbors) if out_neighbors
             else np.zeros((0,), rowv.dtype))
    cnt = np.asarray(out_count, np.int32)
    res = (Tensor(jnp.asarray(neigh.astype(np.int32))),
           Tensor(jnp.asarray(cnt)))
    if return_eids:
        e = (np.concatenate(out_eids) if out_eids
             else np.zeros((0,), np.int32))
        return res + (Tensor(jnp.asarray(e.astype(np.int32))),)
    return res


def graph_reindex(x, neighbors, count, value_buffer=None, index_buffer=None,
                  flag_buffer_hashtable=False, name=None):
    """Compact global node ids to local ids (reference:
    incubate/operators/graph_reindex.py). Host-side, eager-only."""
    xs = np.asarray(unwrap(as_tensor(x))).reshape(-1)
    nb = np.asarray(unwrap(as_tensor(neighbors))).reshape(-1)
    cnt = np.asarray(unwrap(as_tensor(count))).reshape(-1)
    mapping = {}
    for nd in xs.tolist():
        if nd not in mapping:
            mapping[nd] = len(mapping)
    for nd in nb.tolist():
        if nd not in mapping:
            mapping[nd] = len(mapping)
    reindex_src = np.asarray([mapping[v] for v in nb.tolist()], np.int32)
    # dst of edge j is the input node owning that neighbor block
    dst = np.repeat(np.arange(len(xs), dtype=np.int32), cnt)
    nodes = np.asarray(list(mapping.keys()),
                       dtype=np.asarray(xs).dtype)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(nodes.astype(np.int32))))


def graph_khop_sampler(row, colptr, input_nodes, sample_sizes,
                       sorted_eids=None, return_eids=False, name=None):
    """Multi-hop sampling + reindex (reference:
    incubate/operators/graph_khop_sampler.py)."""
    cur = as_tensor(input_nodes)
    all_src, all_cnt = [], []
    frontier = cur
    for size in sample_sizes:
        neigh, cnt = graph_sample_neighbors(row, colptr, frontier,
                                            sample_size=size)
        all_src.append(np.asarray(unwrap(neigh)))
        all_cnt.append(np.asarray(unwrap(cnt)))
        frontier = neigh
    neighbors = np.concatenate(all_src) if all_src else np.zeros(0, np.int32)
    counts = np.concatenate(all_cnt) if all_cnt else np.zeros(0, np.int32)
    # counts from hops beyond the first attach to sampled frontier nodes;
    # reindex against the original seeds + every frontier
    seeds = np.asarray(unwrap(cur)).reshape(-1)
    seed_list = seeds
    for hop_src, _ in zip(all_src, all_cnt):
        seed_list = np.concatenate([seed_list, hop_src])
    mapping = {}
    for nd in seed_list.tolist():
        if nd not in mapping:
            mapping[nd] = len(mapping)
    reindex_src = np.asarray([mapping[v] for v in neighbors.tolist()],
                             np.int32)
    dst_nodes = []
    base = seeds
    for hop_src, hop_cnt in zip(all_src, all_cnt):
        dst_nodes.append(np.repeat(base[:len(hop_cnt)], hop_cnt))
        base = hop_src
    dst = (np.concatenate(dst_nodes) if dst_nodes
           else np.zeros(0, seeds.dtype))
    reindex_dst = np.asarray([mapping[v] for v in dst.tolist()], np.int32)
    nodes = np.asarray(list(mapping.keys()), np.int32)
    out = (Tensor(jnp.asarray(reindex_src)),
           Tensor(jnp.asarray(reindex_dst)),
           Tensor(jnp.asarray(counts.astype(np.int32))),
           Tensor(jnp.asarray(nodes)))
    return out


def identity_loss(x, reduction="none", name=None):
    """reference: incubate/operators/identity_loss — marks x as the loss;
    reduction in {none, sum, mean}."""
    x = as_tensor(x)
    if reduction in (0, "sum"):
        return run_op(jnp.sum, [x], name="identity_loss")
    if reduction in (1, "mean"):
        return run_op(jnp.mean, [x], name="identity_loss")
    return run_op(lambda a: a, [x], name="identity_loss")


def softmax_mask_fuse(x, mask, name=None):
    """reference: incubate/operators/softmax_mask_fuse.py — fused
    (x + mask) softmax on the last axis; XLA fuses this into one kernel."""
    return run_op(lambda a, m: jax.nn.softmax(a + m, axis=-1),
                  [as_tensor(x), as_tensor(mask)], name="softmax_mask_fuse")


def softmax_mask_fuse_upper_triangle(x, name=None):
    """reference: incubate/operators/softmax_mask_fuse_upper_triangle.py —
    causal-masked softmax (scores masked above the diagonal)."""
    def fn(a):
        q, k = a.shape[-2], a.shape[-1]
        causal = jnp.tril(jnp.ones((q, k), bool))
        return jax.nn.softmax(jnp.where(causal, a, -1e9), axis=-1)

    return run_op(fn, [as_tensor(x)], name="softmax_mask_fuse_ut")
