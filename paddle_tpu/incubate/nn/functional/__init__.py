"""Fused-op functional APIs (reference: python/paddle/incubate/nn/functional/).

These are the TPU fused tier: Pallas kernels where profitable, XLA-fused
compositions otherwise (XLA already fuses most of what the reference needed
hand-written CUDA for)."""
from .flash_attention import (  # noqa: F401
    flash_attention,
    flash_attn_unpadded,
    scaled_dot_product_attention,
)
from .serving import (  # noqa: F401
    blha_get_max_len,
    block_multihead_attention,
    fused_bias_act,
    fused_feedforward,
    fused_matmul_bias,
    fused_moe,
    fused_multi_head_attention,
    fused_multi_transformer,
    masked_multihead_attention,
    variable_length_memory_efficient_attention,
)
from .fused_ops import (  # noqa: F401
    fused_bias_dropout_residual_layer_norm,
    fused_dropout_add,
    fused_layer_norm,
    fused_linear,
    fused_linear_activation,
    fused_rms_norm,
    fused_rotary_position_embedding,
    swiglu,
)
