"""Fused-op functional APIs (reference: python/paddle/incubate/nn/functional/).

These are the TPU fused tier: Pallas kernels where profitable, XLA-fused
compositions otherwise (XLA already fuses most of what the reference needed
hand-written CUDA for)."""
from .flash_attention import (  # noqa: F401
    flash_attention,
    flash_attn_unpadded,
    scaled_dot_product_attention,
)
from .fused_ops import (  # noqa: F401
    fused_bias_dropout_residual_layer_norm,
    fused_dropout_add,
    fused_layer_norm,
    fused_linear,
    fused_linear_activation,
    fused_rms_norm,
    fused_rotary_position_embedding,
    swiglu,
)
