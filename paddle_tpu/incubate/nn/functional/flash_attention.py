"""Flash attention (reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu,
python/paddle/nn/functional/flash_attention.py).

Layout: [batch, seq, num_heads, head_dim] (paddle convention).

On TPU this dispatches to the Pallas flash-attention kernel
(:mod:`paddle_tpu.incubate.nn.pallas.flash_attn`) when the shapes tile onto
the MXU (seq % block == 0, head_dim in {64,128,256}); otherwise it falls back
to an XLA softmax composition, which XLA still fuses well.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ....core import random as _rng
from ....ops._helpers import as_tensor, run_op, unwrap

__all__ = ["flash_attention", "flash_attn_unpadded", "scaled_dot_product_attention"]


import threading

_recompute_tls = threading.local()


def _entering_recompute():
    """Context marker set by the recompute engine: the Pallas custom-vjp
    does not compose with jax.checkpoint's re-linearization (the raw fwd
    pallas_call would be jvp'd), so attention inside a rematerialized
    block uses the XLA composition (within ~15% at the shapes where both
    apply; tools/tune_flash_attn.py)."""

    class _Ctx:
        def __enter__(self):
            _recompute_tls.depth = getattr(_recompute_tls, "depth", 0) + 1

        def __exit__(self, *a):
            _recompute_tls.depth -= 1

    return _Ctx()


def _use_pallas(q_shape, kv_seq, head_dim):
    try:
        from ..pallas import flash_attn  # noqa: F401
    except Exception:
        return False
    if getattr(_recompute_tls, "depth", 0):
        return False
    if jax.default_backend() != "tpu":
        return False
    seq = q_shape[1]
    # measured on v5e (tools/tune_flash_attn.py): at seq<=512 the XLA
    # softmax composition beats the Pallas kernel fwd+bwd (13ms vs 16ms
    # per 12 layers at bench shapes) because the s^2 logits still fit HBM
    # comfortably; the flash kernel's O(s) memory wins from ~1k sequence
    # where the materialized [b,h,s,s] tensor starts to dominate
    return (head_dim in (64, 128, 256) and seq % 128 == 0
            and kv_seq % 128 == 0 and seq >= 1024)


def _xla_attention(q, k, v, causal, scale=None):
    """Reference composition: XLA fuses this into a reasonable kernel chain.

    Stays in the paddle [b, s, h, d] layout end to end — the head/seq
    permutation is folded into the dot_general dimension numbers instead of
    materialized transposes (measured ~20% faster fwd+bwd at bench shapes
    on v5e, tools/probe_attn_paths2.py)."""
    s = scale if scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * s
    if causal:
        qlen, klen = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), dtype=bool), klen - qlen)
        logits = jnp.where(mask, logits, -1e30)
    w = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    head_dim = q.shape[-1]

    if _use_pallas(tuple(q.shape), k.shape[1], head_dim) \
            and not return_softmax:
        from ..pallas.flash_attn import flash_attention as pallas_fa

        out = run_op(
            functools.partial(pallas_fa, causal=causal),
            [q, k, v], name="flash_attention",
        )
    else:
        out = run_op(
            lambda qa, ka, va: _xla_attention(qa, ka, va, causal),
            [q, k, v], name="flash_attention",
        )

    if dropout > 0.0 and training:
        key_ = _rng.next_key()
        out = run_op(
            lambda o: jnp.where(
                jax.random.bernoulli(key_, 1.0 - dropout, o.shape),
                o / (1.0 - dropout), 0.0).astype(o.dtype),
            [out], name="attn_dropout",
        )
    if return_softmax:
        return out, None
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False, name=None):
    """Varlen flash attention: segment-masked single-sequence attention.

    q/k/v: [total_tokens, num_heads, head_dim]; cu_seqlens: [batch+1].
    """
    q, k, v = as_tensor(query), as_tensor(key), as_tensor(value)
    cq = unwrap(as_tensor(cu_seqlens_q)).astype(jnp.int32)
    ck = unwrap(as_tensor(cu_seqlens_k)).astype(jnp.int32)

    def fn(qa, ka, va):
        tq = qa.shape[0]
        tk = ka.shape[0]
        # segment id per token
        seg_q = jnp.cumsum(
            jnp.zeros(tq, jnp.int32).at[cq[1:-1]].add(1))
        seg_k = jnp.cumsum(
            jnp.zeros(tk, jnp.int32).at[ck[1:-1]].add(1))
        s = scale if scale is not None else qa.shape[-1] ** -0.5
        logits = jnp.einsum("qhd,khd->hqk", qa, ka,
                            preferred_element_type=jnp.float32) * s
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(tq) - jnp.take(cq, seg_q)
            pos_k = jnp.arange(tk) - jnp.take(ck, seg_k)
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        logits = jnp.where(mask[None], logits, -1e30)
        w = jax.nn.softmax(logits, axis=-1).astype(va.dtype)
        return jnp.einsum("hqk,khd->qhd", w, va)

    out = run_op(fn, [q, k, v], name="flash_attn_unpadded")
    return out, None


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    from ....nn.functional.common import scaled_dot_product_attention as sdpa

    return sdpa(query, key, value, attn_mask, dropout_p, is_causal, training)
