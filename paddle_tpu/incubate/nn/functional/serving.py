"""Serving fused-op tier (reference: python/paddle/incubate/nn/functional/
block_multihead_attention.py, masked_multihead_attention.py, fused_moe.py,
fused_transformer.py, variable_length_memory_efficient_attention.py,
fused_matmul_bias.py, fused_bias_act.py, blha_get_max_len.py).

TPU-native design: every API is ONE jit-able jnp/Pallas program —
- decode-phase attention rides the Pallas paged-attention kernel
  (incubate/nn/pallas/paged_attention.py) when every sequence is in
  decode; mixed prefill/decode batches run the XLA fused gather path;
- the quant knobs (int8 cache scales, shift/smooth) present in the CUDA
  kernels raise NotImplementedError loudly instead of silently ignoring;
- fused_multi_transformer is a statically-unrolled layer loop so XLA sees
  the whole stack (the per-token fused decode engine for generation lives
  in models/generation.py — this API is the reference-compatible surface).
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....ops._helpers import as_tensor, run_op, unwrap

__all__ = ["blha_get_max_len", "block_multihead_attention",
           "masked_multihead_attention", "fused_moe",
           "fused_multi_transformer", "fused_multi_head_attention",
           "fused_feedforward", "fused_matmul_bias", "fused_bias_act",
           "variable_length_memory_efficient_attention"]


def _reject_quant(**kw):
    on = []
    for k, v in kw.items():
        if v is None or v is False:
            continue
        if isinstance(v, (int, float)) and v == -1:
            continue
        if isinstance(v, str) and v == "default":
            continue
        on.append(k)
    if on:
        raise NotImplementedError(
            f"int8/smooth-quant serving args {on} are CUDA-kernel specific; "
            "the TPU build serves bf16 caches (weight-int8 decode lives in "
            "models/generation.py decode_quant).")


def blha_get_max_len(seq_lens_encoder, seq_lens_decoder, batch_size,
                     name=None):
    """reference: blha_get_max_len.py — (max encoder len, max decoder len)
    for block_multihead_attention kernel dispatch."""
    enc = unwrap(as_tensor(seq_lens_encoder))
    dec = unwrap(as_tensor(seq_lens_decoder))
    return (Tensor(jnp.max(enc).reshape(1)),
            Tensor(jnp.max(dec).reshape(1)))


def _apply_rope(q, k, pos, rope_theta=10000.0, neox=False):
    """Rotary embedding at integer positions pos [*]; q/k [..., H, D]."""
    d = q.shape[-1]
    half = d // 2
    inv = rope_theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = pos[..., None].astype(jnp.float32) * inv       # [*, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    cos = cos[..., None, :]
    sin = sin[..., None, :]

    def rot(x):
        if neox:
            x1, x2 = x[..., :half], x[..., half:]
            return jnp.concatenate([x1 * cos - x2 * sin,
                                    x2 * cos + x1 * sin], -1)
        x1, x2 = x[..., 0::2], x[..., 1::2]
        out = jnp.stack([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
        return out.reshape(x.shape)

    return rot(q.astype(jnp.float32)).astype(q.dtype), \
        rot(k.astype(jnp.float32)).astype(k.dtype)


def masked_multihead_attention(
        x, cache_kv=None, bias=None, src_mask=None, cum_offsets=None,
        sequence_lengths=None, rotary_tensor=None, beam_cache_offset=None,
        qkv_out_scale=None, out_shift=None, out_smooth=None, seq_len=1,
        rotary_emb_dims=0, use_neox_rotary_style=False,
        compute_dtype="default", out_scale=-1, quant_round_type=1,
        quant_max_bound=127.0, quant_min_bound=-127.0, name=None):
    """Single-token decode MHA over a dense KV cache (reference:
    masked_multihead_attention.py; CUDA masked_multihead_attention_kernel).

    x: [B, 3*H*D] (qkv of the new token); cache_kv: [2, B, H, max_seq, D];
    sequence_lengths: [B] current cached length. Returns (out, cache_kv).
    """
    _reject_quant(qkv_out_scale=qkv_out_scale, out_shift=out_shift,
                  out_smooth=out_smooth,
                  out_scale=None if out_scale == -1 else out_scale)
    xt = as_tensor(x)
    cache = unwrap(as_tensor(cache_kv))
    _, b, h, max_seq, d = cache.shape
    lens = (unwrap(as_tensor(sequence_lengths)).astype(jnp.int32)
            if sequence_lengths is not None
            else jnp.zeros((b,), jnp.int32))
    bias_t = as_tensor(bias) if bias is not None else None
    mask_t = as_tensor(src_mask) if src_mask is not None else None

    def fn(xa, *rest):
        i = 0
        xa2 = xa
        if bias_t is not None:
            xa2 = xa2 + rest[i]
            i += 1
        qkv = xa2.reshape(b, 3, h, d)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]      # [B, H, D]
        if rotary_emb_dims > 0 or rotary_tensor is not None:
            q, k = _apply_rope(q, k, lens, neox=use_neox_rotary_style)
        ck = cache[0].at[jnp.arange(b), :, lens].set(k)  # write new k
        cv = cache[1].at[jnp.arange(b), :, lens].set(v)
        scores = jnp.einsum("bhd,bhsd->bhs", q, ck) * (d ** -0.5)
        pos_ok = jnp.arange(max_seq)[None, :] <= lens[:, None]
        scores = jnp.where(pos_ok[:, None, :], scores, -1e9)
        if mask_t is not None:
            m = rest[i]
            scores = scores + m.reshape(b, 1, -1)[..., :max_seq]
        p = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhs,bhsd->bhd", p, cv).reshape(b, h * d)
        return out, jnp.stack([ck, cv])

    args = [xt] + ([bias_t] if bias_t is not None else []) \
        + ([mask_t] if mask_t is not None else [])
    out, new_cache = run_op(fn, args, name="masked_multihead_attention")
    if isinstance(cache_kv, Tensor):
        cache_kv._data = new_cache._data      # kernel is in-place on cache
    return out, new_cache


def variable_length_memory_efficient_attention(
        query, key, value, seq_lens, kv_seq_lens, mask=None, scale=None,
        causal=False, pre_cache_length=0, name=None):
    """reference: variable_length_memory_efficient_attention.py (cutlass
    varlen kernel). q [B,H,S,D], k/v [B,KH,S,D], per-batch lens [B(,1)]."""
    q = as_tensor(query)
    b, h, s, d = q.shape
    ql = unwrap(as_tensor(seq_lens)).reshape(-1).astype(jnp.int32)
    kl = unwrap(as_tensor(kv_seq_lens)).reshape(-1).astype(jnp.int32)
    sc = scale if scale is not None else d ** -0.5
    mask_t = as_tensor(mask) if mask is not None else None

    def fn(qa, ka, va, *rest):
        kh = ka.shape[1]
        if kh != h:
            ka = jnp.repeat(ka, h // kh, axis=1)
            va = jnp.repeat(va, h // kh, axis=1)
        sk = ka.shape[2]
        scores = jnp.einsum("bhqd,bhkd->bhqk", qa, ka) * sc
        okq = jnp.arange(s)[None, :] < ql[:, None]           # [B, S]
        okk = jnp.arange(sk)[None, :] < kl[:, None]
        allow = okq[:, None, :, None] & okk[:, None, None, :]
        if causal:
            allow = allow & (jnp.arange(s)[:, None]
                             >= jnp.arange(sk)[None, :] - pre_cache_length
                             )[None, None]
        if rest:
            scores = scores + rest[0]
        scores = jnp.where(allow, scores, -1e9)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, va)

    args = [q, as_tensor(key), as_tensor(value)]
    if mask_t is not None:
        args.append(mask_t)
    return run_op(fn, args, name="varlen_mem_efficient_attention")


def block_multihead_attention(
        qkv, key_cache, value_cache, seq_lens_encoder, seq_lens_decoder,
        seq_lens_this_time, padding_offsets, cum_offsets, cu_seqlens_q,
        cu_seqlens_k, block_tables, pre_key_cache=None, pre_value_cache=None,
        cache_k_quant_scales=None, cache_v_quant_scales=None,
        cache_k_dequant_scales=None, cache_v_dequant_scales=None,
        qkv_out_scale=None, qkv_bias=None, out_shift=None, out_smooth=None,
        max_enc_len_this_time=None, max_dec_len_this_time=None,
        rope_emb=None, mask=None, tgt_mask=None, max_seq_len=-1,
        block_size=64, use_neox_style=False, use_dynamic_cachekv_quant=False,
        quant_round_type=1, quant_max_bound=127.0, quant_min_bound=-127.0,
        out_scale=-1, compute_dtype="default", rope_theta=10000.0,
        name=None):
    """Unified prefill+decode attention over a PAGED block KV cache
    (reference: block_multihead_attention.py over
    block_multi_head_attention_kernel.cu).

    qkv: [token_num, (q_h + 2*kv_h)*D] packed varlen tokens;
    key/value_cache: [max_block_num, kv_h, block_size, D];
    block_tables: [B, max_blocks_per_seq] int32; per-seq lens tell which
    phase each sequence is in (encoder>0 => prefill tokens this call,
    else one decode token attending over seq_lens_decoder cached + self).
    Returns (out, qkv, key_cache, value_cache) like the reference (caches
    updated in place).
    """
    _reject_quant(cache_k_quant_scales=cache_k_quant_scales,
                  cache_v_quant_scales=cache_v_quant_scales,
                  qkv_out_scale=qkv_out_scale, out_shift=out_shift,
                  out_smooth=out_smooth,
                  use_dynamic_cachekv_quant=use_dynamic_cachekv_quant)
    import numpy as np

    qkv_t = as_tensor(qkv)
    kc = unwrap(as_tensor(key_cache))
    vc = unwrap(as_tensor(value_cache))
    n_blocks, kv_h, blk, d = kc.shape
    enc = np.asarray(unwrap(as_tensor(seq_lens_encoder))).reshape(-1)
    dec = np.asarray(unwrap(as_tensor(seq_lens_decoder))).reshape(-1)
    this = np.asarray(unwrap(as_tensor(seq_lens_this_time))).reshape(-1)
    cuq = np.asarray(unwrap(as_tensor(cu_seqlens_q))).reshape(-1)
    bt = unwrap(as_tensor(block_tables)).astype(jnp.int32)
    b = enc.shape[0]
    total = int(qkv_t.shape[0])
    width = qkv_t.shape[1]
    q_h = width // d - 2 * kv_h
    qkv_bias_t = as_tensor(qkv_bias) if qkv_bias is not None else None

    def fn(qkva, *rest):
        a = qkva + rest[0] if qkv_bias_t is not None else qkva
        a = a.reshape(total, q_h + 2 * kv_h, d)
        outs = jnp.zeros((total, q_h, d), a.dtype)
        new_kc, new_vc = kc, vc
        for i in range(b):
            n_tok = int(this[i])
            if n_tok == 0:
                continue
            t0 = int(cuq[i])
            toks = a[t0:t0 + n_tok]
            qi = toks[:, :q_h]                      # [L, qh, D]
            ki = toks[:, q_h:q_h + kv_h]
            vi = toks[:, q_h + kv_h:]
            start = int(dec[i]) if enc[i] == 0 else 0
            pos = start + jnp.arange(n_tok)
            if rope_emb is not None:
                qi, ki = _apply_rope(qi, ki, pos, rope_theta,
                                     use_neox_style)
            # scatter new k/v into the paged cache
            slots = bt[i, pos // blk] * blk + pos % blk   # [L]
            kc_flat = new_kc.swapaxes(0, 1).reshape(kv_h, -1, d)
            vc_flat = new_vc.swapaxes(0, 1).reshape(kv_h, -1, d)
            kc_flat = kc_flat.at[:, slots].set(ki.swapaxes(0, 1))
            vc_flat = vc_flat.at[:, slots].set(vi.swapaxes(0, 1))
            new_kc = kc_flat.reshape(kv_h, n_blocks, blk, d).swapaxes(0, 1)
            new_vc = vc_flat.reshape(kv_h, n_blocks, blk, d).swapaxes(0, 1)
            # gather this sequence's full context and attend causally
            ctx_len = start + n_tok
            cpos = jnp.arange(ctx_len)
            cslots = bt[i, cpos // blk] * blk + cpos % blk
            keys = new_kc.swapaxes(0, 1).reshape(kv_h, -1, d)[:, cslots]
            vals = new_vc.swapaxes(0, 1).reshape(kv_h, -1, d)[:, cslots]
            if kv_h != q_h:
                keys = jnp.repeat(keys, q_h // kv_h, axis=0)
                vals = jnp.repeat(vals, q_h // kv_h, axis=0)
            scores = jnp.einsum("lhd,hkd->hlk", qi, keys) * (d ** -0.5)
            causal = pos[:, None] >= cpos[None, :]
            scores = jnp.where(causal[None], scores, -1e9)
            p = jax.nn.softmax(scores, axis=-1)
            oi = jnp.einsum("hlk,hkd->lhd", p, vals)
            outs = outs.at[t0:t0 + n_tok].set(oi.astype(a.dtype))
        return outs.reshape(total, q_h * d), new_kc, new_vc

    args = [qkv_t] + ([qkv_bias_t] if qkv_bias_t is not None else [])
    out, nk, nv = run_op(fn, args, name="block_multihead_attention")
    if isinstance(key_cache, Tensor):
        key_cache._data = nk._data
    if isinstance(value_cache, Tensor):
        value_cache._data = nv._data
    return out, qkv, key_cache, value_cache


def fused_moe(x, gate_weight, ffn1_weight, ffn2_weight, ffn1_bias=None,
              ffn1_scale=None, ffn2_bias=None, ffn2_scale=None,
              quant_method="None", moe_topk=2, norm_topk_prob=True):
    """Fused top-k MoE FFN (reference: fused_moe.py over
    fused_moe_kernel). x [b, s, d]; ffn1 [E, d, 2*dff] (gated SwiGLU
    halves), ffn2 [E, dff, d].

    TPU path: sort-based ragged dispatch + grouped GEMM
    (incubate/nn/pallas/moe_dispatch.py — counting-sort grouping, one
    expert per 128-row MXU block, 2.6x the one-hot einsum path on
    v5e)."""
    if quant_method not in ("None", "none", None):
        raise NotImplementedError(
            "weight-quant fused_moe is CUDA-specific; TPU build computes "
            "bf16 experts")
    from ..pallas.moe_dispatch import moe_ffn_sorted

    xt = as_tensor(x)
    gw = as_tensor(gate_weight)
    w1 = as_tensor(ffn1_weight)
    w2 = as_tensor(ffn2_weight)
    b1 = as_tensor(ffn1_bias) if ffn1_bias is not None else None
    b2 = as_tensor(ffn2_bias) if ffn2_bias is not None else None

    def fn(xa, gwa, w1a, w2a, *rest):
        i = 0
        b1a = rest[i] if b1 is not None else None
        i += b1 is not None
        b2a = rest[i] if b2 is not None else None
        bsz, s, dm = xa.shape
        e = w1a.shape[0]
        toks = xa.reshape(-1, dm)
        logits = toks @ gwa if gwa.ndim == 2 else gwa.reshape(-1, e)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        out = moe_ffn_sorted(toks, probs, w1a, w2a, k=moe_topk,
                             activation="swiglu",
                             normalize=norm_topk_prob, b1=b1a, b2=b2a)
        return out.reshape(bsz, s, dm)

    args = [xt, gw, w1, w2] + [t for t in (b1, b2) if t is not None]
    return run_op(fn, args, name="fused_moe")


def fused_matmul_bias(x, y, bias=None, transpose_x=False, transpose_y=False,
                      name=None):
    """reference: fused_matmul_bias.py (cublasLt epilogue fusion) — XLA
    fuses the bias add into the matmul on TPU."""
    args = [as_tensor(x), as_tensor(y)]
    if bias is not None:
        args.append(as_tensor(bias))

    def fn(a, bmat, *rest):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2)
        if transpose_y:
            bmat = jnp.swapaxes(bmat, -1, -2)
        out = a @ bmat
        if rest:
            out = out + rest[0]
        return out

    return run_op(fn, args, name="fused_matmul_bias")


_BIAS_ACTS = {
    "gelu": jax.nn.gelu,
    "relu": lambda a: jnp.maximum(a, 0),
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "identity": lambda a: a,
}


def fused_bias_act(x, bias=None, dequant_scales=None, shift=None,
                   smooth=None, act_method="gelu", compute_dtype="default",
                   quant_scale=-1, quant_round_type=0,
                   quant_max_bound=0.0, quant_min_bound=0.0, name=None):
    """reference: fused_bias_act.py — act(x + bias), with geglu/swiglu
    splitting when act_method endswith 'glu'."""
    _reject_quant(dequant_scales=dequant_scales, shift=shift,
                  smooth=smooth,
                  quant_scale=None if quant_scale == -1 else quant_scale)
    args = [as_tensor(x)]
    if bias is not None:
        args.append(as_tensor(bias))

    def fn(a, *rest):
        if rest:
            a = a + rest[0]
        if act_method in ("geglu", "swiglu"):
            g, u = jnp.split(a, 2, axis=-1)
            act = jax.nn.gelu if act_method == "geglu" else jax.nn.silu
            return act(g) * u
        return _BIAS_ACTS[act_method](a)

    return run_op(fn, args, name="fused_bias_act")


def _layer_norm(a, scale, bias, eps):
    mu = jnp.mean(a, -1, keepdims=True)
    var = jnp.var(a, -1, keepdims=True)
    out = (a - mu) / jnp.sqrt(var + eps)
    if scale is not None:
        out = out * scale
    if bias is not None:
        out = out + bias
    return out


def _rms_norm(a, scale, eps):
    var = jnp.mean(a * a, -1, keepdims=True)
    out = a * jax.lax.rsqrt(var + eps)
    return out * scale if scale is not None else out


def fused_feedforward(x, linear1_weight, linear2_weight, linear1_bias=None,
                      linear2_bias=None, ln1_scale=None, ln1_bias=None,
                      ln2_scale=None, ln2_bias=None, dropout1_rate=0.5,
                      dropout2_rate=0.5, activation="relu",
                      ln1_epsilon=1e-5, ln2_epsilon=1e-5,
                      pre_layer_norm=False, training=True,
                      mode="upscale_in_train", ring_id=-1,
                      add_residual=True, name=None):
    """reference: fused_transformer.py fused_feedforward:47 — residual +
    (pre/post) LN + linear-act-dropout-linear-dropout in one program."""
    from ....core import random as _rng

    tensors = {"x": as_tensor(x), "w1": as_tensor(linear1_weight),
               "w2": as_tensor(linear2_weight)}
    opt = {"b1": linear1_bias, "b2": linear2_bias, "s1": ln1_scale,
           "lb1": ln1_bias, "s2": ln2_scale, "lb2": ln2_bias}
    opt = {k: as_tensor(v) for k, v in opt.items() if v is not None}
    names = list(opt.keys())
    keys = (_rng.next_key(), _rng.next_key()) if training else None

    def fn(xa, w1, w2, *rest):
        o = dict(zip(names, rest))
        res = xa
        h = _layer_norm(xa, o.get("s1"), o.get("lb1"), ln1_epsilon) \
            if pre_layer_norm else xa
        h = h @ w1
        if "b1" in o:
            h = h + o["b1"]
        h = _BIAS_ACTS.get(activation, jax.nn.gelu)(h)
        if training and dropout1_rate > 0:
            keep = jax.random.bernoulli(keys[0], 1 - dropout1_rate,
                                        h.shape)
            h = jnp.where(keep, h / (1 - dropout1_rate), 0)
        h = h @ w2
        if "b2" in o:
            h = h + o["b2"]
        if training and dropout2_rate > 0:
            keep = jax.random.bernoulli(keys[1], 1 - dropout2_rate,
                                        h.shape)
            h = jnp.where(keep, h / (1 - dropout2_rate), 0)
        if add_residual:
            h = res + h
        if not pre_layer_norm:
            h = _layer_norm(h, o.get("s2"), o.get("lb2"), ln2_epsilon)
        return h

    return run_op(fn, [tensors["x"], tensors["w1"], tensors["w2"]]
                  + list(opt.values()), name="fused_feedforward")


def fused_multi_head_attention(
        x, qkv_weight, linear_weight, pre_layer_norm=False,
        pre_ln_scale=None, pre_ln_bias=None, ln_scale=None, ln_bias=None,
        pre_ln_epsilon=1e-5, qkv_bias=None, linear_bias=None, cache_kv=None,
        attn_mask=None, dropout_rate=0.5, attn_dropout_rate=0.5,
        ln_epsilon=1e-5, training=True, mode="upscale_in_train", ring_id=-1,
        add_residual=True, num_heads=-1, transpose_qkv_wb=False, name=None):
    """reference: fused_transformer.py fused_multi_head_attention:513 —
    residual + (pre/post) LN + fused qkv + self-attention + out proj in
    one XLA program. qkv_weight [3, H, D, embed] (or [embed, 3*embed]
    with transpose_qkv_wb)."""
    from ....core import random as _rng

    xt = as_tensor(x)
    qkvw = as_tensor(qkv_weight)
    lw = as_tensor(linear_weight)
    opt = {"qb": qkv_bias, "lb": linear_bias, "ps": pre_ln_scale,
           "pb": pre_ln_bias, "ls": ln_scale, "lnb": ln_bias,
           "mask": attn_mask}
    opt = {k: as_tensor(v) for k, v in opt.items() if v is not None}
    names = list(opt.keys())
    keys = (_rng.next_key(), _rng.next_key()) if training else None

    def fn(xa, qw, lwa, *rest):
        o = dict(zip(names, rest))
        b, s, e = xa.shape
        res = xa
        h = _layer_norm(xa, o.get("ps"), o.get("pb"), pre_ln_epsilon) \
            if pre_layer_norm else xa
        if transpose_qkv_wb:
            nh = num_heads
            qkv = (h @ qw).reshape(b, s, 3, nh, e // nh)
            if "qb" in o:
                qkv = qkv + o["qb"].reshape(1, 1, 3, nh, e // nh)
        else:
            three, nh, hd, _ = qw.shape
            qkv = jnp.einsum("bse,khde->bskhd", h, qw)
            if "qb" in o:
                qkv = qkv + o["qb"].reshape(1, 1, 3, nh, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,S,H,D]
        hd = q.shape[-1]
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (hd ** -0.5)
        if "mask" in o:
            scores = scores + o["mask"]
        p = jax.nn.softmax(scores, axis=-1)
        if training and attn_dropout_rate > 0:
            keep = jax.random.bernoulli(keys[0], 1 - attn_dropout_rate,
                                        p.shape)
            p = jnp.where(keep, p / (1 - attn_dropout_rate), 0)
        ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, s, -1)
        out = ctx @ lwa
        if "lb" in o:
            out = out + o["lb"]
        if training and dropout_rate > 0:
            keep = jax.random.bernoulli(keys[1], 1 - dropout_rate,
                                        out.shape)
            out = jnp.where(keep, out / (1 - dropout_rate), 0)
        if add_residual:
            out = res + out
        if not pre_layer_norm:
            out = _layer_norm(out, o.get("ls"), o.get("lnb"), ln_epsilon)
        return out

    return run_op(fn, [xt, qkvw, lw] + list(opt.values()),
                  name="fused_multi_head_attention")


def fused_multi_transformer(
        x, ln_scales, ln_biases, qkv_weights, qkv_biases, linear_weights,
        linear_biases, ffn_ln_scales, ffn_ln_biases, ffn1_weights,
        ffn1_biases, ffn2_weights, ffn2_biases, pre_layer_norm=True,
        epsilon=1e-5, residual_alpha=1.0, cache_kvs=None, beam_offset=None,
        pre_caches=None, seq_lens=None, rotary_embs=None, time_step=None,
        attn_mask=None, dropout_rate=0.0, rotary_emb_dims=0,
        activation="gelu", training=False, mode="upscale_in_train",
        trans_qkvw=True, ring_id=-1, norm_type="layernorm",
        use_neox_rotary_style=False, gqa_group_size=-1, name=None):
    """Whole-stack fused transformer forward (reference:
    fused_transformer.py fused_multi_transformer:976 over
    fused_multi_transformer_op.cu).

    Statically-unrolled layer loop in ONE program. Two phases like the
    kernel: context encoding (time_step None — causal over x) and decode
    (time_step set — single token attending into cache_kvs
    [2, B, H, max_seq, D] per layer, updated in place).
    Returns out or (out, cache_kvs) following the reference.
    """
    n_layers = len(qkv_weights)
    xt = as_tensor(x)
    b, s, e = xt.shape
    decode = time_step is not None
    ts = int(unwrap(as_tensor(time_step))) if decode else 0
    mask_t = as_tensor(attn_mask) if attn_mask is not None else None

    def norm(a, scale, bias):
        if norm_type == "rmsnorm":
            return _rms_norm(a, scale, epsilon)
        return _layer_norm(a, scale, bias, epsilon)

    def get(seq, i):
        if seq is None:
            return None
        t = seq[i]
        return unwrap(as_tensor(t)) if t is not None else None

    h = unwrap(xt)
    new_caches = []
    for li in range(n_layers):
        res = h
        ln_s, ln_b = get(ln_scales, li), get(ln_biases, li)
        hn = norm(h, ln_s, ln_b) if pre_layer_norm else h
        qw = unwrap(as_tensor(qkv_weights[li]))
        # kernel layout [3, H, D, E] when trans_qkvw else [E, 3, H, D]
        if trans_qkvw:
            three, nh, hd, _ = qw.shape
            qkv = jnp.einsum("bse,khde->bskhd", hn, qw)
        else:
            _, three, nh, hd = qw.shape
            qkv = jnp.einsum("bse,ekhd->bskhd", hn, qw)
        qb = get(qkv_biases, li)
        if qb is not None:
            qkv = qkv + qb.reshape(1, 1, 3, nh, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        pos = (jnp.full((b, s), ts) if decode
               else jnp.broadcast_to(jnp.arange(s), (b, s)))
        if rotary_embs is not None or rotary_emb_dims > 0:
            q2 = q.reshape(b * s, nh, hd)
            k2 = k.reshape(b * s, nh, hd)
            q2, k2 = _apply_rope(q2, k2, pos.reshape(-1),
                                 neox=use_neox_rotary_style)
            q, k = q2.reshape(b, s, nh, hd), k2.reshape(b, s, nh, hd)
        if decode:
            cache = unwrap(as_tensor(cache_kvs[li]))
            max_seq = cache.shape[3]
            ck = cache[0].at[jnp.arange(b), :, ts].set(k[:, 0])
            cv = cache[1].at[jnp.arange(b), :, ts].set(v[:, 0])
            scores = jnp.einsum("bhd,bhsd->bhs", q[:, 0], ck) \
                * (hd ** -0.5)
            ok = jnp.arange(max_seq)[None, :] <= ts
            scores = jnp.where(ok[:, None, :], scores, -1e9)
            p = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhs,bhsd->bhd", p, cv)[:, None]
            new_cache = jnp.stack([ck, cv])
            if isinstance(cache_kvs[li], Tensor):
                cache_kvs[li]._data = new_cache
            new_caches.append(new_cache)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (hd ** -0.5)
            causal = jnp.tril(jnp.ones((s, s), bool))
            scores = jnp.where(causal[None, None], scores, -1e9)
            if mask_t is not None:
                scores = scores + unwrap(mask_t)
            p = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("bhqk,bkhd->bqhd", p, v)
            if cache_kvs is not None:
                cache = unwrap(as_tensor(cache_kvs[li]))
                pad = cache.shape[3]
                ck = cache[0].at[:, :, :s].set(k.swapaxes(1, 2))
                cv = cache[1].at[:, :, :s].set(v.swapaxes(1, 2))
                new_cache = jnp.stack([ck, cv])
                if isinstance(cache_kvs[li], Tensor):
                    cache_kvs[li]._data = new_cache
                new_caches.append(new_cache)
        lw = unwrap(as_tensor(linear_weights[li]))
        attn_out = ctx.reshape(b, s, -1) @ lw
        lb = get(linear_biases, li)
        if lb is not None:
            attn_out = attn_out + lb
        h = res * residual_alpha + attn_out
        # ffn
        res2 = h
        fs, fb = get(ffn_ln_scales, li), get(ffn_ln_biases, li)
        hn2 = norm(h, fs, fb) if pre_layer_norm else norm(h, ln_s, ln_b)
        w1 = unwrap(as_tensor(ffn1_weights[li]))
        f1 = hn2 @ w1
        b1 = get(ffn1_biases, li)
        if b1 is not None:
            f1 = f1 + b1
        if activation in ("geglu", "swiglu"):
            g, u = jnp.split(f1, 2, axis=-1)
            act = jax.nn.gelu if activation == "geglu" else jax.nn.silu
            f1 = act(g) * u
        else:
            f1 = _BIAS_ACTS.get(activation, jax.nn.gelu)(f1)
        w2 = unwrap(as_tensor(ffn2_weights[li]))
        f2 = f1 @ w2
        b2 = get(ffn2_biases, li)
        if b2 is not None:
            f2 = f2 + b2
        h = res2 * residual_alpha + f2
        if not pre_layer_norm:
            h = norm(h, fs, fb)
    out = Tensor(h)
    if cache_kvs is not None:
        return out, cache_kvs
    return out
