"""Fused transformer ops (reference: python/paddle/incubate/nn/functional/ —
fused_rotary_position_embedding.py, fused_rms_norm.py, swiglu.py,
fused_dropout_add.py, fused_bias_dropout_residual_layer_norm; CUDA kernels
under paddle/phi/kernels/fusion/gpu/).

On TPU the "fusion" is XLA's job — these compositions compile to fused
kernels; rms_norm/rope additionally have Pallas fast paths.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core import random as _rng
from ....nn.functional.norm import layer_norm as _layer_norm
from ....nn.functional.norm import rms_norm as _rms_norm
from ....nn.functional.activation import swiglu  # noqa: F401  re-export
from ....ops._helpers import as_tensor, run_op

__all__ = ["fused_rotary_position_embedding", "fused_rms_norm",
           "fused_layer_norm", "fused_dropout_add",
           "fused_bias_dropout_residual_layer_norm", "fused_linear",
           "fused_linear_activation", "swiglu"]


def _rope_rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def _rope_rotate_pairwise(x):
    x1 = x[..., 0::2]
    x2 = x[..., 1::2]
    out = jnp.stack([-x2, x1], axis=-1)
    return out.reshape(x.shape)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None,
                                    use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0,
                                    name=None):
    """RoPE over [batch, seq, heads, head_dim]
    (reference: fused_rotary_position_embedding.py; kernel
    phi/kernels/fusion/gpu/fused_rope_kernel.cu)."""
    tensors = [t for t in (q, k, v) if t is not None]
    shapes = as_tensor(tensors[0]).shape
    seq_len, head_dim = shapes[1], shapes[-1]

    if sin is None or cos is None:
        inv = 1.0 / (rotary_emb_base ** (
            jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
        if position_ids is not None:
            from ....ops._helpers import unwrap

            # frequencies straight from the (possibly offset) positions —
            # no table, so decode positions beyond seq_len stay exact
            pid = unwrap(as_tensor(position_ids)).astype(jnp.float32)
            freqs = pid[..., None] * inv  # [batch, seq, head_dim/2]
            if use_neox_rotary_style:
                emb = jnp.concatenate([freqs, freqs], axis=-1)
            else:
                emb = jnp.repeat(freqs, 2, axis=-1)
            cos_arr = jnp.cos(emb)[:, :, None, :]
            sin_arr = jnp.sin(emb)[:, :, None, :]
        else:
            t = jnp.arange(seq_len, dtype=jnp.float32)
            freqs = jnp.outer(t, inv)  # [seq, head_dim/2]
            if use_neox_rotary_style:
                emb = jnp.concatenate([freqs, freqs], axis=-1)
            else:
                emb = jnp.repeat(freqs, 2, axis=-1)
            cos_arr = jnp.cos(emb)[None, :, None, :]
            sin_arr = jnp.sin(emb)[None, :, None, :]
    else:
        from ....ops._helpers import unwrap

        cos_arr = unwrap(as_tensor(cos))
        sin_arr = unwrap(as_tensor(sin))
        if cos_arr.ndim == 2:
            cos_arr = cos_arr[None, :, None, :]
            sin_arr = sin_arr[None, :, None, :]
        if position_ids is not None:
            pid = unwrap(as_tensor(position_ids))  # [batch, seq]
            cos_arr = jnp.squeeze(cos_arr, (0, 2))[pid][:, :, None, :]
            sin_arr = jnp.squeeze(sin_arr, (0, 2))[pid][:, :, None, :]

    rotate = _rope_rotate_half if use_neox_rotary_style \
        else _rope_rotate_pairwise

    def apply_one(t):
        def fn(a):
            af = a.astype(jnp.float32)
            out = af * cos_arr + rotate(af) * sin_arr
            return out.astype(a.dtype)

        return run_op(fn, [as_tensor(t)], name="fused_rope")

    outs = tuple(apply_one(t) if t is not None else None for t in (q, k, v))
    return outs


def _last_axis_norm(begin_norm_axis, x):
    return begin_norm_axis in (-1, x.ndim - 1)


# test hook: force the Pallas dispatch branch on non-TPU backends (the
# kernels run under the interpreter there)
_FORCE_PALLAS = False


def _pallas_norm_ok(x):
    """Gate like flash_attention._use_pallas: TPU backend + importable pallas
    + non-degenerate shape; otherwise the XLA composition path."""
    try:
        from ..pallas import norms  # noqa: F401
    except Exception:
        return False
    if jax.default_backend() != "tpu" and not _FORCE_PALLAS:
        return False
    return x.size > 0


def fused_rms_norm(x, norm_weight, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, bias=None, residual=None,
                   quant_scale=-1, name=None):
    """reference: incubate/nn/functional/fused_rms_norm.py.

    Last-axis case dispatches to the Pallas fused kernel
    (:mod:`paddle_tpu.incubate.nn.pallas.norms`)."""
    if bias is not None:
        x = as_tensor(x) + as_tensor(bias)
    xt = as_tensor(x)
    if residual is not None:
        xt = xt + as_tensor(residual)
    if norm_weight is not None and _last_axis_norm(begin_norm_axis, xt) \
            and _pallas_norm_ok(xt):
        from ..pallas.norms import rms_norm as pallas_rms

        w = as_tensor(norm_weight)
        ts = [xt, w]
        if norm_bias is not None:
            ts.append(as_tensor(norm_bias))
            fn = lambda a, wa, ba: pallas_rms(a, wa, ba, eps=epsilon)
        else:
            fn = lambda a, wa: pallas_rms(a, wa, eps=epsilon)
        out = run_op(fn, ts, name="fused_rms_norm")
    else:
        out = _rms_norm(xt, norm_weight, norm_bias, epsilon, begin_norm_axis)
    if residual is not None:
        return out, xt
    return out


def fused_layer_norm(x, norm_weight, norm_bias=None, epsilon=1e-5,
                     begin_norm_axis=-1, bias=None, residual=None,
                     quant_scale=-1, name=None):
    if bias is not None:
        x = as_tensor(x) + as_tensor(bias)
    if residual is not None:
        x = as_tensor(x) + as_tensor(residual)
        nshape = as_tensor(x).shape[begin_norm_axis:] \
            if begin_norm_axis >= 0 else as_tensor(x).shape[-1:]
        out = _layer_norm(x, nshape, norm_weight, norm_bias, epsilon)
        return out, x
    xt = as_tensor(x)
    nshape = xt.shape[begin_norm_axis:] if begin_norm_axis >= 0 \
        else xt.shape[-1:]
    return _layer_norm(xt, nshape, norm_weight, norm_bias, epsilon)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train",
                      name=None):
    """reference: incubate/nn/functional/fused_dropout_add.py."""
    if not training or p == 0.0:
        return as_tensor(x) + as_tensor(y)
    key = _rng.next_key()

    def fn(a, b):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        if mode == "upscale_in_train":
            d = jnp.where(keep, a / (1.0 - p), 0.0)
        else:
            d = jnp.where(keep, a, 0.0)
        return (d + b).astype(a.dtype)

    return run_op(fn, [as_tensor(x), as_tensor(y)], name="fused_dropout_add")


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.5, ln_epsilon=1e-5,
                                           training=True, mode="upscale_in_train",
                                           name=None):
    """reference: fused_bias_dropout_residual_layer_norm kernel
    (phi/kernels/fusion/gpu/fused_bias_dropout_residual_layer_norm_kernel.cu)."""
    h = as_tensor(x)
    if bias is not None:
        h = h + as_tensor(bias)
    h = fused_dropout_add(h, residual, p=dropout_rate, training=training,
                          mode=mode)
    nshape = h.shape[-1:]
    return _layer_norm(h, nshape, ln_scale, ln_bias, ln_epsilon)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    def fn(*arrs):
        a, w = arrs[0], arrs[1]
        if transpose_weight:
            w = w.T
        out = jnp.matmul(a, w)
        if len(arrs) > 2:
            out = out + arrs[2]
        return out

    ts = [as_tensor(x), as_tensor(weight)]
    if bias is not None:
        ts.append(as_tensor(bias))
    return run_op(fn, ts, name="fused_linear")


def fused_linear_activation(x, y, bias, trans_x=False, trans_y=False,
                            activation="gelu", name=None):
    """fused_gemm_epilogue analog (reference:
    phi/kernels/fusion/gpu/fused_gemm_epilogue_kernel.cu)."""
    act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu,
           "none": lambda v: v}[activation]

    def fn(a, w, b):
        if trans_x:
            a = jnp.swapaxes(a, -1, -2)
        if trans_y:
            w = jnp.swapaxes(w, -1, -2)
        return act(jnp.matmul(a, w) + b)

    return run_op(fn, [as_tensor(x), as_tensor(y), as_tensor(bias)],
                  name="fused_linear_activation")
