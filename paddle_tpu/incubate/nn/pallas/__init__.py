"""Pallas TPU kernel tier — the analog of the reference's fused CUDA
kernels (paddle/phi/kernels/fusion/gpu/) and KPS primitive layer
(paddle/phi/kernels/primitive/kernel_primitives.h).

Kernels here are hand-tiled for the MXU/VPU and run under the Pallas
interpreter on non-TPU backends so tests stay hermetic.
"""
from . import flash_attn, norms, paged_attention as paged
from .flash_attn import flash_attention
from .norms import layer_norm, rms_norm
from .paged_attention import paged_attention, paged_kv_write

__all__ = ["flash_attn", "norms", "paged", "flash_attention", "layer_norm",
           "rms_norm", "paged_attention", "paged_kv_write"]
