"""Pallas TPU fused normalization kernels: rms_norm, layer_norm.

TPU-native analog of the reference fused norm CUDA kernels
(reference: paddle/phi/kernels/fusion/gpu/fused_layernorm_kernel.cu,
fused_rms_norm via incubate/nn/functional/fused_rms_norm.py). One pass
over rows resident in VMEM; mean/var in f32 regardless of input dtype.

Forward is a Pallas kernel; backward is the standard XLA composition via
``jax.custom_vjp`` (XLA fuses norm backwards well — the win here is the
single-pass forward in the serving/decode path).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_DEF_BLOCK_ROWS = 256


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    xf = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    o_ref[...] = (xf * inv * w_ref[...].astype(jnp.float32)) \
        .astype(x_ref.dtype)


def _rms_kernel_bias(x_ref, w_ref, b_ref, o_ref, *, eps):
    xf = x_ref[...].astype(jnp.float32)
    ms = jnp.mean(xf * xf, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(ms + eps)
    out = xf * inv * w_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(x_ref.dtype)


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    xf = x_ref[...].astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    out = xc * inv * w_ref[...].astype(jnp.float32) \
        + b_ref[...].astype(jnp.float32)
    o_ref[...] = out.astype(x_ref.dtype)


def _rowwise_call(kernel, x2d, params, interpret, block_rows=_DEF_BLOCK_ROWS):
    n, d = x2d.shape
    # rows are independent: a cdiv grid lets Pallas pad the trailing block
    # (padded rows compute garbage that is clipped on write) and keeps the
    # block row count 8-aligned regardless of n
    block_rows = n if n < block_rows else block_rows
    grid = (pl.cdiv(n, block_rows),)
    in_specs = [pl.BlockSpec((block_rows, d), lambda i: (i, 0))]
    for p in params:
        in_specs.append(pl.BlockSpec((d,), lambda i: (0,)))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2d.dtype),
        interpret=interpret,
    )(x2d, *params)


# --------------------------------------------------------------------- rms
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _rms_norm(x2d, w, b, eps):
    interpret = _interpret_default()
    if b is None:
        return _rowwise_call(
            functools.partial(_rms_kernel, eps=eps), x2d, [w], interpret)
    return _rowwise_call(
        functools.partial(_rms_kernel_bias, eps=eps), x2d, [w, b], interpret)


def _rms_ref(x2d, w, b, eps):
    xf = x2d.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    out = xf * inv * w.astype(jnp.float32)
    if b is not None:
        out = out + b.astype(jnp.float32)
    return out.astype(x2d.dtype)


def _rms_fwd(x2d, w, b, eps):
    return _rms_norm(x2d, w, b, eps), (x2d, w, b)


def _rms_bwd(eps, res, g):
    x2d, w, b = res
    dx, dw, db = jax.vjp(
        lambda x, w_, b_: _rms_ref(x, w_, b_, eps), x2d, w,
        b if b is not None else jnp.zeros_like(w))[1](g)
    return dx, dw, (db if b is not None else None)


_rms_norm.defvjp(_rms_fwd, _rms_bwd)


# --------------------------------------------------------------------- ln
@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _layer_norm(x2d, w, b, eps):
    interpret = _interpret_default()
    return _rowwise_call(
        functools.partial(_ln_kernel, eps=eps), x2d, [w, b], interpret)


def _ln_ref(x2d, w, b, eps):
    xf = x2d.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mu
    inv = jax.lax.rsqrt(jnp.mean(xc * xc, axis=-1, keepdims=True) + eps)
    return (xc * inv * w.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x2d.dtype)


def _ln_fwd(x2d, w, b, eps):
    return _layer_norm(x2d, w, b, eps), (x2d, w, b)


def _ln_bwd(eps, res, g):
    x2d, w, b = res
    return jax.vjp(lambda x, w_, b_: _ln_ref(x, w_, b_, eps), x2d, w, b)[1](g)


_layer_norm.defvjp(_ln_fwd, _ln_bwd)


# ------------------------------------------------------------------ public
def rms_norm(x, weight, bias=None, eps=1e-6):
    """Fused RMSNorm over the last axis. x: [..., d]."""
    d = x.shape[-1]
    out = _rms_norm(x.reshape(-1, d), weight, bias, float(eps))
    return out.reshape(x.shape)


def layer_norm(x, weight, bias, eps=1e-5):
    """Fused LayerNorm over the last axis. x: [..., d]."""
    d = x.shape[-1]
    out = _layer_norm(x.reshape(-1, d), weight, bias, float(eps))
    return out.reshape(x.shape)


__all__ = ["rms_norm", "layer_norm"]
