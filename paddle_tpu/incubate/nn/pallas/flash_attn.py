"""Pallas TPU flash attention, forward + backward.

TPU-native replacement for the reference's dynloaded flashattention CUDA
kernels (reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu,
paddle/phi/backends/dynload/flashattn.cc). Blockwise online-softmax
attention tiled for the MXU: Q/K/V blocks stream HBM->VMEM, the score
block ``q @ k^T`` and the weighted sum ``p @ v`` hit the 128x128 systolic
array, and the running max/denominator live in VMEM scratch across the
sequential kv-block grid dimension.

Public entry: :func:`flash_attention` on paddle-layout arrays
``[batch, seq, num_heads, head_dim]`` with a custom VJP whose backward is
also two Pallas kernels (dq; dk/dv), using the saved logsumexp — O(seq)
memory, no materialized attention matrix.

On non-TPU backends the same kernels run under the Pallas interpreter so
the numerics are testable on CPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# pltpu imports fine without TPU hardware (interpret mode uses its
# scratch-shape constructors too)
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 1024  # measured on v5e (tools/tune_flash_attn.py):
DEFAULT_BLOCK_K = 1024  # 1024-blocks beat 128 by ~2.5x fwd+bwd
_NEG_INF = -1e30
_LANES = 128  # scratch minor dim: one full lane register row


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _fit_block(block: int, seq: int) -> int:
    """Largest block <= requested that divides seq (callers guarantee
    seq % 128 == 0, so halving from 1024 always terminates >= 128)."""
    block = min(block, seq)
    while seq % block:
        block //= 2
    return max(block, 1)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k,
                num_k_blocks, offset):
    # offset = sk - sq: bottom-right-aligned causal mask (query i attends
    # keys <= i + offset), matching the XLA fallback's tril(..., sk - sq)
    i = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]

        if causal:
            q_pos = i * block_q + offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)

        m_prev = m_scr[:, :1]                       # [bq, 1]
        l_prev = l_scr[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)   # [bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)             # correction for old acc
        p = jnp.exp(s - m_new)                      # [bq, bk]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)

        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    if causal:
        # skip fully-masked blocks above the (offset) diagonal
        @pl.when(j * block_k < (i + 1) * block_q + offset)
        def _run():
            _body()
    else:
        _body()

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe).astype(o_ref.dtype)
        m = m_scr[:, :1]
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))
        lse_ref[0] = lse  # [block_q, 1]


def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    """q/k/v: [bh, s, d] -> (out [bh, s, d], lse [bh, s] f32)."""
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    nq, nk = sq // block_q, sk // block_k

    grid = (bh, nq, nk)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=nk, offset=sk - sq)

    compiler_params = None
    if not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
        cost_estimate=pl.CostEstimate(
            flops=4 * bh * sq * sk * d // (2 if causal else 1),
            bytes_accessed=int(
                (q.size + k.size + v.size + q.size) * q.dtype.itemsize),
            transcendentals=bh * sq * sk,
        ),
    )(q, k, v)
    return out, lse


# ---------------------------------------------------------------------------
# backward: dk/dv kernel (grid over k blocks, sequential over q blocks)
# ---------------------------------------------------------------------------
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                    block_q, block_k, num_q_blocks, offset):
    j = pl.program_id(1)   # k block
    i = pl.program_id(2)   # q block (sequential)

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _body():
        q = q_ref[0]         # [bq, d]
        k = k_ref[0]         # [bk, d]
        v = v_ref[0]
        do = do_ref[0]        # [bq, d]
        lse = lse_ref[0]      # [bq, 1]
        delta = delta_ref[0]  # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [bq, bk]
        p = jnp.exp(s - lse)                         # [bq, bk]
        if causal:
            q_pos = i * block_q + offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            # explicit zero (not exp underflow): fully-masked rows carry
            # lse = -NEG_INF and would otherwise give exp(0) = 1
            p = jnp.where(q_pos >= k_pos, p, 0.0)

        # dv += p^T @ do
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do @ v^T
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)      # [bq, bk]
        ds = p * (dp - delta) * scale                # [bq, bk]
        # dk += ds^T @ q
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when((i + 1) * block_q + offset > j * block_k)
        def _run():
            _body()
    else:
        _body()

    @pl.when(i == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dq kernel (grid over q blocks, sequential over k blocks)
# ---------------------------------------------------------------------------
def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, scale, causal, block_q, block_k,
                   num_k_blocks, offset):
    i = pl.program_id(1)   # q block
    j = pl.program_id(2)   # k block (sequential)

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]      # [bq, 1]
        delta = delta_ref[0]  # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)
        if causal:
            q_pos = i * block_q + offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dq += ds @ k
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when(j * block_k < (i + 1) * block_q + offset)
        def _run():
            _body()
    else:
        _body()

    @pl.when(j == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, dk_scr, dv_scr, dq_all,
                      *, scale, causal, block_q, block_k, num_q_blocks,
                      num_k_blocks, offset):
    """Single-pass backward: dk, dv AND dq from one (j, i) sweep.

    The two-kernel split recomputes s = q k^T and dp = do v^T in both
    kernels (7 block matmuls); sharing them here does the ideal 5. dq
    accumulates across the OUTER j loop, which output windows cannot do
    on TPU (a revisited block is not re-fetched) — so dq for the whole
    sequence lives in a VMEM scratch (seq x d f32) and each (b, i)
    window is flushed at its last j visit. The scratch caps the fused
    path at moderate sequence lengths; _flash_bwd falls back to the
    two-kernel split beyond it."""
    j = pl.program_id(1)   # k block (outer)
    i = pl.program_id(2)   # q block (sequential inner)

    @pl.when(i == 0)
    def _init_kv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    @pl.when(j == 0)
    def _init_dq():
        dq_all[pl.ds(i * block_q, block_q), :] = jnp.zeros(
            (block_q, dq_all.shape[1]), jnp.float32)

    def _body():
        q = q_ref[0]          # [bq, d]
        k = k_ref[0]          # [bk, d]
        v = v_ref[0]
        do = do_ref[0]        # [bq, d]
        lse = lse_ref[0]      # [bq, 1]
        delta = delta_ref[0]  # [bq, 1]

        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        p = jnp.exp(s - lse)
        if causal:
            q_pos = i * block_q + offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        ds = p * (dp - delta) * scale                     # [bq, bk]
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dq_all[pl.ds(i * block_q, block_q), :] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        @pl.when((i + 1) * block_q + offset > j * block_k)
        def _run():
            _body()
    else:
        _body()

    @pl.when(i == num_q_blocks - 1)
    def _flush_kv():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)

    @pl.when(j == num_k_blocks - 1)
    def _flush_dq():
        dq_ref[0] = dq_all[pl.ds(i * block_q, block_q), :] \
            .astype(dq_ref.dtype)


# dq scratch cap for the fused backward: seq * d * 4 bytes of VMEM
_FUSED_BWD_MAX_SEQ_D = 8192 * 128


def _flash_bwd(q, k, v, out, lse, do, causal, scale, block_q, block_k,
               interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    block_q = _fit_block(block_q, sq)
    block_k = _fit_block(block_k, sk)
    nq, nk = sq // block_q, sk // block_k

    if sq == sk and sq * d <= _FUSED_BWD_MAX_SEQ_D:
        return _flash_bwd_fused(q, k, v, out, lse, do, causal, scale,
                                block_q, block_k, nq, nk, interpret)
    return _flash_bwd_split(q, k, v, out, lse, do, causal, scale,
                            block_q, block_k, nq, nk, interpret)


def _flash_bwd_fused(q, k, v, out, lse, do, causal, scale, block_q,
                     block_k, nq, nk, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [bh, sq, 1]
    block_shapes = [
        (1, block_q, d), (1, block_k, d), (1, block_k, d),
        (1, block_q, d), (1, block_q, 1), (1, block_q, 1),
    ]
    maps = [
        lambda b, j, i: (b, i, 0),
        lambda b, j, i: (b, j, 0),
        lambda b, j, i: (b, j, 0),
        lambda b, j, i: (b, i, 0),
        lambda b, j, i: (b, i, 0),
        lambda b, j, i: (b, i, 0),
    ]
    compiler_params = None
    if not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"))
    kernel = functools.partial(
        _bwd_fused_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_q_blocks=nq,
        num_k_blocks=nk, offset=sk - sq)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid=(bh, nk, nq),
        in_specs=[pl.BlockSpec(s, m)
                  for s, m in zip(block_shapes, maps)],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((sq, d), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _flash_bwd_split(q, k, v, out, lse, do, causal, scale, block_q,
                     block_k, nq, nk, interpret):
    bh, sq, d = q.shape
    sk = k.shape[1]

    # delta = rowsum(do * o): cheap XLA reduction, feeds both kernels
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # [bh, sq, 1]

    block_shapes = [
        (1, block_q, d),   # q
        (1, block_k, d),   # k
        (1, block_k, d),   # v
        (1, block_q, d),   # do
        (1, block_q, 1),   # lse
        (1, block_q, 1),   # delta
    ]

    def specs(maps):
        return [pl.BlockSpec(s, m) for s, m in zip(block_shapes, maps)]

    compiler_params = None
    if not interpret:
        compiler_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"))

    # ---- dk, dv: grid (bh, nk, nq), q-dim sequential
    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_q_blocks=nq, offset=sk - sq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=specs([
            lambda b, j, i: (b, i, 0),
            lambda b, j, i: (b, j, 0),
            lambda b, j, i: (b, j, 0),
            lambda b, j, i: (b, i, 0),
            lambda b, j, i: (b, i, 0),
            lambda b, j, i: (b, i, 0),
        ]),
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # ---- dq: grid (bh, nq, nk), k-dim sequential
    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=nk, offset=sk - sq)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=specs([
            lambda b, i, j: (b, i, 0),
            lambda b, i, j: (b, j, 0),
            lambda b, i, j: (b, j, 0),
            lambda b, i, j: (b, i, 0),
            lambda b, i, j: (b, i, 0),
            lambda b, i, j: (b, i, 0),
        ]),
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=compiler_params,
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper on [bh, s, d]
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    dq, dk, dv = _flash_bwd(q, k, v, out, lse, do, causal, scale,
                            block_q, block_k, interpret)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    interpret=None):
    """Flash attention on paddle-layout arrays [batch, seq, heads, head_dim].

    Supports GQA/MQA (k/v may have fewer heads; must divide q heads).
    Differentiable via Pallas backward kernels.
    """
    b, sq, hq, d = q.shape
    hk = k.shape[2]
    sk = k.shape[1]
    if interpret is None:
        interpret = _interpret_default()
    if scale is None:
        scale = float(d) ** -0.5
    if hk != hq:
        assert hq % hk == 0, (hq, hk)
        rep = hq // hk
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    # [b, s, h, d] -> [b*h, s, d]
    def to_bh(x, s):
        return jnp.swapaxes(x, 1, 2).reshape(b * hq, s, x.shape[-1])

    qb = to_bh(q, sq)
    kb = to_bh(k, sk)
    vb = to_bh(v, sk)
    ob = _flash(qb, kb, vb, causal, scale, block_q, block_k, interpret)
    return jnp.swapaxes(ob.reshape(b, hq, sq, d), 1, 2)


__all__ = ["flash_attention"]
