"""Sort-based MoE dispatch + grouped GEMM (VERDICT r3 next #8; reference:
paddle/phi/kernels/fusion/gpu/fused_moe_kernel.cu — sort tokens by expert,
run one grouped GEMM per projection, scatter back).

TPU-idiomatic ragged dispatch (the megablocks/MaxText pattern):
  1. top-k routing -> (token, expert) pairs, grouped by expert with a
     COUNTING sort (cumsum over the one-hot — XLA's bitonic sort and
     row scatters are both slow paths on TPU; this is one VPU prefix
     pass, no capacity dropping, and the wide data movement is
     gather-only);
  2. tokens land in expert-contiguous rows, each expert's group padded
     to the 128-row MXU block so every grid block belongs to exactly
     ONE expert;
  3. grouped GEMM: a Pallas kernel whose BlockSpec index_map reads the
     per-block expert id from scalar-prefetch SMEM and pulls that
     expert's weight tile — [BM, K] x [K, BN] MXU matmuls, zero wasted
     FLOPs on other experts' weights (jax.lax.ragged_dot drives the same
     Mosaic path and is used off-TPU / in interpret mode);
  4. gather-only combine: dest is pair-major, so the weighted top-k
     reduction needs no scatter and no un-sort.

Measured (v5e, 8192 tokens x 2048, E=8 swiglu dff=2816, top-2):
7.7 ms/step, 74 TF/s on the grouped GEMMs, dispatch below timer
resolution — 2.6x the GShard [S,E,C] one-hot einsum path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["sort_dispatch", "grouped_matmul", "moe_ffn_sorted"]

_BM = 128  # row block: one expert per block after padding


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def sort_dispatch(x, probs, k, normalize=True):
    """Route tokens to top-k experts via one sort.

    x: [S, M]; probs: [S, E] router probabilities.
    Returns dict with padded expert-contiguous rows and the metadata to
    combine back:
      xp [P, M] (P static = S*k + E*_BM, block-aligned groups),
      dest [S*k] padded row of each (token, k) pair,
      tok [S*k] source token ids (pair-major),
      weight [S*k] combine weights,
      block_gid [P/_BM] expert id per row block,
      group_sizes [E] true rows per expert.
    """
    s, m = x.shape
    e = probs.shape[-1]
    t = s * k
    top_p, top_e = jax.lax.top_k(probs, k)            # [S, K]
    if normalize:
        top_p = top_p / jnp.sum(top_p, -1, keepdims=True)
    flat_e = top_e.reshape(-1)                        # [T]
    flat_p = top_p.reshape(-1)
    # counting sort via cumsum over the one-hot — XLA's bitonic sort is
    # the slow path on TPU; a [T, E] prefix-sum is one cheap VPU pass
    oh = jax.nn.one_hot(flat_e, e, dtype=jnp.int32)   # [T, E]
    prefix = jnp.cumsum(oh, axis=0)                   # [T, E]
    counts = prefix[-1]                               # [E]
    rank = jnp.take_along_axis(prefix, flat_e[:, None],
                               axis=1)[:, 0] - 1      # rank within expert
    padded = ((counts + _BM - 1) // _BM) * _BM
    group_start = jnp.cumsum(padded) - padded         # padded offsets
    dest = group_start[flat_e] + rank                 # [T] padded row
    p_rows = ((t + _BM - 1) // _BM) * _BM + e * _BM   # static upper bound
    # row -> source pair: one small int32 scatter (pad rows gather the
    # appended zero row); the WIDE data movement stays gather-only
    row_pair = jnp.full((p_rows,), t, jnp.int32).at[dest].set(
        jnp.arange(t, dtype=jnp.int32))
    src_tok = jnp.where(row_pair < t, row_pair // k, s)
    xz = jnp.concatenate([x, jnp.zeros((1, m), x.dtype)], 0)
    xp = xz[src_tok]
    rows = jnp.arange(p_rows)
    gid_of_row = jnp.clip(
        jnp.searchsorted(jnp.cumsum(padded), rows, side="right"),
        0, e - 1)
    block_gid = gid_of_row[::_BM].astype(jnp.int32)
    return {"xp": xp, "dest": dest, "weight": flat_p,
            "block_gid": block_gid, "group_sizes": counts,
            "padded_sizes": padded}


def _gmm_kernel(gid_ref, x_ref, w_ref, o_ref):
    o_ref[...] = jnp.dot(x_ref[...], w_ref[0],
                         preferred_element_type=jnp.float32
                         ).astype(o_ref.dtype)


def grouped_matmul(xp, w, block_gid, *, bn=None, impl=None,
                   interpret=None):
    """Block-aligned grouped GEMM: row block i multiplies expert
    ``block_gid[i]``'s weight.  xp [P, K] (P % 128 == 0), w [E, K, N].

    impl: "pallas" (the scalar-prefetch kernel; interpret=True runs it on
    CPU), "ragged" (jax.lax.ragged_dot — same Mosaic path on TPU), or
    None = pallas on TPU, ragged elsewhere."""
    if impl is None:
        impl = "ragged" if _interpret_default() else "pallas"
    p, kdim = xp.shape
    e, _, n = w.shape
    def _ragged():
        # padded group sizes from the block map (nondecreasing by
        # construction, so rows are expert-contiguous as ragged_dot needs)
        sizes = jnp.bincount(block_gid, length=e) * _BM
        return jax.lax.ragged_dot(xp, w, sizes.astype(jnp.int32))

    if impl == "ragged" or pltpu is None:
        return _ragged()
    if interpret is None:
        interpret = _interpret_default()
    # bn must DIVIDE n: the grid has n // bn column blocks, so a remainder
    # would leave the last n % bn output columns unwritten (garbage)
    if bn is not None:
        if n % bn:
            raise ValueError(f"bn={bn} does not divide N={n}")
    elif n <= 512:
        bn = n
    else:
        bn = next((c for c in (512, 384, 256, 128) if n % c == 0), None)
        if bn is None:  # no MXU-aligned divisor — ragged handles any N
            return _ragged()
    grid = (p // _BM, n // bn)
    return pl.pallas_call(
        _gmm_kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((_BM, kdim), lambda i, j, gid: (i, 0)),
                pl.BlockSpec((1, kdim, bn),
                             lambda i, j, gid: (gid[i], 0, j)),
            ],
            out_specs=pl.BlockSpec((_BM, bn), lambda i, j, gid: (i, j)),
        ),
        out_shape=jax.ShapeDtypeStruct((p, n), xp.dtype),
        interpret=interpret,
    )(block_gid, xp, w)


def moe_ffn_sorted(x, probs, w1, w2, k=2, *, activation="swiglu",
                   normalize=True, b1=None, b2=None, impl=None,
                   interpret=None):
    """Full sort-dispatched MoE FFN.

    x [S, M]; probs [S, E]; w1 [E, M, H] (H = 2*dff for swiglu);
    w2 [E, H'|dff, M]. Returns [S, M]."""
    d = sort_dispatch(x, probs, k, normalize=normalize)
    h = grouped_matmul(d["xp"], w1, d["block_gid"], impl=impl,
                       interpret=interpret)
    if b1 is not None:
        h = h + b1.reshape(b1.shape[0], -1)[d["block_gid"]
                                            ].repeat(_BM, 0)[:h.shape[0]]
    if activation == "swiglu":
        g, u = jnp.split(h, 2, axis=-1)
        h = jax.nn.silu(g) * u
    elif activation == "gelu":
        h = jax.nn.gelu(h)
    else:
        h = jnp.maximum(h, 0)
    y = grouped_matmul(h, w2, d["block_gid"], impl=impl,
                       interpret=interpret)
    if b2 is not None:
        y = y + b2.reshape(b2.shape[0], -1)[d["block_gid"]
                                            ].repeat(_BM, 0)[:y.shape[0]]
    s, m = x.shape
    # gather-only combine: dest is pair-major, so y[dest] is already in
    # (token, k) order — weighted reduce over k, no scatter, no un-sort
    pair_y = y[d["dest"]] * d["weight"][:, None].astype(y.dtype)
    return jnp.sum(pair_y.reshape(s, k, m), axis=1)
