"""Pallas TPU paged-attention decode kernel (block KV cache).

TPU-native analog of the reference paged/blocked-KV fused kernels
(reference: phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and
masked_multihead_attention_kernel.cu; python surface
incubate/nn/functional/block_multihead_attention.py).

Single-token decode: each (batch, kv_head) program walks that sequence's
pages via a scalar-prefetched block table — the page indirection happens in
the BlockSpec index_map, so only the pages actually referenced are DMA'd
into VMEM (the point of paged attention). Online-softmax accumulation in
f32 VMEM scratch across the page grid dimension.

Layouts:
  q:            [batch, num_heads, head_dim]   (one decode step)
  k/v_pages:    [num_kv_heads, total_pages, page_size, head_dim]
  block_tables: [batch, pages_per_seq] int32 (page id per slot)
  context_lens: [batch] int32
Grouped-query attention: num_heads % num_kv_heads == 0; the group of query
heads sharing a kv head is processed together (one MXU matmul per page).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["paged_attention", "ragged_paged_attention", "paged_kv_write",
           "paged_kv_write_chunk", "quantize_kv_pages"]


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _dequant(q8, s, dtype=jnp.float32):
    """The ONE int8-page decode rule: ``value = q8 * s`` with the
    per-row absmax scale broadcast over the trailing head dim.  Every
    consumer of ``{"q8","s"}`` pages decodes through this helper — the
    XLA gather path, the ragged Pallas kernel, and the engine's
    cross-pool handoff import — so the representation has exactly one
    reader (the write side is :func:`_quantize_rows`)."""
    return q8.astype(dtype) * s[..., None].astype(dtype)


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_s, l_s, acc_s, *, scale, page_size):
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, -jnp.inf)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    q = q_ref[0, 0].astype(jnp.float32)         # [group, d]
    k = k_ref[0, 0].astype(jnp.float32)         # [page, d]
    v = v_ref[0, 0].astype(jnp.float32)         # [page, d]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    # mask tokens beyond this sequence's length
    token_idx = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    s = jnp.where(token_idx < len_ref[b], s, -jnp.inf)

    m_prev = m_s[...]                           # [group, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked pages (m_new = -inf): exp(-inf - -inf) -> use 0
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
    pexp = jnp.where(jnp.isfinite(s), jnp.exp(s - safe_m), 0.0)

    l_s[...] = l_s[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_s[...] = m_new

    @pl.when(p == n_pages - 1)
    def _flush():
        l = l_s[...]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / l).astype(o_ref.dtype)


def _xla_paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                         scale):
    """Reference composition: gather pages then masked attention.

    Handles EMPTY slots (``context_lens == 0``): freshly-joined or
    inactive continuous-batching slots carry arbitrary block-table
    entries over uninitialized pages, so their rows are forced to zero
    instead of softmax(all -inf) = NaN over garbage gathers. Pools may
    be plain arrays or int8 dicts ``{"q8": [kv, pages, page, d] int8,
    "s": [kv, pages, page] f32}`` — gathered rows decode through the
    shared :func:`_dequant` rule; the elementwise scale feeds straight
    into the einsum so XLA fuses it (no separate f32 pool copy)."""
    bsz, n_heads, d = q.shape
    quant = isinstance(k_pages, dict)
    kp = k_pages["q8"] if quant else k_pages
    n_kv, total_pages, page, _ = kp.shape
    group = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]
    max_len = pages_per_seq * page
    bt = jnp.clip(block_tables, 0, total_pages - 1)

    def gather(pages):                 # [n_kv, b, pp, page, ...]
        g = jnp.take(pages, bt, axis=1)
        return jnp.moveaxis(g, 1, 0).reshape(
            (bsz, n_kv, max_len) + pages.shape[3:])

    qg = q.reshape(bsz, n_kv, group, d).astype(jnp.float32)
    if quant:
        kg = _dequant(gather(k_pages["q8"]), gather(k_pages["s"]))
    else:
        kg = gather(k_pages).astype(jnp.float32)
    s = jnp.einsum("bkgd,bktd->bkgt", qg, kg) * scale
    mask = jnp.arange(max_len)[None, None, None, :] \
        < context_lens[:, None, None, None]
    s = jnp.where(mask, s, -jnp.inf)
    # empty slot: all positions masked -> softmax would be 0/0 = NaN
    w = jnp.where(mask, jax.nn.softmax(s, axis=-1), 0.0)
    if quant:
        vg = _dequant(gather(v_pages["q8"]), gather(v_pages["s"]))
    else:
        vg = gather(v_pages).astype(jnp.float32)
    out = jnp.einsum("bkgt,bktd->bkgd", w, vg)
    return out.reshape(bsz, n_heads, d).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret",
                                             "use_kernel"))
def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    scale=None, interpret=None, use_kernel=None):
    """Decode-step attention over a paged KV cache. See module docstring.

    Slots with ``context_lens == 0`` (inactive / freshly-joined
    continuous-batching slots) return ZEROS: their block-table rows may
    reference uninitialized pages, so the gather indices are clamped
    into range and the fully-masked softmax short-circuits to zero
    weight instead of NaN. int8 pools (``{"q8", "s"}`` dicts from
    :func:`quantize_kv_pages` / :func:`paged_kv_write_chunk`) take the
    XLA dequant-fused gather path."""
    bsz, n_heads, d = q.shape
    if isinstance(k_pages, dict):      # int8 pool: XLA dequant path
        if scale is None:
            scale = d ** -0.5
        return _xla_paged_attention(q, k_pages, v_pages, block_tables,
                                    context_lens, scale)
    n_kv, total_pages, page, _ = k_pages.shape
    assert n_heads % n_kv == 0
    group = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    if use_kernel is None:
        # kernel path needs TPU-friendly tiles; group dim feeds the MXU
        use_kernel = (d in (64, 128, 256) and page % 128 == 0) \
            or interpret
    if not use_kernel:
        return _xla_paged_attention(q, k_pages, v_pages, block_tables,
                                    context_lens, scale)

    # empty-slot safety: the scalar-prefetched index_map DMAs page
    # bt[b, p] unconditionally — garbage ids from inactive rows must be
    # clamped into the pool before they pick the DMA source
    block_tables = jnp.clip(block_tables, 0, total_pages - 1)
    qg = q.reshape(bsz, n_kv, group, d)
    grid = (bsz, n_kv, pages_per_seq)

    kernel = functools.partial(_decode_kernel, scale=scale, page_size=page)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # block_tables, context_lens
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda b, h, p, bt, cl: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, page, d),
                         lambda b, h, p, bt, cl: (h, bt[b, p], 0, 0)),
            pl.BlockSpec((1, 1, page, d),
                         lambda b, h, p, bt, cl: (h, bt[b, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda b, h, p, bt, cl: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bsz, n_kv, group, d), q.dtype),
        interpret=interpret,
    )(block_tables, context_lens,
      qg.reshape(bsz, n_kv, group, d),
      k_pages.reshape(n_kv, total_pages, page, d),
      v_pages)
    return out.reshape(bsz, n_heads, d)


# ---------------------------------------------------------------------------
# Ragged paged attention: mixed prefill+decode rows in ONE launch.
#
# The serving engine used to dispatch two jitted programs per scheduler
# tick — a [1, prefill_chunk] chunked-prefill step and a [max_slots]
# decode step. The ragged kernel kills that dispatch seam: the batch is
# a FLAT token axis [T] packed row-major (row r owns tokens
# q_starts[r] .. q_starts[r]+query_lens[r]), where a decode row
# contributes query_lens == 1 token and a prefill row contributes its
# whole chunk. context_lens[r] is the total KV length of row r AFTER
# this step's tokens are written, so token j of row r (0-based within
# the row) attends causally to KV positions < context_lens[r] -
# query_lens[r] + j + 1. Rows with query_lens == 0 and padding tokens
# (not owned by any row) produce zeros.
# ---------------------------------------------------------------------------


def _ragged_accumulate(q2, k, v, start, n, ctx, p, m_s, l_s, acc_s, *,
                       scale, page_size, group):
    """Online-softmax update of (m, l, acc) scratch for ONE (row, page)
    visit. ``q2`` is the whole flat token batch [T*group, d] — tokens
    outside row ``b``'s [start, start+n) span and KV slots beyond the
    causal limit are masked to -inf, so foreign rows' statistics are
    untouched (alpha == 1 / pexp == 0 for them). Same guarded math as
    :func:`_decode_kernel` (fully-masked visits keep m at -inf)."""
    s = jax.lax.dot_general(q2, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    tok = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
    kv_pos = page_size * p + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    # causal limit for token j = tok - start of row b: ctx - n + j + 1
    limit = ctx - n + (tok - start) + 1
    mask = (tok >= start) & (tok < start + n) & (kv_pos < limit)
    s = jnp.where(mask, s, -jnp.inf)

    m_prev = m_s[...]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
    pexp = jnp.where(jnp.isfinite(s), jnp.exp(s - safe_m), 0.0)
    l_s[...] = l_s[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_s[...] = m_new


def _ragged_kernel(bt_ref, cl_ref, ql_ref, qs_ref, q_ref, k_ref, v_ref,
                   o_ref, m_s, l_s, acc_s, *, scale, page_size, group):
    """Grid (n_kv_heads, rows, pages_per_seq). The output block depends
    only on the head index, so it is revisited consecutively across the
    (row, page) inner dims — scratch spans the WHOLE flat token axis
    and is reset once per head, flushed at the last (row, page) step.
    v1 masking cost: each (row, page) visit computes scores for all T
    tokens and masks the foreign ones; fine for serving-step T (tens to
    low hundreds), revisit with per-row q blocking if T grows."""
    b = pl.program_id(1)
    p = pl.program_id(2)
    last = (b == pl.num_programs(1) - 1) & (p == pl.num_programs(2) - 1)

    @pl.when((b == 0) & (p == 0))
    def _init():
        m_s[...] = jnp.full_like(m_s, -jnp.inf)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when((ql_ref[b] > 0) & (page_size * p < cl_ref[b]))
    def _accum():
        q = q_ref[:, 0].astype(jnp.float32)       # [T, group, d]
        t, g, d = q.shape
        _ragged_accumulate(q.reshape(t * g, d),
                           k_ref[0, 0].astype(jnp.float32),
                           v_ref[0, 0].astype(jnp.float32),
                           qs_ref[b], ql_ref[b], cl_ref[b], p,
                           m_s, l_s, acc_s, scale=scale,
                           page_size=page_size, group=group)

    @pl.when(last)
    def _flush():
        l = l_s[...]
        l = jnp.where(l == 0.0, 1.0, l)
        t = q_ref.shape[0]
        d = q_ref.shape[3]
        o_ref[:, 0] = (acc_s[...] / l).reshape(t, group, d) \
            .astype(o_ref.dtype)


def _ragged_kernel_q8(bt_ref, cl_ref, ql_ref, qs_ref, q_ref, k8_ref,
                      ks_ref, v8_ref, vs_ref, o_ref, m_s, l_s, acc_s, *,
                      scale, page_size, group):
    """int8-pool variant of :func:`_ragged_kernel`: K/V page blocks
    arrive as (q8, per-row scale) pairs and decode in-register through
    the shared :func:`_dequant` rule."""
    b = pl.program_id(1)
    p = pl.program_id(2)
    last = (b == pl.num_programs(1) - 1) & (p == pl.num_programs(2) - 1)

    @pl.when((b == 0) & (p == 0))
    def _init():
        m_s[...] = jnp.full_like(m_s, -jnp.inf)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when((ql_ref[b] > 0) & (page_size * p < cl_ref[b]))
    def _accum():
        q = q_ref[:, 0].astype(jnp.float32)       # [T, group, d]
        t, g, d = q.shape
        k = _dequant(k8_ref[0, 0], ks_ref[0, 0])      # [page, d]
        v = _dequant(v8_ref[0, 0], vs_ref[0, 0])
        _ragged_accumulate(q.reshape(t * g, d), k, v,
                           qs_ref[b], ql_ref[b], cl_ref[b], p,
                           m_s, l_s, acc_s, scale=scale,
                           page_size=page_size, group=group)

    @pl.when(last)
    def _flush():
        l = l_s[...]
        l = jnp.where(l == 0.0, 1.0, l)
        t = q_ref.shape[0]
        d = q_ref.shape[3]
        o_ref[:, 0] = (acc_s[...] / l).reshape(t, group, d) \
            .astype(o_ref.dtype)


def _token_rows(q_starts, query_lens, n_tokens):
    """Derive the per-token owning row [T] (-1 for padding tokens) from
    per-row spans. Used by the XLA fallback when the caller did not
    pass ``row_of`` explicitly."""
    t = jnp.arange(n_tokens)
    in_row = (t[None, :] >= q_starts[:, None]) & \
        (t[None, :] < (q_starts + query_lens)[:, None])
    return jnp.where(jnp.any(in_row, axis=0),
                     jnp.argmax(in_row, axis=0), -1)


def _xla_ragged_paged_attention(q, k_pages, v_pages, block_tables,
                                context_lens, query_lens, q_starts,
                                row_of, scale):
    """XLA-composition fallback: expand the ragged batch to per-TOKEN
    (lens, block-table) views and delegate to the existing batched
    :func:`_xla_paged_attention` (b == T, one 'sequence' per token with
    its causal prefix length). Padding tokens get lens == 0 -> zeros."""
    n_tokens = q.shape[0]
    n_rows = block_tables.shape[0]
    if row_of is None:
        row_of = _token_rows(q_starts, query_lens, n_tokens)
    r = jnp.clip(row_of, 0, n_rows - 1)
    j = jnp.arange(n_tokens) - q_starts[r]        # token idx within row
    lens = context_lens[r] - query_lens[r] + j + 1
    lens = jnp.where(row_of >= 0, jnp.maximum(lens, 0), 0)
    bt_tok = jnp.take(block_tables, r, axis=0)    # [T, pages_per_seq]
    return _xla_paged_attention(q, k_pages, v_pages, bt_tok, lens, scale)


@functools.partial(jax.jit, static_argnames=("scale", "interpret",
                                             "use_kernel"))
def ragged_paged_attention(q, k_pages, v_pages, block_tables,
                           context_lens, query_lens, q_starts=None,
                           row_of=None, scale=None, interpret=None,
                           use_kernel=None):
    """Attention for a RAGGED batch mixing prefill and decode rows over
    one paged KV pool, in one launch (arxiv 2604.15464 style).

    Layouts:
      q:            [n_tokens, num_heads, head_dim] — flat token axis,
                    rows packed contiguously (row r owns tokens
                    q_starts[r] .. q_starts[r] + query_lens[r])
      k/v_pages:    fp pool [n_kv, pages, page, d] or int8
                    ``{"q8","s"}`` dict
      block_tables: [n_rows, pages_per_seq] int32
      context_lens: [n_rows] — KV length INCLUDING this step's tokens
      query_lens:   [n_rows] — tokens this row contributes (1 for a
                    decode row, the chunk length for prefill, 0 for an
                    idle slot)
      q_starts:     [n_rows] exclusive prefix of query_lens (derived
                    when omitted)
      row_of:       [n_tokens] owning row per token, -1 for padding
                    (derived from q_starts/query_lens when omitted)

    Token j of row r attends to KV positions
    ``< context_lens[r] - query_lens[r] + j + 1`` (causal within the
    chunk, full history before it). Idle rows (query_lens == 0) and
    padding tokens return zeros. int8 pools decode through the shared
    :func:`_dequant` rule on both the kernel and XLA paths."""
    n_tokens, n_heads, d = q.shape
    quant = isinstance(k_pages, dict)
    kp = k_pages["q8"] if quant else k_pages
    n_kv, total_pages, page, _ = kp.shape
    assert n_heads % n_kv == 0
    group = n_heads // n_kv
    n_rows, pages_per_seq = block_tables.shape
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    if q_starts is None:
        q_starts = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(query_lens.astype(jnp.int32))[:-1]])
    if use_kernel is None:
        # same tile constraints as the decode kernel; int8 dicts default
        # to the XLA composition (matching paged_attention) unless the
        # caller opts the kernel in explicitly
        use_kernel = (not quant) and \
            ((d in (64, 128, 256) and page % 128 == 0) or interpret)
    if not use_kernel:
        return _xla_ragged_paged_attention(
            q, k_pages, v_pages, block_tables, context_lens, query_lens,
            q_starts, row_of, scale)

    block_tables = jnp.clip(block_tables, 0, total_pages - 1)
    cl = context_lens.astype(jnp.int32)
    ql = query_lens.astype(jnp.int32)
    qs = q_starts.astype(jnp.int32)
    qr = q.reshape(n_tokens, n_kv, group, d)
    grid = (n_kv, n_rows, pages_per_seq)
    scratch = [
        pltpu.VMEM((n_tokens * group, 1), jnp.float32),
        pltpu.VMEM((n_tokens * group, 1), jnp.float32),
        pltpu.VMEM((n_tokens * group, d), jnp.float32),
    ]
    out_spec = pl.BlockSpec((n_tokens, 1, group, d),
                            lambda h, b, p, *_: (0, h, 0, 0))
    q_spec = pl.BlockSpec((n_tokens, 1, group, d),
                          lambda h, b, p, *_: (0, h, 0, 0))
    if quant:
        kernel = functools.partial(_ragged_kernel_q8, scale=scale,
                                   page_size=page, group=group)
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,      # bt, cl, ql, qs
            grid=grid,
            in_specs=[
                q_spec,
                pl.BlockSpec((1, 1, page, d),
                             lambda h, b, p, bt, *_: (h, bt[b, p], 0, 0)),
                pl.BlockSpec((1, 1, page),
                             lambda h, b, p, bt, *_: (h, bt[b, p], 0)),
                pl.BlockSpec((1, 1, page, d),
                             lambda h, b, p, bt, *_: (h, bt[b, p], 0, 0)),
                pl.BlockSpec((1, 1, page),
                             lambda h, b, p, bt, *_: (h, bt[b, p], 0)),
            ],
            out_specs=out_spec,
            scratch_shapes=scratch,
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((n_tokens, n_kv, group, d),
                                           q.dtype),
            interpret=interpret,
        )(block_tables, cl, ql, qs, qr,
          k_pages["q8"], k_pages["s"], v_pages["q8"], v_pages["s"])
        return out.reshape(n_tokens, n_heads, d)

    kernel = functools.partial(_ragged_kernel, scale=scale,
                               page_size=page, group=group)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,          # bt, cl, ql, qs
        grid=grid,
        in_specs=[
            q_spec,
            pl.BlockSpec((1, 1, page, d),
                         lambda h, b, p, bt, *_: (h, bt[b, p], 0, 0)),
            pl.BlockSpec((1, 1, page, d),
                         lambda h, b, p, bt, *_: (h, bt[b, p], 0, 0)),
        ],
        out_specs=out_spec,
        scratch_shapes=scratch,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_tokens, n_kv, group, d),
                                       q.dtype),
        interpret=interpret,
    )(block_tables, cl, ql, qs, qr, k_pages, v_pages)
    return out.reshape(n_tokens, n_heads, d)


@jax.jit
def paged_kv_write(k_pages, v_pages, k_new, v_new, block_tables,
                   context_lens):
    """Append one decode step's k/v ([batch, n_kv, d]) into the paged cache
    at position ``context_lens`` (the slot the new token occupies).
    Returns (k_pages, v_pages) updated — functional, donatable under jit.
    Reference analog: the cache-write half of
    block_multi_head_attention_kernel.cu."""
    n_kv, total_pages, page, d = k_pages.shape
    bsz = k_new.shape[0]
    pages_per_seq = block_tables.shape[1]
    pos = context_lens                     # [b], slot of the new token
    # sequences whose pages are already full have no slot: no-op write
    # (otherwise the clamped index would corrupt the last page's slot 0)
    valid = pos < page * pages_per_seq
    page_slot = jnp.minimum(pos // page, pages_per_seq - 1)
    page_idx = jnp.take_along_axis(
        block_tables, page_slot[:, None], axis=1)[:, 0]       # [b]
    slot = pos % page                      # [b]

    def write(pages, new):
        # scatter [b, n_kv, d] into [n_kv, total_pages, page, d]
        def one(pages, b):
            cur = pages[:, page_idx[b], slot[b], :]
            val = jnp.where(valid[b], new[b].astype(pages.dtype), cur)
            return pages.at[:, page_idx[b], slot[b], :].set(val)

        return jax.lax.fori_loop(0, bsz, lambda b, p: one(p, b), pages)

    return write(k_pages, k_new), write(v_pages, v_new)


def _quantize_rows(x):
    """Per-(row, head) symmetric int8 for [..., n_kv, d] K/V rows (the
    paged analog of models/generation.py _quantize_kv: each written row
    carries its own scale, so the read side is exact)."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, s


def quantize_kv_pages(pages):
    """Quantize a bf16/f32 pool [n_kv, pages, page, d] into the int8
    pool representation ``{"q8": int8 same shape, "s": [n_kv, pages,
    page] f32}`` consumed by :func:`paged_attention` and
    :func:`paged_kv_write_chunk`."""
    q, s = _quantize_rows(pages)
    return {"q8": q, "s": s}


@jax.jit
def paged_kv_write_chunk(k_pages, v_pages, k_new, v_new, block_tables,
                         pos):
    """Scatter a CHUNK of per-row-position k/v rows into paged pools.

    k/v_new: [b, g, n_kv, d] — g tokens per row at positions
    ``pos [b, g]``; block_tables: [b, pages_per_seq]. Rows with
    ``pos < 0`` or past the block-table window are DROPPED (inactive
    continuous-batching slots / prefill-chunk padding). Pools may be
    plain arrays or int8 ``{"q8", "s"}`` dicts (rows are quantized at
    write time, per-row scales ride in ``"s"``). Functional — returns
    the updated (k_pages, v_pages).
    """
    quant = isinstance(k_pages, dict)
    kp = k_pages["q8"] if quant else k_pages
    n_kv, total_pages, page, d = kp.shape
    b, g = pos.shape
    pages_per_seq = block_tables.shape[1]
    window = page * pages_per_seq
    valid = (pos >= 0) & (pos < window)
    safe = jnp.clip(pos, 0, window - 1)
    page_id = jnp.take_along_axis(
        jnp.clip(block_tables, 0, total_pages - 1),
        safe // page, axis=1)                       # [b, g]
    flat_slot = page_id * page + safe % page
    # invalid rows get an out-of-range slot; scatter mode="drop" skips
    flat_slot = jnp.where(valid, flat_slot, total_pages * page)
    idx = flat_slot.reshape(b * g)

    def write(pages, new):
        rows = new.reshape(b * g, n_kv, -1).swapaxes(0, 1)  # [kv, M, d]
        if not quant:
            flat = pages.reshape(n_kv, total_pages * page, d)
            flat = flat.at[:, idx].set(rows.astype(flat.dtype),
                                       mode="drop")
            return flat.reshape(n_kv, total_pages, page, d)
        q8, s = _quantize_rows(rows)
        qflat = pages["q8"].reshape(n_kv, total_pages * page, d)
        sflat = pages["s"].reshape(n_kv, total_pages * page)
        qflat = qflat.at[:, idx].set(q8, mode="drop")
        sflat = sflat.at[:, idx].set(s, mode="drop")
        return {"q8": qflat.reshape(n_kv, total_pages, page, d),
                "s": sflat.reshape(n_kv, total_pages, page)}

    return write(k_pages, k_new), write(v_pages, v_new)
