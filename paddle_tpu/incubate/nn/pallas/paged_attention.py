"""Pallas TPU paged-attention decode kernel (block KV cache).

TPU-native analog of the reference paged/blocked-KV fused kernels
(reference: phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu and
masked_multihead_attention_kernel.cu; python surface
incubate/nn/functional/block_multihead_attention.py).

Single-token decode: each (batch, kv_head) program walks that sequence's
pages via a scalar-prefetched block table — the page indirection happens in
the BlockSpec index_map, so only the pages actually referenced are DMA'd
into VMEM (the point of paged attention). Online-softmax accumulation in
f32 VMEM scratch across the page grid dimension.

Layouts:
  q:            [batch, num_heads, head_dim]   (one decode step)
  k/v_pages:    [num_kv_heads, total_pages, page_size, head_dim]
  block_tables: [batch, pages_per_seq] int32 (page id per slot)
  context_lens: [batch] int32
Grouped-query attention: num_heads % num_kv_heads == 0; the group of query
heads sharing a kv head is processed together (one MXU matmul per page).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = ["paged_attention", "paged_kv_write", "paged_kv_write_chunk",
           "quantize_kv_pages"]


def _interpret_default() -> bool:
    return jax.default_backend() != "tpu"


def _decode_kernel(bt_ref, len_ref, q_ref, k_ref, v_ref, o_ref,
                   m_s, l_s, acc_s, *, scale, page_size):
    b = pl.program_id(0)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, -jnp.inf)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    q = q_ref[0, 0].astype(jnp.float32)         # [group, d]
    k = k_ref[0, 0].astype(jnp.float32)         # [page, d]
    v = v_ref[0, 0].astype(jnp.float32)         # [page, d]

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    # mask tokens beyond this sequence's length
    token_idx = p * page_size + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 1)
    s = jnp.where(token_idx < len_ref[b], s, -jnp.inf)

    m_prev = m_s[...]                           # [group, 1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # guard fully-masked pages (m_new = -inf): exp(-inf - -inf) -> use 0
    safe_m = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    alpha = jnp.where(jnp.isfinite(m_prev), jnp.exp(m_prev - safe_m), 0.0)
    pexp = jnp.where(jnp.isfinite(s), jnp.exp(s - safe_m), 0.0)

    l_s[...] = l_s[...] * alpha + jnp.sum(pexp, axis=-1, keepdims=True)
    acc_s[...] = acc_s[...] * alpha + jax.lax.dot_general(
        pexp, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_s[...] = m_new

    @pl.when(p == n_pages - 1)
    def _flush():
        l = l_s[...]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[...] / l).astype(o_ref.dtype)


def _xla_paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                         scale):
    """Reference composition: gather pages then masked attention.

    Handles EMPTY slots (``context_lens == 0``): freshly-joined or
    inactive continuous-batching slots carry arbitrary block-table
    entries over uninitialized pages, so their rows are forced to zero
    instead of softmax(all -inf) = NaN over garbage gathers. Pools may
    be plain arrays or int8 dicts ``{"q8": [kv, pages, page, d] int8,
    "s": [kv, pages, page] f32}`` — the dequant is applied on the score
    side / folded into the V weights exactly like the dense int8 cache
    path in models/generation.py, so no bf16 copy of the pool is ever
    materialized."""
    bsz, n_heads, d = q.shape
    quant = isinstance(k_pages, dict)
    kp = k_pages["q8"] if quant else k_pages
    n_kv, total_pages, page, _ = kp.shape
    group = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]
    max_len = pages_per_seq * page
    bt = jnp.clip(block_tables, 0, total_pages - 1)

    def gather(pages):                 # [n_kv, b, pp, page, ...]
        g = jnp.take(pages, bt, axis=1)
        return jnp.moveaxis(g, 1, 0).reshape(
            (bsz, n_kv, max_len) + pages.shape[3:])

    qg = q.reshape(bsz, n_kv, group, d).astype(jnp.float32)
    if quant:
        kg = gather(k_pages["q8"])
        ks = gather(k_pages["s"])               # [b, n_kv, max_len]
        s = jnp.einsum("bkgd,bktd->bkgt", qg, kg.astype(jnp.float32))
        s = s * ks[:, :, None, :] * scale
    else:
        kg = gather(k_pages)
        s = jnp.einsum("bkgd,bktd->bkgt", qg,
                       kg.astype(jnp.float32)) * scale
    mask = jnp.arange(max_len)[None, None, None, :] \
        < context_lens[:, None, None, None]
    s = jnp.where(mask, s, -jnp.inf)
    # empty slot: all positions masked -> softmax would be 0/0 = NaN
    w = jnp.where(mask, jax.nn.softmax(s, axis=-1), 0.0)
    if quant:
        vg = gather(v_pages["q8"])
        vs = gather(v_pages["s"])
        w = w * vs[:, :, None, :]
    else:
        vg = gather(v_pages)
    out = jnp.einsum("bkgt,bktd->bkgd", w, vg.astype(jnp.float32))
    return out.reshape(bsz, n_heads, d).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret",
                                             "use_kernel"))
def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    scale=None, interpret=None, use_kernel=None):
    """Decode-step attention over a paged KV cache. See module docstring.

    Slots with ``context_lens == 0`` (inactive / freshly-joined
    continuous-batching slots) return ZEROS: their block-table rows may
    reference uninitialized pages, so the gather indices are clamped
    into range and the fully-masked softmax short-circuits to zero
    weight instead of NaN. int8 pools (``{"q8", "s"}`` dicts from
    :func:`quantize_kv_pages` / :func:`paged_kv_write_chunk`) take the
    XLA dequant-fused gather path."""
    bsz, n_heads, d = q.shape
    if isinstance(k_pages, dict):      # int8 pool: XLA dequant path
        if scale is None:
            scale = d ** -0.5
        return _xla_paged_attention(q, k_pages, v_pages, block_tables,
                                    context_lens, scale)
    n_kv, total_pages, page, _ = k_pages.shape
    assert n_heads % n_kv == 0
    group = n_heads // n_kv
    pages_per_seq = block_tables.shape[1]
    if scale is None:
        scale = d ** -0.5
    if interpret is None:
        interpret = _interpret_default()
    if use_kernel is None:
        # kernel path needs TPU-friendly tiles; group dim feeds the MXU
        use_kernel = (d in (64, 128, 256) and page % 128 == 0) \
            or interpret
    if not use_kernel:
        return _xla_paged_attention(q, k_pages, v_pages, block_tables,
                                    context_lens, scale)

    # empty-slot safety: the scalar-prefetched index_map DMAs page
    # bt[b, p] unconditionally — garbage ids from inactive rows must be
    # clamped into the pool before they pick the DMA source
    block_tables = jnp.clip(block_tables, 0, total_pages - 1)
    qg = q.reshape(bsz, n_kv, group, d)
    grid = (bsz, n_kv, pages_per_seq)

    kernel = functools.partial(_decode_kernel, scale=scale, page_size=page)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,          # block_tables, context_lens
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, group, d),
                         lambda b, h, p, bt, cl: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, page, d),
                         lambda b, h, p, bt, cl: (h, bt[b, p], 0, 0)),
            pl.BlockSpec((1, 1, page, d),
                         lambda b, h, p, bt, cl: (h, bt[b, p], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, group, d),
                               lambda b, h, p, bt, cl: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((bsz, n_kv, group, d), q.dtype),
        interpret=interpret,
    )(block_tables, context_lens,
      qg.reshape(bsz, n_kv, group, d),
      k_pages.reshape(n_kv, total_pages, page, d),
      v_pages)
    return out.reshape(bsz, n_heads, d)


@jax.jit
def paged_kv_write(k_pages, v_pages, k_new, v_new, block_tables,
                   context_lens):
    """Append one decode step's k/v ([batch, n_kv, d]) into the paged cache
    at position ``context_lens`` (the slot the new token occupies).
    Returns (k_pages, v_pages) updated — functional, donatable under jit.
    Reference analog: the cache-write half of
    block_multi_head_attention_kernel.cu."""
    n_kv, total_pages, page, d = k_pages.shape
    bsz = k_new.shape[0]
    pages_per_seq = block_tables.shape[1]
    pos = context_lens                     # [b], slot of the new token
    # sequences whose pages are already full have no slot: no-op write
    # (otherwise the clamped index would corrupt the last page's slot 0)
    valid = pos < page * pages_per_seq
    page_slot = jnp.minimum(pos // page, pages_per_seq - 1)
    page_idx = jnp.take_along_axis(
        block_tables, page_slot[:, None], axis=1)[:, 0]       # [b]
    slot = pos % page                      # [b]

    def write(pages, new):
        # scatter [b, n_kv, d] into [n_kv, total_pages, page, d]
        def one(pages, b):
            cur = pages[:, page_idx[b], slot[b], :]
            val = jnp.where(valid[b], new[b].astype(pages.dtype), cur)
            return pages.at[:, page_idx[b], slot[b], :].set(val)

        return jax.lax.fori_loop(0, bsz, lambda b, p: one(p, b), pages)

    return write(k_pages, k_new), write(v_pages, v_new)


def _quantize_rows(x):
    """Per-(row, head) symmetric int8 for [..., n_kv, d] K/V rows (the
    paged analog of models/generation.py _quantize_kv: each written row
    carries its own scale, so the read side is exact)."""
    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / s[..., None]),
                 -127, 127).astype(jnp.int8)
    return q, s


def quantize_kv_pages(pages):
    """Quantize a bf16/f32 pool [n_kv, pages, page, d] into the int8
    pool representation ``{"q8": int8 same shape, "s": [n_kv, pages,
    page] f32}`` consumed by :func:`paged_attention` and
    :func:`paged_kv_write_chunk`."""
    q, s = _quantize_rows(pages)
    return {"q8": q, "s": s}


@jax.jit
def paged_kv_write_chunk(k_pages, v_pages, k_new, v_new, block_tables,
                         pos):
    """Scatter a CHUNK of per-row-position k/v rows into paged pools.

    k/v_new: [b, g, n_kv, d] — g tokens per row at positions
    ``pos [b, g]``; block_tables: [b, pages_per_seq]. Rows with
    ``pos < 0`` or past the block-table window are DROPPED (inactive
    continuous-batching slots / prefill-chunk padding). Pools may be
    plain arrays or int8 ``{"q8", "s"}`` dicts (rows are quantized at
    write time, per-row scales ride in ``"s"``). Functional — returns
    the updated (k_pages, v_pages).
    """
    quant = isinstance(k_pages, dict)
    kp = k_pages["q8"] if quant else k_pages
    n_kv, total_pages, page, d = kp.shape
    b, g = pos.shape
    pages_per_seq = block_tables.shape[1]
    window = page * pages_per_seq
    valid = (pos >= 0) & (pos < window)
    safe = jnp.clip(pos, 0, window - 1)
    page_id = jnp.take_along_axis(
        jnp.clip(block_tables, 0, total_pages - 1),
        safe // page, axis=1)                       # [b, g]
    flat_slot = page_id * page + safe % page
    # invalid rows get an out-of-range slot; scatter mode="drop" skips
    flat_slot = jnp.where(valid, flat_slot, total_pages * page)
    idx = flat_slot.reshape(b * g)

    def write(pages, new):
        rows = new.reshape(b * g, n_kv, -1).swapaxes(0, 1)  # [kv, M, d]
        if not quant:
            flat = pages.reshape(n_kv, total_pages * page, d)
            flat = flat.at[:, idx].set(rows.astype(flat.dtype),
                                       mode="drop")
            return flat.reshape(n_kv, total_pages, page, d)
        q8, s = _quantize_rows(rows)
        qflat = pages["q8"].reshape(n_kv, total_pages * page, d)
        sflat = pages["s"].reshape(n_kv, total_pages * page)
        qflat = qflat.at[:, idx].set(q8, mode="drop")
        sflat = sflat.at[:, idx].set(s, mode="drop")
        return {"q8": qflat.reshape(n_kv, total_pages, page, d),
                "s": sflat.reshape(n_kv, total_pages, page)}

    return write(k_pages, k_new), write(v_pages, v_new)
