from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate
from .grad_clip import ClipGradForMOEByGlobalNorm
from .moe_layer import MoELayer

__all__ = ["MoELayer", "BaseGate", "NaiveGate", "GShardGate", "SwitchGate",
           "ClipGradForMOEByGlobalNorm"]
