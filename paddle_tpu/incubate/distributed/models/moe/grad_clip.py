"""MoE-aware global-norm gradient clipping (reference:
python/paddle/incubate/distributed/models/moe/grad_clip.py
ClipGradForMOEByGlobalNorm).

Expert parameters live only on their EP rank, so the global norm must sum
the *local* expert-grad norm-squares across the MoE group before combining
with the (replicated) dense-param norm.
"""
from __future__ import annotations

import jax.numpy as jnp

from paddle_tpu.core.tensor import Tensor

__all__ = ["ClipGradForMOEByGlobalNorm"]


class ClipGradForMOEByGlobalNorm:
    def __init__(self, clip_norm: float, is_expert_param_func=None,
                 moe_group=None):
        self.clip_norm = float(clip_norm)
        self.moe_group = moe_group
        self.is_expert_param = is_expert_param_func or (
            lambda p: getattr(p, "no_sync", False))

    def __call__(self, params_grads):
        from paddle_tpu.distributed import collective as dist

        normal_sq = 0.0
        expert_sq = 0.0
        for p, g in params_grads:
            if g is None:
                continue
            s = float(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            if self.is_expert_param(p):
                expert_sq += s
            else:
                normal_sq += s
        if self.moe_group is not None and self.moe_group.nranks > 1:
            t = Tensor(jnp.asarray([expert_sq], dtype=jnp.float32))
            dist.all_reduce(t, group=self.moe_group)
            expert_sq = float(t._data[0])
        global_norm = (normal_sq + expert_sq) ** 0.5
        if global_norm <= self.clip_norm:
            return params_grads
        scale = self.clip_norm / (global_norm + 1e-6)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
            else:
                out.append((p, Tensor(g._data * scale)))
        return out
