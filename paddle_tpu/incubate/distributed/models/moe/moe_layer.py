"""MoE layer with expert parallelism (reference surface:
python/paddle/incubate/distributed/models/moe/moe_layer.py:263 MoELayer,
MoEScatter:99 / MoEGather:149 over global_scatter/global_gather all-to-all).

TPU-first design
----------------
The reference dispatches tokens with dynamic scatter positions and a
variable-size ncclAllToAll. Dynamic shapes are hostile to XLA, so this
implementation uses GShard fixed-capacity dispatch:

  combine_weights [S, E, C] = gate output (S tokens, E global experts,
  C capacity slots)
  dispatch:  x_e[E, C, M] = einsum('sec,sm->ecm', dispatch_mask, x)
  exchange:  all_to_all over the expert-parallel group on the E axis
             (E = world_size * num_local_expert), so each rank holds
             [world, local_E, C, M] -> its local experts' tokens
  experts:   per-local-expert FFN (batched, MXU-friendly)
  exchange back + combine: y = einsum('sec,ecm->sm', combine_weights, y_e)

Everything is static-shape; under jit the all_to_all lowers to a single
XLA AllToAll on the ICI mesh.
"""
from __future__ import annotations

from typing import List, Optional

import jax.numpy as jnp

import jax

from paddle_tpu import nn
from paddle_tpu import observability as _obs
from paddle_tpu.autograd import PyLayer
from paddle_tpu.core.autograd import run_op
from paddle_tpu.core.tensor import Tensor
from .gate import BaseGate, GShardGate, NaiveGate, SwitchGate

__all__ = ["MoELayer"]


class _AllToAllOnAxis(PyLayer):
    """Differentiable all_to_all on axis 0 over an EP group; the backward is
    the inverse all_to_all (reference MoEScatter/MoEGather backward)."""

    @staticmethod
    def forward(ctx, x, group):
        from paddle_tpu.distributed import collective as dist

        ctx.group = group
        world = group.nranks if group is not None else 1
        if world <= 1:
            return Tensor(x._data)
        parts = [Tensor(p) for p in jnp.split(x._data, world, axis=0)]
        outs: List[Tensor] = [Tensor(jnp.zeros_like(p._data)) for p in parts]
        dist.all_to_all(outs, parts, group=group)
        return Tensor(jnp.concatenate([o._data for o in outs], axis=0))

    @staticmethod
    def backward(ctx, dy):
        from paddle_tpu.distributed import collective as dist

        group = ctx.group
        world = group.nranks if group is not None else 1
        if world <= 1:
            return Tensor(dy._data)
        parts = [Tensor(p) for p in jnp.split(dy._data, world, axis=0)]
        outs: List[Tensor] = [Tensor(jnp.zeros_like(p._data)) for p in parts]
        dist.all_to_all(outs, parts, group=group)
        return Tensor(jnp.concatenate([o._data for o in outs], axis=0))


def _make_gate(gate, d_model, num_expert, world_size, top_k, group):
    if isinstance(gate, BaseGate):
        return gate
    name = gate or "gshard"
    if name == "naive":
        return NaiveGate(d_model, num_expert, world_size, topk=top_k)
    if name == "gshard":
        return GShardGate(d_model, num_expert, world_size, topk=2, group=group)
    if name == "switch":
        return SwitchGate(d_model, num_expert, world_size, topk=1, group=group)
    raise ValueError(f"unknown gate type {gate!r}")


class MoELayer(nn.Layer):
    """Mixture-of-experts layer (reference: moe_layer.py:263).

    Args:
        d_model: hidden size of tokens.
        experts: list of expert Layers held on this rank (local experts).
        gate: "gshard" | "switch" | "naive" | a BaseGate instance.
        moe_group: expert-parallel communication group (tokens exchanged).
        mp_group: tensor-parallel group experts are sharded over (optional;
            grads of non-expert params are synced by the caller as usual).
        top_k: number of experts per token (naive gate only; gshard=2,
            switch=1).
    """

    def __init__(self, d_model: int, experts: List[nn.Layer],
                 gate: str | BaseGate = "gshard", moe_group=None,
                 mp_group=None, top_k: int = 2, **kwargs):
        super().__init__()
        self.d_model = d_model
        self.group = moe_group
        self.mp_group = mp_group
        self.world_size = moe_group.nranks if moe_group is not None else 1
        self.num_expert = len(experts)
        self.experts = nn.LayerList(experts)
        self.top_k = top_k
        self.gate = _make_gate(gate, d_model, self.num_expert,
                               self.world_size, top_k, moe_group)
        # expert params must not be synced by DP/sharding wrappers
        for e in self.experts:
            for p in e.parameters():
                p.no_sync = True

    # ------------------------------------------------------------------
    def _experts_fwd(self, xe: Tensor) -> Tensor:
        """xe: [world*local_E, C, M] -> same shape; slot i%local_E runs
        local expert i (each rank sees every peer's slice for its experts)."""
        world, local_e = self.world_size, self.num_expert
        outs = []
        # reshape to [world, local_E, C, M]: per local expert, batch all
        # ranks' capacity slots into one matmul (MXU-friendly)
        c, m = xe.shape[1], xe.shape[2]
        xr = run_op(lambda a: a.reshape(world, local_e, c, m), [xe],
                    name="moe_reshape")
        for ei in range(local_e):
            xi = run_op(lambda a, ei=ei: a[:, ei].reshape(world * c, m), [xr],
                        name="moe_slice")
            yi = self.experts[ei](xi)
            outs.append(run_op(lambda a: a.reshape(world, 1, c, m), [yi],
                               name="moe_unslice"))
        y = outs[0]
        if local_e > 1:
            y = run_op(lambda *parts: jnp.concatenate(parts, axis=1), outs,
                       name="moe_concat")
        return run_op(lambda a: a.reshape(world * local_e, c, m), [y],
                      name="moe_flatten")

    def forward(self, inp: Tensor) -> Tensor:
        orig_shape = inp.shape
        d = orig_shape[-1]
        assert d == self.d_model
        x = run_op(lambda a: a.reshape(-1, d), [inp], name="moe_flatten_in")

        if isinstance(self.gate, NaiveGate):
            return self._forward_naive(x, orig_shape)

        cw, dm = self.gate(x, training=self.training)  # [S, E, C] each
        if _obs.enabled() and not isinstance(dm._data, jax.core.Tracer):
            self._record_dispatch_telemetry(x, dm)
        # dispatch: [E, C, M]
        xe = run_op(lambda m_, a: jnp.einsum("sec,sm->ecm", m_, a), [dm, x],
                    name="moe_dispatch")
        xe = _AllToAllOnAxis.apply(xe, self.group)
        ye = self._experts_fwd(xe)
        ye = _AllToAllOnAxis.apply(ye, self.group)
        y = run_op(lambda w, a: jnp.einsum("sec,ecm->sm", w, a), [cw, ye],
                   name="moe_combine")
        return run_op(lambda a: a.reshape(orig_shape), [y],
                      name="moe_reshape_out")

    def _record_dispatch_telemetry(self, x, dm):
        """Host-side gate telemetry (eager path only — under jit the
        dispatch mask is a tracer with nothing concrete to read). Load
        imbalance = max/mean per-expert routed tokens; capacity drops =
        top-k assignments the [S, E, C] mask had no slot for."""
        import numpy as np

        mask = np.asarray(dm._data)
        per_expert = mask.sum(axis=(0, 2))           # [E]
        routed = float(per_expert.sum())
        reg = _obs.registry
        reg.counter("moe.tokens_routed").inc(routed)
        topk = int(getattr(self.gate, "top_k", self.top_k))
        reg.counter("moe.capacity_dropped_tokens").inc(
            max(int(x.shape[0]) * topk - routed, 0.0))
        mean = float(per_expert.mean())
        if mean > 0:
            reg.gauge("moe.expert_load_imbalance").set(
                float(per_expert.max()) / mean)

    # ------------------------------------------------------------------
    def _forward_naive(self, x: Tensor, orig_shape) -> Tensor:
        """Naive top-k gate: soft-combine all experts' outputs with gate
        weights built as dense one-hots (no capacity). Single-process only
        (the reference NaiveGate path is likewise the no-EP debug path)."""
        if self.world_size > 1:
            raise NotImplementedError(
                "gate='naive' does not support expert parallelism "
                "(moe_group.nranks>1); use 'gshard' or 'switch'")
        idx, val = self.gate(x)
        probs = run_op(lambda v: jax.nn.softmax(v, axis=-1), [val],
                       name="moe_naive_softmax")
        E = self.world_size * self.num_expert
        outs = [self.experts[e](x) for e in range(self.num_expert)]
        stacked = run_op(lambda *o: jnp.stack(o, axis=1), outs,
                         name="moe_naive_stack")  # [S, E, M]

        def combine(p_, st, id_):
            onehot = jnp.take_along_axis(
                jnp.eye(E, dtype=st.dtype)[None], id_[..., None], axis=1)
            w = jnp.einsum("sk,ske->se", p_, onehot)
            return jnp.einsum("se,sem->sm", w, st)

        y = run_op(lambda p_, st: combine(p_, st, idx._data), [probs, stacked],
                   name="moe_naive_combine")
        return run_op(lambda a: a.reshape(orig_shape), [y],
                      name="moe_reshape_out")
