"""MoE gates (reference surface:
python/paddle/incubate/distributed/models/moe/gate/{base_gate,naive_gate,
gshard_gate,switch_gate}.py).

TPU-first design: every gate produces *static-shape* dispatch/combine
tensors (GShard-style capacity masks, one-hot einsums) instead of the
reference's dynamic scatter positions — dynamic shapes would defeat XLA
tiling onto the MXU. The math (top-k routing, auxiliary load-balance loss,
capacity dropping, switch jitter) matches the reference gates.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.core import random as _random
from paddle_tpu.core.autograd import run_op
from paddle_tpu.core.tensor import Tensor

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate"]


class BaseGate(nn.Layer):
    """Common gate state (reference: gate/base_gate.py)."""

    def __init__(self, num_expert: int, world_size: int = 1):
        super().__init__()
        self.world_size = world_size
        self.num_expert = num_expert
        self.tot_expert = world_size * num_expert
        self.loss = None

    def get_loss(self, clear: bool = True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss

    def set_loss(self, loss):
        self.loss = loss


def _one_hot(idx, depth):
    return jax.nn.one_hot(idx, depth, dtype=jnp.float32)


def _load_balance_loss(gates, mask):
    """GShard aux loss: E * sum_e(mean_s(gates_e) * mean_s(mask_e))."""
    density = jnp.mean(mask, axis=0)            # fraction routed per expert
    density_proxy = jnp.mean(gates, axis=0)     # mean gate prob per expert
    return jnp.sum(density * density_proxy) * gates.shape[-1]


class NaiveGate(BaseGate):
    """Plain top-k softmax gate, no aux loss, no capacity
    (reference: gate/naive_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2):
        super().__init__(num_expert, world_size)
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.top_k = topk

    def forward(self, inp):
        logits = self.gate(inp)
        # indices are non-differentiable: compute outside the tape
        gate_idx = Tensor(jax.lax.top_k(logits._data, self.top_k)[1])
        gate_val = run_op(
            lambda lg: jax.lax.top_k(lg, self.top_k)[0], [logits],
            name="naive_gate_topk")
        return gate_idx, gate_val


def _capacity(num_tokens: int, num_experts: int, cap_factor: float,
              topk: int = 1) -> int:
    # total slots must cover topk dispatches per token (matches GPTMoEMLP's
    # b*s*topk/E and the reference's per-expert ceil(cap_rate*S) semantics);
    # without the topk multiplier, balanced top-2 routing at factor 1.2 would
    # silently drop ~40% of second-choice dispatches.
    import math

    cap = math.ceil(cap_factor * topk * num_tokens / num_experts)
    return max(cap, 4)


class GShardGate(BaseGate):
    """Top-2 gate with capacity + load-balance aux loss
    (reference: gate/gshard_gate.py). Returns static-shape
    (combine_weights [S,E,C], dispatch_mask [S,E,C]) per GShard."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 2, capacity=(1.2, 2.4), random_routing: bool = True,
                 group=None):
        super().__init__(num_expert, world_size)
        assert topk == 2, "GShardGate is a top-2 gate"
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.capacity_factor = capacity
        self.random_routing = random_routing
        self.group = group

    def forward(self, inp, training: bool = True):
        logits = self.gate(inp)
        E = self.tot_expert
        cap_f = self.capacity_factor[0] if training else self.capacity_factor[1]
        rand_route = self.random_routing and training
        key = _random.next_key() if rand_route else None

        def route(lg):
            S = lg.shape[0]
            C = _capacity(S, E, cap_f, topk=2)
            gates = jax.nn.softmax(lg, axis=-1)
            # top-1
            idx1 = jnp.argmax(gates, axis=-1)
            mask1 = _one_hot(idx1, E)
            g1 = jnp.sum(gates * mask1, axis=-1)
            # top-2 on remaining
            gates_wo1 = gates * (1.0 - mask1)
            idx2 = jnp.argmax(gates_wo1, axis=-1)
            mask2 = _one_hot(idx2, E)
            g2 = jnp.sum(gates_wo1 * mask2, axis=-1)

            if rand_route:
                # reference gshard_gate.py _random_routing: keep the second
                # expert with probability min(1, 2*g2) — tokens whose
                # second-choice weight is small skip the extra dispatch
                keep = jax.random.uniform(key, (S,)) < 2.0 * g2
                mask2 = mask2 * keep[:, None].astype(mask2.dtype)

            aux = _load_balance_loss(gates, mask1)

            # positions within each expert via cumsum over tokens
            pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1
            pos2 = (jnp.cumsum(mask2, axis=0) - 1.0 +
                    jnp.sum(mask1, axis=0, keepdims=True)) * mask2
            # capacity drop
            mask1 = mask1 * (pos1 < C)
            mask2 = mask2 * (pos2 < C)
            p1 = jnp.sum(pos1, axis=-1).astype(jnp.int32)
            p2 = jnp.sum(pos2, axis=-1).astype(jnp.int32)

            keep1 = jnp.sum(mask1, axis=-1)
            keep2 = jnp.sum(mask2, axis=-1)
            g1 = g1 * keep1
            g2 = g2 * keep2
            denom = g1 + g2
            denom = jnp.where(denom > 0, denom, 1.0)
            g1, g2 = g1 / denom, g2 / denom

            cw = (g1[:, None, None] * mask1[:, :, None] * _one_hot(p1, C)[:, None, :]
                  + g2[:, None, None] * mask2[:, :, None] * _one_hot(p2, C)[:, None, :])
            dm = (cw > 0).astype(lg.dtype)
            return cw.astype(lg.dtype), dm, aux.astype(lg.dtype)

        cw, dm, aux = run_op(route, [logits], name="gshard_gate")
        self.set_loss(aux)
        return cw, dm


class SwitchGate(BaseGate):
    """Top-1 switch gate with jitter + capacity + switch aux loss
    (reference: gate/switch_gate.py)."""

    def __init__(self, d_model: int, num_expert: int, world_size: int = 1,
                 topk: int = 1, switch_eps: float = 0.1, capacity=(1.2, 2.4),
                 group=None):
        super().__init__(num_expert, world_size)
        assert topk == 1, "SwitchGate is a top-1 gate"
        self.gate = nn.Linear(d_model, self.tot_expert)
        self.switch_eps = switch_eps
        self.capacity_factor = capacity
        self.group = group

    def forward(self, inp, training: bool = True):
        logits = self.gate(inp)
        E = self.tot_expert
        cap_f = self.capacity_factor[0] if training else self.capacity_factor[1]
        eps = self.switch_eps if training else 0.0
        key = _random.next_key() if eps else None

        def route(lg):
            S = lg.shape[0]
            C = _capacity(S, E, cap_f)
            if eps:
                noise = jax.random.uniform(
                    key, lg.shape, lg.dtype, 1.0 - eps, 1.0 + eps)
                lg = lg * noise
            gates = jax.nn.softmax(lg, axis=-1)
            idx1 = jnp.argmax(gates, axis=-1)
            mask1 = _one_hot(idx1, E)
            g1 = jnp.sum(gates * mask1, axis=-1)

            aux = _load_balance_loss(gates, mask1)

            pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1
            mask1 = mask1 * (pos1 < C)
            p1 = jnp.sum(pos1, axis=-1).astype(jnp.int32)
            g1 = g1 * jnp.sum(mask1, axis=-1)

            cw = g1[:, None, None] * mask1[:, :, None] * _one_hot(p1, C)[:, None, :]
            dm = (cw > 0).astype(lg.dtype)
            return cw.astype(lg.dtype), dm, aux.astype(lg.dtype)

        cw, dm, aux = run_op(route, [logits], name="switch_gate")
        self.set_loss(aux)
        return cw, dm
