"""Incubate optimizer wrappers: LookAhead, ModelAverage (reference:
python/paddle/incubate/optimizer/{lookahead,modelaverage}.py).

Both wrap an inner optimizer and keep per-parameter shadow state as raw
jax arrays (device-resident, no tape)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.autograd import no_grad
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """k steps forward, 1 step back (Zhang et al. 2019). Every ``k`` inner
    steps: slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner_optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        params = inner_optimizer._parameter_list
        super().__init__(learning_rate=alpha, parameters=params)
        self._slow = {}

    @no_grad()
    def step(self):
        self.inner_optimizer.step()
        self._global_step += 1
        if self._global_step % self.k:
            return
        for p in self._parameter_list:
            pid = id(p)
            if pid not in self._slow:
                self._slow[pid] = p._data
            slow = self._slow[pid] + self.alpha * (p._data
                                                   - self._slow[pid])
            self._slow[pid] = slow
            p._data = slow

    def clear_grad(self, set_to_zero=False):
        self.inner_optimizer.clear_grad(set_to_zero)

    def state_dict(self):
        sd = self.inner_optimizer.state_dict()
        sd["lookahead_step"] = self._global_step
        return sd

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()


class ModelAverage(Optimizer):
    """Running average of parameters for eval (Polyak averaging with a
    windowed restart schedule, reference modelaverage.py). ``apply()``
    swaps averaged weights in (optionally restoring on exit)."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        super().__init__(learning_rate=0.0, parameters=parameters)
        self.avg_rate = float(average_window_rate)
        self.min_avg_window = int(min_average_window)
        self.max_avg_window = int(max_average_window)
        self._sum = {}
        self._num_updates = 0
        self._backup = None

    @no_grad()
    def step(self):
        self._num_updates += 1
        window = max(self.min_avg_window,
                     min(self.max_avg_window,
                         int(self._num_updates * self.avg_rate)))
        for p in self._parameter_list:
            pid = id(p)
            if pid not in self._sum:
                self._sum[pid] = (p._data, 1)
                continue
            acc, n = self._sum[pid]
            if n >= window:
                # restart the window keeping the current average
                acc = acc / n
                n = 1
            self._sum[pid] = (acc + p._data, n + 1)

    def _averaged(self, p):
        acc, n = self._sum.get(id(p), (p._data, 1))
        return acc / n

    @no_grad()
    def apply(self, executor=None, need_restore=True):
        """Swap averaged parameters in. Usable as a context manager when
        need_restore=True (the reference's with-apply pattern)."""
        self._backup = {id(p): p._data for p in self._parameter_list}
        for p in self._parameter_list:
            p._data = self._averaged(p)
        mgr = self

        class _Ctx:
            def __enter__(self):
                return mgr

            def __exit__(self, *exc):
                if need_restore:
                    mgr.restore()
                return False

        return _Ctx()

    @no_grad()
    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._parameter_list:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
        self._backup = None

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
