"""paddle.incubate.jit.inference decorator (reference:
python/paddle/incubate/jit/inference_decorator.py — converts a Layer /
function into a cached optimized predictor).

TPU-native: the "predictor" is a jit-compiled, no-grad forward with a
shape/dtype-keyed compile cache — XLA plays the role of the Paddle
Inference pass pipeline."""
from __future__ import annotations

from typing import Callable

__all__ = ["inference"]


def inference(function=None, cache_static_model=False, save_model_dir=None,
              memory_pool_init_size_mb=1000, precision_mode="float32",
              switch_ir_optim=True, switch_ir_debug=False,
              enable_cinn=False, with_trt=False, trt_precision_mode=None,
              trt_use_static=False, collect_shape=False,
              skip_prune_program=False):
    """Decorator: compile ``function`` (or a Layer's forward) for
    inference. Extra knobs are accepted for reference-script compatibility;
    on TPU they map to the single XLA pipeline."""
    from ..jit import to_static
    from ..core.autograd import no_grad
    from ..nn.layer.layers import Layer

    def wrap(fn):
        if isinstance(fn, Layer):
            layer = fn
            compiled = to_static(layer.forward)

            def innermost_decorator(*args, **kwargs):
                with no_grad():
                    return compiled(*args, **kwargs)

            layer.forward = innermost_decorator
            return layer
        compiled = to_static(fn)

        def innermost_decorator(*args, **kwargs):
            with no_grad():
                return compiled(*args, **kwargs)

        innermost_decorator.__name__ = getattr(fn, "__name__",
                                               "inference_fn")
        return innermost_decorator

    if function is not None:
        return wrap(function)
    return wrap
