"""ASP: automatic structured (n:m) sparsity
(reference: python/paddle/incubate/asp/ — asp.py decorate/prune_model,
utils.py calculate_density/create_mask/check_sparsity, supported_layer_list).

TPU-first: the n:m masks are plain multiplicative tensors; the decorated
optimizer re-applies them after each step, so masked weights stay zero
through training. XLA folds the mask multiply into adjacent ops; on
hardware with sparsity support the mask layout is the standard 2:4 pattern.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ... import nn
from ...core.tensor import Tensor

__all__ = ["calculate_density", "create_mask", "check_sparsity",
           "prune_model", "decorate", "set_excluded_layers",
           "reset_excluded_layers"]

_masks: Dict[int, jnp.ndarray] = {}       # id(param) -> mask array
_excluded: set = set()                    # excluded layer names


def calculate_density(x) -> float:
    """Fraction of non-zeros (reference: utils.py calculate_density)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    return float(np.count_nonzero(arr)) / max(arr.size, 1)


def _mask_1d(arr: np.ndarray, n: int, m: int) -> np.ndarray:
    """Keep the n largest-|w| in every m consecutive weights along the
    last axis (reference: utils.py get_mask_1d). Rows are padded to a
    multiple of m (as the reference pads the second dimension) so m-blocks
    never span row boundaries; the pad is cropped from the result."""
    shape = arr.shape
    last = shape[-1] if arr.ndim else arr.size
    rows2d = arr.reshape(-1, last)
    pad = (-last) % m
    if pad:
        rows2d = np.concatenate(
            [rows2d, np.zeros((rows2d.shape[0], pad), rows2d.dtype)], axis=1)
    flat = rows2d.reshape(-1, m)
    order = np.argsort(-np.abs(flat), axis=1)
    mask = np.zeros_like(flat)
    rows = np.arange(flat.shape[0])[:, None]
    mask[rows, order[:, :n]] = 1.0
    mask = mask.reshape(rows2d.shape)
    if pad:
        mask = mask[:, :last]
    return mask.reshape(shape)


def _mask_2d_greedy(arr: np.ndarray, n: int, m: int) -> np.ndarray:
    """Greedy m×m block mask with n:m on rows AND columns (reference:
    utils.py get_mask_2d_greedy). Falls back to 1d when shapes don't tile."""
    h, w = arr.shape
    if h % m or w % m:
        return _mask_1d(arr, n, m)
    mask = np.zeros_like(arr)
    for bi in range(0, h, m):
        for bj in range(0, w, m):
            block = np.abs(arr[bi:bi + m, bj:bj + m])
            bmask = np.zeros((m, m))
            order = np.argsort(-block, axis=None)
            row_cnt = np.zeros(m, dtype=int)
            col_cnt = np.zeros(m, dtype=int)
            for flat_idx in order:
                r, c = divmod(int(flat_idx), m)
                if row_cnt[r] < n and col_cnt[c] < n:
                    bmask[r, c] = 1.0
                    row_cnt[r] += 1
                    col_cnt[c] += 1
            mask[bi:bi + m, bj:bj + m] = bmask
    return mask


_MASK_ALGOS = {"mask_1d": _mask_1d, "mask_2d_greedy": _mask_2d_greedy,
               "mask_2d_best": _mask_2d_greedy}


def create_mask(tensor, func_name: str = "mask_1d", n: int = 2, m: int = 4):
    """reference: utils.py create_mask."""
    arr = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    if arr.ndim <= 1:
        return Tensor(jnp.asarray(_mask_1d(arr, n, m), dtype=jnp.float32))
    algo = _MASK_ALGOS[func_name]
    if arr.ndim != 2:
        flat = arr.reshape(arr.shape[0], -1)
        mask = _mask_1d(flat, n, m).reshape(arr.shape)
    else:
        mask = algo(arr, n, m)
    return Tensor(jnp.asarray(mask, dtype=jnp.float32))


def check_sparsity(tensor, n: int = 2, m: int = 4,
                   func_name: str = "check_mask_1d") -> bool:
    """Every m-block along the last axis has at most n non-zeros
    (reference: utils.py check_sparsity)."""
    arr = np.asarray(tensor._data if isinstance(tensor, Tensor) else tensor)
    # flatten exactly as create_mask does (ndim>2 → (shape[0], -1)) so
    # block boundaries agree with the masks this module produces
    rows2d = arr.reshape(arr.shape[0], -1) if arr.ndim >= 2 \
        else arr.reshape(1, -1)
    pad = (-rows2d.shape[1]) % m
    if pad:
        rows2d = np.concatenate(
            [rows2d, np.zeros((rows2d.shape[0], pad), rows2d.dtype)], axis=1)
    flat = (rows2d.reshape(-1, m) != 0).sum(axis=1)
    return bool((flat <= n).all())


def set_excluded_layers(param_names: List[str], main_program=None):
    _excluded.update(param_names)


def reset_excluded_layers(main_program=None):
    _excluded.clear()


def _prunable_params(model: nn.Layer):
    for name, sub in model.named_sublayers(include_self=True):
        if not isinstance(sub, nn.Linear):
            continue
        w = getattr(sub, "weight", None)
        if w is None:
            continue
        if any(ex in name or ex in (w.name or "") for ex in _excluded):
            continue
        if w.ndim == 2:
            yield name, w


def prune_model(model: nn.Layer, n: int = 2, m: int = 4,
                mask_algo: str = "mask_1d", with_mask: bool = True):
    """Apply n:m masks to every supported layer's weight (reference:
    asp.py prune_model). Returns {param_name: mask Tensor}."""
    out = {}
    for name, w in _prunable_params(model):
        mask = create_mask(w, mask_algo, n, m)
        w._data = w._data * mask._data.astype(w._data.dtype)
        if with_mask:
            _masks[id(w)] = mask._data
        out[name] = mask
    return out


class _ASPOptimizer:
    """Optimizer wrapper re-applying masks after each update (reference:
    asp.py OptimizerWithSparsityGuarantee)."""

    def __init__(self, optimizer):
        self._inner = optimizer

    def __getattr__(self, item):
        return getattr(self._inner, item)

    def step(self):
        self._inner.step()
        for p in self._inner._parameter_list or []:
            mask = _masks.get(id(p))
            if mask is not None:
                p._data = p._data * mask.astype(p._data.dtype)

    def minimize(self, loss, *a, **k):
        loss.backward()
        self.step()
        return None, None


def decorate(optimizer):
    """reference: asp.py decorate."""
    return _ASPOptimizer(optimizer)
