"""paddle_tpu.incubate (reference: python/paddle/incubate/)."""
from . import nn  # noqa: F401
from .ops import (graph_khop_sampler, graph_reindex,  # noqa: F401
                  graph_sample_neighbors, graph_send_recv, identity_loss,
                  segment_max, segment_mean, segment_min, segment_sum,
                  softmax_mask_fuse, softmax_mask_fuse_upper_triangle)
from .optimizer import LookAhead, ModelAverage  # noqa: F401
from .inference import inference  # noqa: F401

__all__ = ["LookAhead", "ModelAverage", "graph_khop_sampler",
           "graph_reindex", "graph_sample_neighbors", "graph_send_recv",
           "identity_loss", "segment_max", "segment_mean", "segment_min",
           "segment_sum", "softmax_mask_fuse",
           "softmax_mask_fuse_upper_triangle", "inference"]

_LAZY = ("distributed", "asp")


def __getattr__(name):
    # lazy: incubate.nn is imported during paddle_tpu.nn's own init, so
    # eagerly importing incubate.distributed here would cycle back into nn
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)
