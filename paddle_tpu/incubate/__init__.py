"""paddle_tpu.incubate (reference: python/paddle/incubate/)."""
from . import nn  # noqa: F401

_LAZY = ("distributed", "asp")


def __getattr__(name):
    # lazy: incubate.nn is imported during paddle_tpu.nn's own init, so
    # eagerly importing incubate.distributed here would cycle back into nn
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(name)
