"""Custom-op extension point (VERDICT r1 missing #8; reference:
paddle/fluid/framework/custom_operator.cc PD_BUILD_OP + the device-plugin
C API phi/backends/device_ext.h:95).

TPU-native: a custom op is a pure jax function — jnp code or a hand-
written Pallas kernel — registered once and mounted on ``paddle_tpu.ops``
(and optionally as a Tensor method). It records on the eager tape, traces
under jit/TrainStep, and differentiates either through ``jax.vjp``
(default) or a user-supplied backward, exactly the PD_BUILD_OP
forward/backward pairing.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

__all__ = ["register_op", "deregister_op", "registered_ops"]

_registry = {}
_shadowed = {}  # name -> {"pt"/"ops"/"tensor": original attr} for restore


def registered_ops():
    """Names of currently registered custom ops (used by the op-coverage
    gate to exclude runtime-registered ops from the static sweep)."""
    return set(_registry)


def deregister_op(name: str) -> None:
    """Remove a custom op registered with :func:`register_op` — unmounts it
    from ``paddle_tpu``, ``paddle_tpu.ops`` and ``Tensor``, restoring any
    builtin the registration shadowed. Tests register throwaway ops and must
    clean up so suite-wide sweeps stay deterministic."""
    if name not in _registry:
        raise KeyError(f"custom op '{name}' is not registered")
    shadowed = _shadowed.pop(name, {})
    del _registry[name]

    from ..core.tensor import Tensor
    import paddle_tpu as _pt
    from .. import ops as _ops

    for key, host in (("pt", _pt), ("ops", _ops)):
        if key in shadowed:
            setattr(host, name, shadowed[key])
        else:
            try:
                delattr(host, name)
            except AttributeError:
                pass
    if shadowed.get("appended_all") and name in _ops.__all__:
        _ops.__all__.remove(name)
    if shadowed.get("set_tensor_method"):
        if "tensor" in shadowed:
            setattr(Tensor, name, shadowed["tensor"])
        elif name in getattr(Tensor, "__dict__", {}):
            delattr(Tensor, name)


def register_op(name: str, fn: Optional[Callable] = None, *,
                backward: Optional[Callable] = None,
                num_outputs: int = 1,
                tensor_method: bool = False):
    """Register ``fn(*arrays, **attrs) -> array(s)`` as ``paddle.ops.<name>``.

    - ``fn`` operates on raw jax arrays (jnp / lax / pallas_call).
    - ``backward(res, *cotangents) -> input-grads`` optional: when given,
      the op gets a ``jax.custom_vjp`` with ``res = (inputs, outputs)``;
      otherwise jax.vjp differentiates ``fn`` directly.
    - ``tensor_method=True`` additionally mounts it as ``Tensor.<name>``.

    Usable as a decorator::

        @register_op("fancy_relu")
        def fancy_relu(x):
            return jnp.maximum(x, 0) * 1.5
    """
    if fn is None:
        return lambda f: register_op(name, f, backward=backward,
                                     num_outputs=num_outputs,
                                     tensor_method=tensor_method)

    import jax

    from ..core.tensor import Tensor
    from ..ops._helpers import as_tensor, run_op

    if name in _registry:
        raise ValueError(f"custom op '{name}' is already registered")

    inner = fn
    if backward is not None:
        @jax.custom_vjp
        def inner(*arrays, **attrs):
            return fn(*arrays, **attrs)

        def _fwd(*arrays, **attrs):
            out = fn(*arrays, **attrs)
            return out, (arrays, out)

        def _bwd(res, cot):
            grads = backward(res, cot)
            return tuple(grads) if isinstance(grads, (list, tuple)) \
                else (grads,)

        inner.defvjp(_fwd, _bwd)

    def op(*inputs, **attrs):
        tensors = [as_tensor(t) if isinstance(t, Tensor) or _is_arrayish(t)
                   else t for t in inputs]
        tensor_args = [t for t in tensors if isinstance(t, Tensor)]
        other = [(i, t) for i, t in enumerate(tensors)
                 if not isinstance(t, Tensor)]

        def call(*arrays):
            full = list(arrays)
            for i, t in other:
                full.insert(i, t)
            return inner(*full, **attrs)

        return run_op(call, tensor_args, name=name)

    op.__name__ = name
    op.__doc__ = fn.__doc__ or f"custom op '{name}'"
    _registry[name] = op

    from .. import ops as _ops

    # remember exactly what we touch so deregister_op can undo it: any
    # shadowed attrs, whether we appended to ops.__all__, and whether we
    # mounted a Tensor method at all
    shadowed = {"set_tensor_method": tensor_method,
                "appended_all": name not in _ops.__all__}
    import paddle_tpu as _pt

    if hasattr(_ops, name):
        shadowed["ops"] = getattr(_ops, name)
    if hasattr(_pt, name):
        shadowed["pt"] = getattr(_pt, name)
    setattr(_ops, name, op)
    if shadowed["appended_all"]:
        _ops.__all__.append(name)

    setattr(_pt, name, op)
    if tensor_method:
        if name in Tensor.__dict__:
            shadowed["tensor"] = Tensor.__dict__[name]

        def method(self, *a, **kw):
            return op(self, *a, **kw)

        method.__name__ = name
        setattr(Tensor, name, method)
    _shadowed[name] = shadowed
    return op


def _is_arrayish(x):
    import numpy as np

    import jax

    return isinstance(x, (np.ndarray, jax.Array, jax.core.Tracer))
