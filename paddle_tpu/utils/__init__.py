"""paddle.utils (reference: python/paddle/utils/ — cpp_extension custom-op
loading, deprecated-decorator, install checks)."""
from __future__ import annotations

from . import cpp_extension  # noqa: F401
from .custom_op import deregister_op, register_op, registered_ops  # noqa: F401

__all__ = ["register_op", "deregister_op", "registered_ops", "cpp_extension",
           "run_check"]


def run_check():
    """reference: paddle.utils.run_check — sanity-check the install."""
    import jax
    import numpy as np

    import paddle_tpu as pt

    x = pt.to_tensor(np.ones((2, 2), np.float32))
    y = (x @ x).sum()
    assert float(y.numpy()) == 8.0
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed successfully! device: {dev}")


def deprecated(update_to="", since="", reason="", level=0):
    """reference: python/paddle/utils/deprecated.py decorator."""
    import functools
    import warnings

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = f"API {fn.__name__} is deprecated since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f": {reason}"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)

        return wrapper

    return deco


def try_import(module_name, err_msg=None):
    """reference: python/paddle/utils/lazy_import.py try_import."""
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(
            err_msg or f"{module_name} is required but not installed")


def require_version(min_version, max_version=None):
    """reference: python/paddle/utils/__init__.py require_version —
    checks the installed framework version."""
    from ..version import full_version

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3]
                     if x.isdigit())

    cur = parse(full_version)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {full_version} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {full_version} > allowed {max_version}")
    return True


__all__ += ["deprecated", "try_import", "require_version"]
