"""paddle.utils (reference: python/paddle/utils/ — cpp_extension custom-op
loading, deprecated-decorator, install checks)."""
from __future__ import annotations

from . import cpp_extension  # noqa: F401
from .custom_op import deregister_op, register_op, registered_ops  # noqa: F401

__all__ = ["register_op", "deregister_op", "registered_ops", "cpp_extension",
           "run_check"]


def run_check():
    """reference: paddle.utils.run_check — sanity-check the install."""
    import jax
    import numpy as np

    import paddle_tpu as pt

    x = pt.to_tensor(np.ones((2, 2), np.float32))
    y = (x @ x).sum()
    assert float(y.numpy()) == 8.0
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed successfully! device: {dev}")
