"""paddle.utils.cpp_extension surface (reference:
python/paddle/utils/cpp_extension/ — setup/load/CppExtension/
CUDAExtension building custom C++/CUDA operators).

TPU-native guidance: CUDA sources cannot target TPUs. Out-of-tree ops are
registered as jax/Pallas functions via
:func:`paddle_tpu.utils.register_op` (same capability as PD_BUILD_OP:
custom forward + custom backward, eager + jit + grad); host-side native
code plugs in through the ctypes tier (paddle_tpu/core/native.py, see
native/ for the in-tree examples).
"""
from __future__ import annotations

__all__ = ["load", "setup", "CppExtension", "CUDAExtension"]

_MSG = (
    "is not supported on the TPU backend: CUDA/C++ kernel sources cannot "
    "target TPUs. Register custom ops as jax/Pallas functions with "
    "paddle_tpu.utils.register_op(name, fn, backward=...) — they run "
    "eager, under jit, and differentiate; for host-side native code use "
    "the ctypes tier (paddle_tpu/core/native.py)."
)


class _Unsupported(NotImplementedError):
    def __init__(self, what):
        super().__init__(f"{what} {_MSG}")


def load(name, sources, *a, **kw):
    raise _Unsupported("cpp_extension.load")


def setup(**kw):
    raise _Unsupported("cpp_extension.setup")


def CppExtension(sources, *a, **kw):
    raise _Unsupported("CppExtension")


def CUDAExtension(sources, *a, **kw):
    raise _Unsupported("CUDAExtension")
