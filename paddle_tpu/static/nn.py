"""Static-graph control flow (reference: python/paddle/static/nn/
control_flow.py — cond, while_loop, case, switch_case).

TPU-native: these lower to ``lax.cond`` / ``lax.while_loop`` so
data-dependent control flow stays INSIDE the compiled program (the jit
analog of the reference's conditional_block / while ops). Under eager they
still work — lax primitives execute immediately on concrete arrays.
Differentiable through the tape via ``run_op`` (jax.vjp supplies the
cond/scan transpose rules).
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops._helpers import as_tensor, run_op, unwrap

__all__ = ["cond", "while_loop", "case", "switch_case"]


def _tensorize(xs):
    return [x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
            for x in xs]


def _is_tracing(*tensors) -> bool:
    import jax.core as jcore

    return any(isinstance(unwrap(as_tensor(t)), jcore.Tracer)
               for t in tensors if t is not None)


def cond(pred, true_fn: Callable, false_fn: Callable, operands=None,
         name=None):
    """reference: static/nn/control_flow.py cond. Both branches must
    return structures of matching shapes/dtypes (lax.cond contract —
    same as the reference's requirement that both branches produce
    matching out vars).

    Eager (dygraph) semantics match the reference: the predicate is
    concrete, so the chosen branch simply executes on the tape
    (differentiable through the taken branch). Under tracing (to_static /
    TrainStep) it lowers to ``lax.cond`` so the branch stays inside the
    compiled program."""
    operands = _tensorize(operands or [])
    p = as_tensor(pred)
    if not _is_tracing(p, *operands):
        taken = true_fn if bool(unwrap(p).reshape(())) else false_fn
        return taken(*operands) if operands else taken()

    def fn(pa, *ops):
        def wrap(branch):
            def inner(arrs):
                outs = branch(*[Tensor(a) for a in arrs]) if arrs else \
                    branch()
                leaves, treedef = jax.tree_util.tree_flatten(
                    outs, is_leaf=lambda x: isinstance(x, Tensor))
                fn._treedef = treedef
                return tuple(o._data if isinstance(o, Tensor)
                             else jnp.asarray(o) for o in leaves)
            return inner

        flag = jnp.reshape(pa.astype(jnp.bool_), ())
        return jax.lax.cond(flag, wrap(true_fn), wrap(false_fn),
                            tuple(ops))

    outs = run_op(fn, [p] + operands, name="cond")
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    treedef = getattr(fn, "_treedef", None)
    if treedef is not None:
        return jax.tree_util.tree_unflatten(treedef, list(outs))
    return outs[0] if len(outs) == 1 else outs


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test=False, name=None):
    """reference: static/nn/control_flow.py while_loop. Shapes must be
    loop-invariant (lax.while_loop contract; the reference requires the
    same of its while op's block outputs).

    NOT reverse-differentiable under tracing: lax.while_loop has no
    transpose rule, so traced outputs are detached (eager python-loop mode
    stays fully on the tape). Use ``cond``/``lax.scan``-style ops when the
    loop must carry gradients through a compiled program."""
    loop_vars = _tensorize(list(loop_vars))
    if not _is_tracing(*loop_vars):
        # dygraph semantics (reference: while_loop under dynamic mode is a
        # plain python loop — fully on the eager tape)
        vals = list(loop_vars)
        while bool(unwrap(as_tensor(cond_fn(*vals))).reshape(())):
            outs = body_fn(*vals)
            vals = list(outs) if isinstance(outs, (list, tuple)) else [outs]
            vals = _tensorize(vals)
        return vals

    def fn(*arrs):
        def c(vals):
            out = cond_fn(*[Tensor(v) for v in vals])
            return jnp.reshape(unwrap(as_tensor(out)).astype(jnp.bool_), ())

        def b(vals):
            outs = body_fn(*[Tensor(v) for v in vals])
            if not isinstance(outs, (list, tuple)):
                outs = (outs,)
            return tuple(unwrap(as_tensor(o)) for o in outs)

        return jax.lax.while_loop(c, b, tuple(arrs))

    # detach: no vjp is recorded (while_loop is not reverse-differentiable)
    detached = []
    for t in loop_vars:
        d = Tensor(t._data)
        d.stop_gradient = True
        detached.append(d)
    outs = run_op(fn, detached, name="while_loop")
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    return list(outs)


def case(pred_fn_pairs: List, default: Callable = None, name=None):
    """reference: control_flow.py case — first true pred wins."""
    if not pred_fn_pairs:
        raise ValueError("case needs at least one (pred, fn) pair")
    pred, fn = pred_fn_pairs[0]
    rest = pred_fn_pairs[1:]
    if not rest:
        if default is None:
            return cond(pred, fn, fn)
        return cond(pred, fn, default)
    return cond(pred, fn, lambda: case(rest, default))


def switch_case(branch_index, branch_fns, default: Callable = None,
                name=None):
    """reference: control_flow.py switch_case."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    idx = as_tensor(branch_index)
    fns = [fn for _, fn in items]
    keys = [k for k, _ in items]
    if not _is_tracing(idx):
        iv = int(unwrap(idx).reshape(()))
        for k, f in items:
            if iv == k:
                return f()
        if default is not None:
            return default()
        return fns[-1]()

    def fn(ia):
        def _run_branch(f):
            outs = f()
            leaves, treedef = jax.tree_util.tree_flatten(
                outs, is_leaf=lambda x: isinstance(x, Tensor))
            fn._treedef = treedef
            return tuple(o._data if isinstance(o, Tensor)
                         else jnp.asarray(o) for o in leaves)

        branches = [lambda _, f=f: _run_branch(f) for f in fns]
        if default is not None:
            branches.append(lambda _, f=default: _run_branch(f))
        # map branch_index -> position (unknown index = last branch when a
        # default exists, else clamp to the last listed branch)
        pos = jnp.full((), len(branches) - 1, jnp.int32)
        iv = jnp.reshape(ia.astype(jnp.int32), ())
        for j, k in enumerate(keys):
            pos = jnp.where(iv == k, j, pos)
        return jax.lax.switch(pos, branches, None)

    outs = run_op(fn, [idx], name="switch_case")
    if not isinstance(outs, (list, tuple)):
        outs = (outs,)
    treedef = getattr(fn, "_treedef", None)
    if treedef is not None:
        return jax.tree_util.tree_unflatten(treedef, list(outs))
    return outs[0] if len(outs) == 1 else outs
