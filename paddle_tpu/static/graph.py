"""Static-graph capture: symbolic tensors + lazy op DAG.

TPU-native analog of the reference's PIR program capture
(paddle/pir/ Program/Operation/Value + fluid/pir operator dialect,
SURVEY §2.1 "PIR"). Instead of building an MLIR-like IR and writing a
lowering, ops are recorded as a DAG of pure jax closures (each node is the
same pure fn the eager path would have executed); the Executor composes the
DAG into one python callable and hands it to jax.jit, so XLA sees the whole
program — the role the reference splits between PirInterpreter and CINN is
played entirely by XLA (SURVEY §2.4.9).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import jax
import numpy as np

from ..core import static_flags
from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor

__all__ = ["OpNode", "FeedLeaf", "make_symbolic", "record_op", "is_symbolic",
           "evaluate"]


class FeedLeaf:
    """A named graph input (static.data)."""

    def __init__(self, name: str, aval: jax.ShapeDtypeStruct):
        self.name = name
        self.aval = aval


class OpNode:
    """One recorded op: pure fn over parent values.

    parents: list of entries, each either
      - (OpNode, out_idx)      symbolic input
      - FeedLeaf               feed input
      - Tensor                 concrete tensor (parameter/buffer constant)
      - raw array/scalar       literal constant
    """

    def __init__(self, fn, parents, out_avals, name: str, single: bool,
                 attrs: Optional[dict] = None):
        self.fn = fn
        self.parents = parents
        self.out_avals = out_avals
        self.name = name
        self.single = single
        # declared attributes of this op instance (axis, epsilon, ...) —
        # consumed by attr-aware decomposition rules (decomposition/)
        self.attrs = attrs


def is_symbolic(t) -> bool:
    return isinstance(t, Tensor) and getattr(t, "_sym_node", None) is not None


def make_symbolic(aval_or_node, out_index: int = 0,
                  name: Optional[str] = None) -> Tensor:
    """Build a Tensor whose payload is a ShapeDtypeStruct (no data)."""
    t = Tensor.__new__(Tensor)
    if isinstance(aval_or_node, (OpNode, FeedLeaf)):
        node = aval_or_node
        aval = (node.aval if isinstance(node, FeedLeaf)
                else node.out_avals[out_index])
    else:
        node = None
        aval = aval_or_node
    t._data = aval  # ShapeDtypeStruct: .shape/.dtype metadata work
    t._stop_gradient = True
    t._grad = None
    t._grad_node = None
    t._out_index = out_index
    t._grad_hooks = []
    t.name = name or f"sym_{id(t)}"
    t.persistable = False
    t._dist_attr = None
    t.dist_spec = None
    t._sym_node = (node, out_index)
    return t


def record_op(fn, tensors, name: str, attrs: Optional[dict] = None):
    """Called from run_op when static capture is on and an input is
    symbolic: infer shapes with jax.eval_shape, return symbolic outputs."""
    parents: List[Any] = []
    avals_in = []
    for t in tensors:
        if is_symbolic(t):
            node, idx = t._sym_node
            parents.append((node, idx) if isinstance(node, OpNode) else node)
            avals_in.append(t._data)
        elif isinstance(t, Tensor):
            parents.append(t)
            avals_in.append(jax.ShapeDtypeStruct(tuple(t._data.shape),
                                                 t._data.dtype))
        else:
            arr = t
            parents.append(arr)
            avals_in.append(arr)
    out = jax.eval_shape(fn, *avals_in)
    single = not isinstance(out, (tuple, list))
    outs = (out,) if single else tuple(out)
    node = OpNode(fn, parents, list(outs), name, single, attrs=attrs)
    wrapped = tuple(make_symbolic(node, i) for i in range(len(outs)))
    return wrapped[0] if single else wrapped


def _collect(node, feeds: Dict[str, int], params: Dict[int, Tensor],
             seen: set):
    """DFS over the DAG collecting feed leaves + concrete tensor inputs."""
    if id(node) in seen:
        return
    seen.add(id(node))
    for p in node.parents:
        if isinstance(p, tuple):
            _collect(p[0], feeds, params, seen)
        elif isinstance(p, FeedLeaf):
            feeds.setdefault(p.name, len(feeds))
        elif isinstance(p, Tensor):
            params.setdefault(id(p), p)


def trace(fetch_nodes):
    """Return (callable, feed_names, param_tensors).

    callable(feed_values_by_name: dict, param_values: list) -> list of
    fetch values; pure, jit-friendly.
    """
    feeds: Dict[str, int] = {}
    params: Dict[int, Tensor] = {}
    seen: set = set()
    for node, _ in fetch_nodes:
        if isinstance(node, OpNode):
            _collect(node, feeds, params, seen)
        elif isinstance(node, FeedLeaf):
            feeds.setdefault(node.name, len(feeds))
    param_list = list(params.values())
    param_pos = {pid: i for i, pid in enumerate(params.keys())}

    def run(feed_values: Dict[str, Any], param_values: List[Any]):
        memo: Dict[int, Any] = {}

        def eval_node(node):
            key = id(node)
            if key in memo:
                return memo[key]
            vals = []
            for p in node.parents:
                if isinstance(p, tuple):
                    parent_out = eval_node(p[0])
                    vals.append(parent_out[p[1]] if not p[0].single
                                else parent_out)
                elif isinstance(p, FeedLeaf):
                    vals.append(feed_values[p.name])
                elif isinstance(p, Tensor):
                    vals.append(param_values[param_pos[id(p)]])
                else:
                    vals.append(p)
            out = node.fn(*vals)
            memo[key] = out
            return out

        results = []
        for node, idx in fetch_nodes:
            if isinstance(node, FeedLeaf):
                results.append(feed_values[node.name])
                continue
            out = eval_node(node)
            results.append(out if node.single else out[idx])
        return results

    return run, list(feeds.keys()), param_list


def evaluate(fetch_tensors, feed: Dict[str, Any]):
    """Eagerly evaluate symbolic fetches (used by Executor; jitted there)."""
    fetch_nodes = []
    for t in fetch_tensors:
        if not is_symbolic(t):
            fetch_nodes.append(None)
        else:
            fetch_nodes.append(t._sym_node)
    syms = [fn for fn in fetch_nodes if fn is not None]
    run, feed_names, param_list = trace(syms)
    feed_arr = {k: np.asarray(v) for k, v in feed.items()}
    vals = run(feed_arr, [p._data for p in param_list])
    out = []
    i = 0
    for t, fn in zip(fetch_tensors, fetch_nodes):
        if fn is None:
            out.append(t._data)
        else:
            out.append(vals[i])
            i += 1
    return out
