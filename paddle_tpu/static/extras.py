"""Static-API completions (reference: python/paddle/static/__init__.py
exports: Variable/scopes/places, append_backward/gradients,
program serialization + state, EMA, py_func, metrics, device/name scopes,
BuildStrategy, WeightNormParamAttr, IPU stubs).

TPU-native: gradients/append_backward build a symbolic grad OpNode that
jax.grad's the traced sub-program — the whole captured DAG stays one XLA
program, exactly how the Executor already compiles fetches.
"""
from __future__ import annotations

import contextlib
import io as _io
import pickle
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from . import graph as _g

__all__ = [
    "Variable", "BuildStrategy", "ExponentialMovingAverage", "Print",
    "WeightNormParamAttr", "accuracy", "auc", "append_backward",
    "gradients", "create_global_var", "create_parameter", "cpu_places",
    "cuda_places", "xpu_places", "device_guard", "global_scope",
    "scope_guard", "name_scope", "py_func", "save", "load", "save_to_file",
    "load_from_file", "serialize_program", "deserialize_program",
    "serialize_persistables", "deserialize_persistables",
    "normalize_program", "load_program_state", "set_program_state",
    "ctr_metric_bundle", "IpuStrategy", "IpuCompiledProgram",
    "ipu_shard_guard", "set_ipu_shard",
]

# The reference's static.Variable is the graph var handle; here symbolic
# Tensors play that role (static/graph.py make_symbolic).
Variable = Tensor


class BuildStrategy:
    """reference: paddle.static.BuildStrategy. The knobs configure the
    legacy ParallelExecutor pass pipeline; on XLA every one of these
    (fusion, memory optimize, reduce strategy) is the compiler's job, so
    they are accepted and recorded for introspection only."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.debug_graphviz_path = ""
        self.enable_sequential_execution = False
        self.fuse_broadcast_ops = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_relu_depthwise_conv = False
        self.memory_optimize = None
        self.sync_batch_norm = False
        self.enable_inplace = True
        self.build_cinn_pass = False


# ----------------------------------------------------------------- scopes
class Scope:
    """Name -> value store (reference: paddle/fluid/framework/scope.h via
    global_scope()); the Executor keeps parameters on Tensors, so this
    holds fetched/assigned host values for reference-style workflows."""

    def __init__(self):
        self._vars: Dict[str, object] = {}

    def var(self, name):
        self._vars.setdefault(name, _ScopeVar(name, self))
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)

    def drop_kids(self):
        pass


class _ScopeVar:
    def __init__(self, name, scope):
        self._name = name
        self._scope = scope
        self._value = None

    def get_tensor(self):
        return self._value

    def set(self, value, place=None):
        self._value = np.asarray(value)


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    global _global_scope
    prev = _global_scope
    _global_scope = scope
    try:
        yield
    finally:
        _global_scope = prev


_name_stack: List[str] = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """reference: paddle.static.name_scope — names ops for debugging; the
    recorded OpNode names pick up the active prefix."""
    _name_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_stack.pop()


# ----------------------------------------------------------------- places
def cpu_places(device_count=None):
    n = device_count or len(jax.devices("cpu"))
    return [f"cpu:{i}" for i in range(n)]


def cuda_places(device_ids=None):
    # no CUDA on this build; expose accelerator devices the same way
    try:
        devs = jax.devices("tpu")
    except RuntimeError:
        devs = []
    ids = device_ids if device_ids is not None else range(len(devs))
    return [f"tpu:{i}" for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


@contextlib.contextmanager
def device_guard(device=None):
    """reference: paddle.static.device_guard — pins following ops to a
    device; maps to jax.default_device for host-pinned sections."""
    if device and device.split(":")[0] == "cpu":
        with jax.default_device(jax.devices("cpu")[0]):
            yield
    else:
        yield


# ------------------------------------------------------------- var helpers
def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: python/paddle/tensor/creation.py create_global_var."""
    from ..core.dtype import to_jax_dtype

    t = Tensor(jnp.full(tuple(shape), value, dtype=to_jax_dtype(dtype)))
    t.persistable = persistable
    if name:
        t.name = name
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference: python/paddle/tensor/creation.py create_parameter."""
    from ..nn.layer.layers import Layer

    helper = Layer()
    p = helper.create_parameter(list(shape), attr=attr, dtype=dtype,
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    if name:
        p.name = name
    return p


# ------------------------------------------------------- autodiff surface
def _collect_feed_leaves(nodes):
    leaves, params, seen = [], [], set()

    def walk(node):
        if id(node) in seen or not isinstance(node, _g.OpNode):
            return
        seen.add(id(node))
        for p in node.parents:
            if isinstance(p, tuple):
                walk(p[0])
            elif isinstance(p, _g.FeedLeaf):
                if p not in leaves:
                    leaves.append(p)
            elif isinstance(p, Tensor):
                if not any(q is p for q in params):
                    params.append(p)

    for node, _ in nodes:
        if isinstance(node, _g.OpNode):
            walk(node)
        elif isinstance(node, _g.FeedLeaf) and node not in leaves:
            leaves.append(node)
    return leaves, params


def gradients(outputs, inputs, target_gradients=None, no_grad_set=None):
    """Symbolic grads of outputs wrt inputs (reference:
    python/paddle/base/backward.py gradients). Returns symbolic Tensors
    that the Executor compiles as part of the one XLA program."""
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    out_nodes = [t._sym_node for t in outputs]
    leaves, params = _collect_feed_leaves(out_nodes)
    run, feed_names, param_list = _g.trace(out_nodes)

    # classify each requested input: feed leaf or parameter tensor
    specs = []
    for t in inputs:
        if _g.is_symbolic(t) and isinstance(t._sym_node[0], _g.FeedLeaf):
            specs.append(("feed", t._sym_node[0].name))
        elif isinstance(t, Tensor) and not _g.is_symbolic(t):
            pos = next((i for i, p in enumerate(param_list) if p is t),
                       None)
            if pos is None:
                raise ValueError(
                    "gradients(): input Tensor does not appear in the "
                    "program producing the outputs")
            specs.append(("param", pos))
        else:
            raise ValueError(
                "gradients() inputs must be feed vars (static.data) or "
                "parameters used by the outputs")

    # one grad OpNode: parents = feed leaves + params, fn = jax.grad
    parents = list(leaves) + list(param_list)
    n_feeds = len(leaves)

    def grad_fn(*vals):
        feed_vals = {lf.name: v for lf, v in zip(leaves, vals[:n_feeds])}
        param_vals = list(vals[n_feeds:])

        def scalar_loss(wrt):
            fv = dict(feed_vals)
            pv = list(param_vals)
            for spec, w in zip(specs, wrt):
                if spec[0] == "feed":
                    fv[spec[1]] = w
                else:
                    pv[spec[1]] = w
            outs = run(fv, pv)
            total = 0.0
            for i, o in enumerate(outs):
                if target_gradients is not None \
                        and target_gradients[i] is not None:
                    tg = target_gradients[i]
                    tg = tg._data if isinstance(tg, Tensor) else tg
                    total = total + jnp.sum(o * tg)
                else:
                    total = total + jnp.sum(o)
            return total

        wrt0 = tuple(
            feed_vals[s[1]] if s[0] == "feed" else param_vals[s[1]]
            for s in specs)
        return jax.grad(scalar_loss)(wrt0)

    avals_in = []
    for p in parents:
        if isinstance(p, _g.FeedLeaf):
            avals_in.append(p.aval)
        else:
            avals_in.append(jax.ShapeDtypeStruct(tuple(p._data.shape),
                                                 p._data.dtype))
    out_avals = jax.eval_shape(grad_fn, *avals_in)
    node = _g.OpNode(grad_fn, parents, list(out_avals), "gradients",
                     single=False)
    return [_g.make_symbolic(node, i) for i in range(len(specs))]


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """reference: python/paddle/base/backward.py append_backward —
    returns [(param, grad)] with symbolic grad vars."""
    out_nodes = [loss._sym_node]
    _, params = _collect_feed_leaves(out_nodes)
    if parameter_list is not None:
        wanted = parameter_list
    else:
        wanted = [p for p in params
                  if getattr(p, "trainable", False)
                  and not p.stop_gradient]
    grads = gradients([loss], list(wanted))
    return list(zip(wanted, grads))


# ------------------------------------------------------------------ EMA
class ExponentialMovingAverage:
    """reference: python/paddle/static/ema.py — shadow = decay*shadow +
    (1-decay)*param, with apply()/restore() swap."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._shadow: Dict[int, object] = {}
        self._backup = None
        self._params = []
        self._step = 0

    def _track(self, parameters):
        if parameters is not None:
            self._params = list(parameters)
        elif not self._params:
            raise ValueError("ExponentialMovingAverage.update needs "
                             "parameters on the first call")

    def update(self, parameters=None):
        self._track(parameters)
        self._step += 1
        d = self._decay
        for p in self._params:
            pid = id(p)
            if pid not in self._shadow:
                self._shadow[pid] = p._data
            else:
                self._shadow[pid] = (d * self._shadow[pid]
                                     + (1.0 - d) * p._data)

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            p._data = self._shadow.get(id(p), p._data)
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        if self._backup:
            for p in self._params:
                if id(p) in self._backup:
                    p._data = self._backup[id(p)]
            self._backup = None


# ------------------------------------------------------------------ ops
def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: python/paddle/static/nn/control_flow.py Print — identity
    op that prints at execution time (jax.debug.print survives jit)."""
    from ..ops._helpers import as_tensor, run_op

    msg = message or ""

    def fn(a):
        jax.debug.print(msg + " {}", a)
        return a

    return run_op(fn, [as_tensor(input)], name="print")


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: python/paddle/static/nn/common.py py_func — run a host
    python function as an op. Forward runs through jax.pure_callback (so
    it works inside the compiled program); a custom backward_func hooks in
    via jax.custom_vjp."""
    from ..ops._helpers import as_tensor, run_op

    xs = [as_tensor(t) for t in (x if isinstance(x, (list, tuple)) else [x])]
    outs = out if isinstance(out, (list, tuple)) else [out]
    out_avals = [jax.ShapeDtypeStruct(tuple(o.shape),
                                      o._data.dtype
                                      if hasattr(o._data, "dtype")
                                      else np.float32)
                 for o in outs]
    single = not isinstance(out, (list, tuple))

    def host(*arrays):
        res = func(*[np.asarray(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r, dtype=av.dtype)
                     for r, av in zip(res, out_avals))

    inner = lambda *arrays: jax.pure_callback(
        host, tuple(out_avals), *arrays)
    if backward_func is not None:
        @jax.custom_vjp
        def inner(*arrays):
            return jax.pure_callback(host, tuple(out_avals), *arrays)

        def fwd(*arrays):
            return inner(*arrays), arrays

        def bwd(res, cots):
            grad_avals = tuple(
                jax.ShapeDtypeStruct(np.shape(a), np.asarray(a).dtype)
                if not hasattr(a, "dtype") else
                jax.ShapeDtypeStruct(a.shape, a.dtype) for a in res)

            def host_bwd(*args):
                n = len(res)
                ins, gs = args[:n], args[n:]
                out_g = backward_func(*[np.asarray(v) for v in ins],
                                      *[np.asarray(g) for g in gs])
                out_g = out_g if isinstance(out_g, (list, tuple)) \
                    else [out_g]
                return tuple(np.asarray(g, dtype=av.dtype)
                             for g, av in zip(out_g, grad_avals))

            return jax.pure_callback(host_bwd, grad_avals, *res, *cots)

        inner.defvjp(fwd, bwd)

    def fn(*arrays):
        r = inner(*arrays)
        return r[0] if single else r

    return run_op(fn, xs, name="py_func")


# ----------------------------------------------------------------- metrics
def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """reference: python/paddle/static/nn/metric.py accuracy (top-k)."""
    from ..ops._helpers import as_tensor, run_op, unwrap

    lab = unwrap(as_tensor(label))

    def fn(pred):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        l2 = lab.reshape(-1, 1)
        hit = jnp.any(topk == l2, axis=-1)
        return jnp.mean(hit.astype(jnp.float32))

    return run_op(fn, [as_tensor(input)], name="accuracy")


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """reference: python/paddle/static/nn/metric.py auc — returns
    (auc_value, batch_auc, [states]) like the reference; computed exactly
    from the positive-class scores via the rank statistic."""
    from ..ops._helpers import as_tensor, run_op, unwrap

    lab = unwrap(as_tensor(label)).reshape(-1)

    def fn(pred):
        score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
            else pred.reshape(-1)
        order = jnp.argsort(score)
        ranks = jnp.empty_like(order).at[order].set(
            jnp.arange(1, score.shape[0] + 1))
        pos = (lab > 0)
        n_pos = jnp.sum(pos)
        n_neg = score.shape[0] - n_pos
        s = jnp.sum(jnp.where(pos, ranks, 0))
        denom = jnp.maximum(n_pos * n_neg, 1)
        return ((s - n_pos * (n_pos + 1) / 2) / denom).astype(jnp.float32)

    a = run_op(fn, [as_tensor(input)], name="auc")
    return a, a, []


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """reference: python/paddle/static/nn/metric.py ctr_metric_bundle —
    (local_sqrerr, local_abserr, local_prob, local_q, local_pos_ins,
    local_total_ins)."""
    from ..ops._helpers import as_tensor, run_op, unwrap

    lab = unwrap(as_tensor(label)).reshape(-1).astype(jnp.float32)

    def fn(pred):
        p = pred.reshape(-1)
        return (jnp.sum((p - lab) ** 2), jnp.sum(jnp.abs(p - lab)),
                jnp.sum(lab), jnp.sum(p), jnp.sum(lab),
                jnp.asarray(float(p.shape[0]), jnp.float32))

    outs = run_op(fn, [as_tensor(input)], name="ctr_metric_bundle")
    return tuple(outs)


# ------------------------------------------------- program (de)serialize
def serialize_program(feed_vars=None, fetch_vars=None, program=None,
                      **kwargs):
    """Serialize the captured program structure (reference:
    static/io.py serialize_program). The payload is the pickled feed
    specs + StableHLO of the fetches when available."""
    from . import default_main_program

    prog = program or default_main_program()
    payload = {
        "feeds": {k: (tuple(t.shape), str(np.dtype(t._data.dtype)))
                  for k, t in prog._feed_leaves.items()},
        "random_seed": prog.random_seed,
    }
    return pickle.dumps(payload)


def deserialize_program(data: bytes):
    from . import Program, data as _data

    payload = pickle.loads(data)
    prog = Program()
    from . import program_guard

    with program_guard(prog):
        for name, (shape, dtype) in payload["feeds"].items():
            _data(name, list(shape), dtype)
    prog.random_seed = payload.get("random_seed", 0)
    return prog


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None,
                           **kwargs):
    """Pickle every persistable/parameter tensor reachable from the
    fetches (reference: static/io.py serialize_persistables)."""
    fetch_vars = fetch_vars or []
    nodes = [t._sym_node for t in fetch_vars if _g.is_symbolic(t)]
    _, params = _collect_feed_leaves(nodes)
    state = {getattr(p, "name", f"param_{i}"): np.asarray(p._data)
             for i, p in enumerate(params)}
    return pickle.dumps(state)


def deserialize_persistables(program, data, executor=None):
    return pickle.loads(data)


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference: static/io.py normalize_program — prunes to the feed->
    fetch subgraph; capture already records exactly that closure."""
    return program


def save_to_file(path, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def save(program, model_path, protocol=4, **configs):
    """reference: static/io.py save — program + persistables to
    model_path.[pdmodel|pdparams]."""
    save_to_file(model_path + ".pdmodel", serialize_program(
        program=program))
    state = load_program_state_obj(program)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """reference: static/io.py load."""
    try:
        with open(model_path + ".pdparams", "rb") as f:
            state = pickle.load(f)
    except FileNotFoundError:
        return
    set_program_state(program, state)


def load_program_state_obj(program):
    params = {}
    for i, (loss_t, opt) in enumerate(getattr(program, "_train_ops", [])):
        _, ps = _collect_feed_leaves([loss_t._sym_node])
        for j, p in enumerate(ps):
            params[getattr(p, "name", None) or f"p{i}_{j}"] = \
                np.asarray(p._data)
    return params


def load_program_state(model_path, var_list=None):
    """reference: static/io.py load_program_state."""
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    """reference: static/io.py set_program_state."""
    for i, (loss_t, opt) in enumerate(getattr(program, "_train_ops", [])):
        _, ps = _collect_feed_leaves([loss_t._sym_node])
        for j, p in enumerate(ps):
            key = getattr(p, "name", None) or f"p{i}_{j}"
            if key in state_dict:
                p._data = jnp.asarray(state_dict[key])


# ----------------------------------------------------------- param attrs
from ..framework.param_attr import ParamAttr as _ParamAttr


class WeightNormParamAttr(_ParamAttr):
    """reference: python/paddle/static/nn/common.py WeightNormParamAttr —
    ParamAttr requesting weight normalization (w = g * v/||v||) on the
    created parameter; layers read .dim like the reference."""

    params_with_weight_norm = []

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        super().__init__(name=name, initializer=initializer,
                         learning_rate=learning_rate,
                         trainable=trainable)
        self.dim = dim


# ------------------------------------------------------------- IPU stubs
class _IpuUnsupported(RuntimeError):
    def __init__(self, what):
        super().__init__(
            f"{what} targets GraphCore IPU hardware, which this TPU build "
            "does not drive. Use the XLA pipeline (plain "
            "Executor/CompiledProgram) — sharding is expressed with "
            "paddle.distributed (ProcessMesh / shard_tensor) instead of "
            "ipu_shard_guard.")


class IpuStrategy:
    def __init__(self):
        raise _IpuUnsupported("IpuStrategy")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise _IpuUnsupported("IpuCompiledProgram")


def ipu_shard_guard(index=-1, stage=-1):
    raise _IpuUnsupported("ipu_shard_guard")


def set_ipu_shard(call_func, index=-1, stage=-1):
    raise _IpuUnsupported("set_ipu_shard")
