"""Static-graph API: Program / Executor / data / save+load_inference_model
(reference: python/paddle/static/ — Executor at base/executor.py:1234,
program capture via PIR; SURVEY §3.4).

TPU-native design: ops recorded into a closure DAG (static/graph.py), the
Executor composes fetches into ONE pure function and jax.jit-compiles it —
XLA plays the role of the reference's PirInterpreter + CINN. Training works
through ``optimizer.minimize(loss)``: the Executor differentiates the whole
captured program with jax.grad and applies the optimizer's functional
`update` rule, donating parameter buffers — the idiomatic-XLA equivalent of
the reference's append_backward + optimizer ops.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import static_flags
from ..core.dtype import to_jax_dtype
from ..core.tensor import Tensor
from . import graph as _g

__all__ = ["Program", "default_main_program", "default_startup_program",
           "program_guard", "data", "InputSpec", "Executor",
           "CompiledProgram", "save_inference_model", "load_inference_model",
           "enable_static", "disable_static", "in_static_mode", "nn",
           "Variable", "BuildStrategy", "ExponentialMovingAverage", "Print",
           "WeightNormParamAttr", "accuracy", "auc", "append_backward",
           "gradients", "create_global_var", "create_parameter",
           "cpu_places", "cuda_places", "xpu_places", "device_guard",
           "global_scope", "scope_guard", "name_scope", "py_func", "save",
           "load", "save_to_file", "load_from_file", "serialize_program",
           "deserialize_program", "serialize_persistables",
           "deserialize_persistables", "normalize_program",
           "load_program_state", "set_program_state", "ctr_metric_bundle",
           "IpuStrategy", "IpuCompiledProgram", "ipu_shard_guard",
           "set_ipu_shard"]


class Program:
    """reference: python/paddle/base/framework.py Program (PIR program)."""

    def __init__(self):
        self.random_seed = 0
        self._feed_leaves: Dict[str, Tensor] = {}
        self._train_ops = []  # [(loss_tensor, optimizer)]
        self._fetch_cache = {}

    def global_block(self):
        return self

    def clone(self, for_test: bool = False):
        import copy

        p = Program()
        p.random_seed = self.random_seed
        p._feed_leaves = dict(self._feed_leaves)
        if not for_test:
            p._train_ops = list(self._train_ops)
        return p

    def list_vars(self):
        return list(self._feed_leaves.values())


_default_main = Program()
_default_startup = Program()


def default_main_program() -> Program:
    return _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    """reference: paddle.static.program_guard."""

    def __init__(self, main_program, startup_program=None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        global _default_main, _default_startup
        self._saved = (_default_main, _default_startup)
        _default_main = self.main
        if self.startup is not None:
            _default_startup = self.startup
        return self

    def __exit__(self, *exc):
        global _default_main, _default_startup
        _default_main, _default_startup = self._saved


def enable_static():
    static_flags.enabled = True


def disable_static(place=None):
    static_flags.enabled = False


def in_static_mode() -> bool:
    return static_flags.enabled


def data(name: str, shape, dtype="float32", lod_level=0) -> Tensor:
    """Declare a graph input (reference: paddle.static.data).

    Unlike the reference, dynamic dims (None / -1) are rejected: capture
    bakes shapes into the recorded program exactly as XLA compilation
    does. Declare the program per batch size (the Executor caches one
    compiled program per feed shape)."""
    if any(s is None or s < 0 for s in shape):
        raise ValueError(
            f"static.data({name!r}, shape={list(shape)}): dynamic dims "
            "(None/-1) are not supported on the TPU build — shapes are "
            "compiled into the XLA program. Use a concrete batch size; "
            "different sizes each get their own cached executable.")
    shape = tuple(int(s) for s in shape)
    aval = jax.ShapeDtypeStruct(shape, to_jax_dtype(dtype))
    leaf = _g.FeedLeaf(name, aval)
    t = _g.make_symbolic(leaf, 0, name=name)
    default_main_program()._feed_leaves[name] = t
    return t


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


class CompiledProgram:
    """reference: paddle.static.CompiledProgram (pass-through: jit caching
    happens inside the Executor)."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy


class Executor:
    """reference: python/paddle/base/executor.py:1234 Executor +
    _ExecutorCache:871 — run() compiles (program, fetch, feed-shapes) once
    and reuses the XLA executable."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy: bool = True, scope=None):
        if isinstance(program, CompiledProgram):
            program = program.program
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])

        if program._train_ops:
            outs = self._run_train(program, feed, fetch_list)
        else:
            outs = self._run_infer(program, feed, fetch_list)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    # ------------------------------------------------------------ infer
    def _key(self, program, feed, fetch_list, tag):
        shapes = tuple((k, tuple(np.shape(v)), str(np.asarray(v).dtype))
                       for k, v in sorted(feed.items()))
        return (id(program), tag,
                tuple(id(t._sym_node[0]) if _g.is_symbolic(t) else id(t)
                      for t in fetch_list), shapes)

    def _run_infer(self, program, feed, fetch_list):
        key = self._key(program, feed, fetch_list, "infer")
        entry = self._cache.get(key)
        if entry is None:
            sym_nodes = [t._sym_node for t in fetch_list
                         if _g.is_symbolic(t)]
            run, feed_names, param_list = _g.trace(sym_nodes)
            jitted = jax.jit(lambda feeds, params: run(feeds, params))
            entry = (jitted, feed_names, param_list)
            self._cache[key] = entry
        jitted, feed_names, param_list = entry
        feed_arrays = {k: jnp.asarray(v) for k, v in feed.items()}
        vals = jitted(feed_arrays, [p._data for p in param_list])
        out, i = [], 0
        for t in fetch_list:
            if _g.is_symbolic(t):
                out.append(vals[i])
                i += 1
            else:
                out.append(t._data)
        return out

    # ------------------------------------------------------------ train
    def _run_train(self, program, feed, fetch_list):
        # prefer the train op whose loss is being fetched (programs with
        # several minimize() calls train the op the caller is driving)
        loss_t, opt = program._train_ops[0]
        for lt, o in program._train_ops:
            if any(t is lt for t in fetch_list):
                loss_t, opt = lt, o
                break
        key = self._key(program, feed, fetch_list + [loss_t], "train")
        entry = self._cache.get(key)
        if entry is None:
            fetches = [t for t in fetch_list if _g.is_symbolic(t)]
            sym_nodes = [t._sym_node for t in [loss_t] + fetches]
            run, feed_names, param_list = _g.trace(sym_nodes)
            trainable_idx = [
                i for i, p in enumerate(param_list)
                if getattr(p, "trainable", False) and not p.stop_gradient]
            if opt._parameter_list:
                # optimizer bound to explicit params: train only those;
                # a bare optimizer (canonical static idiom) trains all
                opt_params = {id(p) for p in opt._parameter_list}
                trainable_idx = [i for i in trainable_idx
                                 if id(param_list[i]) in opt_params]

            def loss_from(feeds, params):
                return run(feeds, params)[0]

            def step(feeds, params, opt_state, lr):
                def f(train_vals):
                    full = list(params)
                    for i, v in zip(trainable_idx, train_vals):
                        full[i] = v
                    vals = run(feeds, full)
                    return jnp.sum(vals[0].astype(jnp.float32)), vals

                train_vals = [params[i] for i in trainable_idx]
                (_, vals), grads = jax.value_and_grad(f, has_aux=True)(
                    train_vals)
                new_train, new_state = opt.update(train_vals, grads,
                                                  opt_state, lr=lr)
                new_params = list(params)
                for i, v in zip(trainable_idx, new_train):
                    new_params[i] = v.astype(params[i].dtype)
                return vals, new_params, new_state

            jitted = jax.jit(step, donate_argnums=(1, 2))
            opt_state = opt.init_state(
                [p._data for p in [param_list[i] for i in trainable_idx]])
            entry = [jitted, feed_names, param_list, trainable_idx,
                     opt_state]
            self._cache[key] = entry
        jitted, feed_names, param_list, trainable_idx, opt_state = entry
        feed_arrays = {k: jnp.asarray(v) for k, v in feed.items()}
        vals, new_params, new_state = jitted(
            feed_arrays, [p._data for p in param_list], opt_state,
            jnp.asarray(opt.get_lr(), jnp.float32))
        entry[4] = new_state
        for p, v in zip(param_list, new_params):
            p._data = v
        # vals[0] is the internal loss slot; vals[1:] line up with the
        # symbolic fetches in order (including the loss if it was fetched)
        out, i = [], 1
        for t in fetch_list:
            if _g.is_symbolic(t):
                out.append(vals[i])
                i += 1
            else:
                out.append(t._data)
        return out

    def close(self):
        self._cache.clear()


def append_train_op(loss, optimizer):
    """Registered by Optimizer.minimize under static mode."""
    default_main_program()._train_ops.append((loss, optimizer))


# ------------------------------------------------------------------ io
def save_inference_model(path_prefix: str, feed_vars, fetch_vars, executor,
                         program=None):
    """reference: python/paddle/static/io.py save_inference_model.
    Serializes the traced program via jax.export (StableHLO) + params."""
    feed_vars = list(feed_vars) if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = list(fetch_vars) if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    sym_nodes = [t._sym_node for t in fetch_vars]
    run, feed_names, param_list = _g.trace(sym_nodes)
    # order feeds as given
    names = [t.name for t in feed_vars]
    param_vals = [p._data for p in param_list]

    def infer(*feed_arrays):
        feeds = dict(zip(names, feed_arrays))
        return tuple(run(feeds, param_vals))

    shapes = [jax.ShapeDtypeStruct(tuple(t.shape), t._data.dtype)
              for t in feed_vars]
    from jax import export as jexport

    exported = jexport.export(jax.jit(infer))(*shapes)
    blob = exported.serialize()
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        f.write(bytes(blob))
    meta = {"feed_names": names,
            "fetch_count": len(fetch_vars)}
    import pickle

    with open(path_prefix + ".pdiparams", "wb") as f:
        pickle.dump(meta, f)


class _LoadedProgram:
    def __init__(self, exported, feed_names, fetch_count):
        self._exported = exported
        self.feed_names = feed_names
        self.fetch_count = fetch_count

    def run(self, feed: Dict[str, np.ndarray]):
        args = [jnp.asarray(feed[n]) for n in self.feed_names]
        return list(self._exported.call(*args))


def load_inference_model(path_prefix: str, executor=None):
    """reference: python/paddle/static/io.py load_inference_model.
    Returns (program, feed_names, fetch_targets_placeholder)."""
    from jax import export as jexport
    import pickle

    with open(path_prefix + ".pdmodel", "rb") as f:
        blob = f.read()
    with open(path_prefix + ".pdiparams", "rb") as f:
        meta = pickle.load(f)
    exported = jexport.deserialize(bytearray(blob))
    prog = _LoadedProgram(exported, meta["feed_names"], meta["fetch_count"])
    return prog, meta["feed_names"], list(range(meta["fetch_count"]))


class _StaticNN:
    """paddle.static.nn minimal surface (fc/batch_norm map onto nn.*;
    control flow — cond/while_loop/case/switch_case — lowers to lax,
    static/nn.py)."""

    from .nn import case, cond, switch_case, while_loop

    cond = staticmethod(cond)
    while_loop = staticmethod(while_loop)
    case = staticmethod(case)
    switch_case = staticmethod(switch_case)

    @staticmethod
    def fc(x, size, num_flatten_dims=1, activation=None, name=None):
        from .. import nn as _nn
        from ..nn import functional as F

        layer = _nn.Linear(x.shape[-1], size)
        out = layer(x)
        if activation == "relu":
            out = F.relu(out)
        elif activation == "softmax":
            out = F.softmax(out)
        elif activation == "tanh":
            out = F.tanh(out)
        return out


nn = _StaticNN()


from .extras import (  # noqa: F401,E402
    BuildStrategy, ExponentialMovingAverage, IpuCompiledProgram,
    IpuStrategy, Print, Variable, WeightNormParamAttr, accuracy,
    append_backward, auc, cpu_places, create_global_var, create_parameter,
    ctr_metric_bundle, cuda_places, deserialize_persistables,
    deserialize_program, device_guard, global_scope, gradients,
    ipu_shard_guard, load, load_from_file, load_program_state, name_scope,
    normalize_program, py_func, save, save_to_file, scope_guard,
    serialize_persistables, serialize_program, set_ipu_shard,
    set_program_state, xpu_places)
