"""DLRM-style recommender workload over the sparse PS tier.

The production shape the reference framework's PS layer exists for
(PAPER.md: the_one_ps.py + memory_sparse_table.cc): zipfian-skewed
sparse feature ids per slot -> embedding rows pulled from the sparse
table -> a dense MLP over the concatenated slot embeddings (the dense
"tower" runs through ONE fixed-shape jit, compiled once) -> per-row
embedding grads pushed back to the sparse table, MLP grads to a dense
table with server-side SGD.

Everything is deterministic given ``RecommenderConfig.seed``: ids,
targets, table init (per-id, see ps/tables.py) and the jitted tower —
so a run against the sharded fault-tolerant PS tier must be BIT-EXACT
vs :func:`run_reference` over local tables. The failover drill
(tools/ps_drill.py) leans on exactly that.

The client protocol is duck-typed: anything with
``pull_sparse/push_sparse/pull_dense/push_dense`` works — ``PSWorker``
(rpc or LocalTransport) and :class:`LocalClient` both qualify.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["RecommenderConfig", "Recommender", "LocalClient",
           "run_reference"]


class RecommenderConfig:
    def __init__(self, seed: int = 123, batch: int = 16, slots: int = 4,
                 vocab: int = 1000, dim: int = 8, hidden: int = 16,
                 zipf_a: float = 1.3, optimizer: str = "adagrad",
                 lr: float = 0.1):
        self.seed = int(seed)
        self.batch = int(batch)
        self.slots = int(slots)
        self.vocab = int(vocab)
        self.dim = int(dim)
        self.hidden = int(hidden)
        self.zipf_a = float(zipf_a)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.sparse_table_id = 0
        self.dense_table_id = 1

    @property
    def dense_size(self) -> int:
        # W1 [slots*dim, hidden] + b1 [hidden] + w2 [hidden] + b2 [1]
        return (self.slots * self.dim * self.hidden + self.hidden
                + self.hidden + 1)


# one compiled tower per shape tuple; fixed shapes -> compiled once
_GRAD_FNS: Dict[Tuple[int, int, int, int], object] = {}


def _grad_fn(batch: int, slots: int, dim: int, hidden: int):
    key = (batch, slots, dim, hidden)
    fn = _GRAD_FNS.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    n_w1 = slots * dim * hidden

    def loss_fn(dense, rows, targets):
        w1 = dense[:n_w1].reshape(slots * dim, hidden)
        b1 = dense[n_w1:n_w1 + hidden]
        w2 = dense[n_w1 + hidden:n_w1 + 2 * hidden]
        b2 = dense[n_w1 + 2 * hidden]
        x = rows.reshape(batch, slots * dim)
        h = jnp.tanh(x @ w1 + b1)
        pred = h @ w2 + b2
        return jnp.mean((pred - targets) ** 2)

    fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1)))
    _GRAD_FNS[key] = fn
    return fn


class Recommender:
    """Deterministic synthetic CTR-ish regression: the target for a
    sample is the mean of a frozen per-id teacher value over its slots,
    so the embeddings + tower genuinely co-train (loss decreases)."""

    def __init__(self, cfg: Optional[RecommenderConfig] = None):
        self.cfg = cfg or RecommenderConfig()

    def ids(self, step: int) -> np.ndarray:
        """[batch, slots] int64; zipfian within each slot's disjoint
        vocab range (slot s owns [s*vocab, (s+1)*vocab))."""
        c = self.cfg
        rng = np.random.default_rng([c.seed, 555, int(step)])
        z = rng.zipf(c.zipf_a, size=(c.batch, c.slots)) % c.vocab
        return (z + np.arange(c.slots, dtype=np.int64) * c.vocab
                ).astype(np.int64)

    def _teacher(self, rid: int) -> float:
        return float(np.random.default_rng(
            [self.cfg.seed, 777, int(rid)]).standard_normal())

    def targets(self, ids: np.ndarray) -> np.ndarray:
        t = np.array([[self._teacher(r) for r in row] for row in ids],
                     np.float32)
        return t.mean(axis=1)

    def step(self, client, step_idx: int) -> float:
        """One training step through ``client``; returns the loss."""
        c = self.cfg
        ids = self.ids(step_idx)
        flat = ids.ravel()
        rows = client.pull_sparse(c.sparse_table_id, flat, dim=c.dim)
        dense = client.pull_dense(c.dense_table_id)
        targets = self.targets(ids)
        loss, (g_dense, g_rows) = _grad_fn(
            c.batch, c.slots, c.dim, c.hidden)(
                np.asarray(dense, np.float32),
                np.asarray(rows, np.float32).reshape(len(flat), c.dim),
                targets)
        client.push_sparse(c.sparse_table_id, flat,
                           np.asarray(g_rows, np.float32))
        client.push_dense(c.dense_table_id,
                          np.asarray(g_dense, np.float32))
        return float(np.asarray(loss, np.float32))


class LocalClient:
    """Reference client over in-process tables, constructed with the
    SAME seeds/hyperparams TheOnePSRuntime gives the sharded tier —
    per-id deterministic row init makes the two bit-identical."""

    def __init__(self, cfg: RecommenderConfig, entry_attr=None,
                 capacity=None):
        from ..distributed.ps.tables import DenseTable, SparseTable

        self.cfg = cfg
        self.sparse = SparseTable(
            cfg.dim, optimizer=cfg.optimizer, lr=cfg.lr,
            seed=1000 + cfg.sparse_table_id, entry_attr=entry_attr,
            capacity=capacity)
        self.dense = DenseTable((cfg.dense_size,), lr=cfg.lr)

    def pull_sparse(self, table_id: int, ids, dim=None) -> np.ndarray:
        assert table_id == self.cfg.sparse_table_id
        return self.sparse.pull(np.asarray(ids, np.int64).ravel())

    def push_sparse(self, table_id: int, ids, grads) -> None:
        assert table_id == self.cfg.sparse_table_id
        self.sparse.push(np.asarray(ids, np.int64).ravel(),
                         np.asarray(grads, np.float32))

    def pull_dense(self, table_id: int) -> np.ndarray:
        assert table_id == self.cfg.dense_table_id
        return self.dense.pull()

    def push_dense(self, table_id: int, grad) -> None:
        assert table_id == self.cfg.dense_table_id
        self.dense.push(np.asarray(grad, np.float32))


def run_reference(cfg: RecommenderConfig,
                  steps: int) -> Tuple[List[float], LocalClient]:
    """Fault-free single-table reference run: the loss sequence every
    PS-tier run (sharded, replicated, failed-over) must reproduce
    bit-exactly."""
    client = LocalClient(cfg)
    rec = Recommender(cfg)
    losses = [rec.step(client, i) for i in range(steps)]
    return losses, client
