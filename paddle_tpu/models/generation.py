"""Fused decode path: whole-generation compiled autoregressive decoding
(reference: the serving fusion tier paddle/phi/kernels/fusion/gpu/ —
fused_multi_transformer_kernel.cu, masked_multihead_attention_kernel.cu —
and PaddleNLP's generate loop).

TPU-native design: instead of per-op fused CUDA kernels driven by a host
loop, the ENTIRE decode runs as one XLA program — prefill fills a
fixed-size KV cache, then ``lax.scan`` iterates single-token steps with
``dynamic_update_slice`` cache writes and masked single-query attention.
Zero host round-trips per token (the 97ms tunnel dispatch would otherwise
dwarf the ~µs of decode math); XLA fuses ln/rope/proj into the matmuls
the way fused_multi_transformer does by hand.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _rng
from ..core.tensor import Tensor

__all__ = ["generate"]


def _gpt_weights(model):
    """Flat pytree of decode-relevant arrays for a GPTForCausalLM."""
    g = model.gpt
    layers = []
    for blk in g.h:
        layers.append({
            "ln1_w": blk.ln_1.weight._data, "ln1_b": blk.ln_1.bias._data,
            "qkv_w": blk.attn.qkv_proj.weight._data,
            "qkv_b": (blk.attn.qkv_proj.bias._data
                      if blk.attn.qkv_proj.bias is not None else None),
            "out_w": blk.attn.out_proj.weight._data,
            "out_b": (blk.attn.out_proj.bias._data
                      if blk.attn.out_proj.bias is not None else None),
            "ln2_w": blk.ln_2.weight._data, "ln2_b": blk.ln_2.bias._data,
            "fc1_w": blk.mlp.fc1.weight._data,
            "fc1_b": (blk.mlp.fc1.bias._data
                      if blk.mlp.fc1.bias is not None else None),
            "fc2_w": blk.mlp.fc2.weight._data,
            "fc2_b": (blk.mlp.fc2.bias._data
                      if blk.mlp.fc2.bias is not None else None),
        })
    head = None if model.lm_head is None else model.lm_head.weight._data
    return {
        "wte": g.wte.weight._data, "wpe": g.wpe.weight._data,
        "lnf_w": g.ln_f.weight._data, "lnf_b": g.ln_f.bias._data,
        "layers": layers, "lm_head": head,
    }


def _ln(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x.dtype)


def _linear(x, w, b):
    y = x @ w
    return y if b is None else y + b


def _block_step(cfg, W, x, ck, cv, pos, t_mask):
    """One decoder block for a single token x [b, h]; cache [b, T, nh, hd].
    The masked single-query attention + cache write is the
    masked_multihead_attention analog."""
    nh, hd = cfg.num_heads, cfg.head_dim
    b = x.shape[0]
    h1 = _ln(x, W["ln1_w"], W["ln1_b"], cfg.layer_norm_eps)
    qkv = _linear(h1, W["qkv_w"], W["qkv_b"]).reshape(b, 3, nh, hd)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    ck = jax.lax.dynamic_update_slice(ck, k[:, None], (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v[:, None], (0, pos, 0, 0))
    scores = jnp.einsum("bhd,bthd->bht", q, ck,
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    scores = jnp.where(t_mask[None, None, :], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    attn = jnp.einsum("bht,bthd->bhd", w, cv).reshape(b, nh * hd)
    x = x + _linear(attn, W["out_w"], W["out_b"])
    h2 = _ln(x, W["ln2_w"], W["ln2_b"], cfg.layer_norm_eps)
    m = _linear(h2, W["fc1_w"], W["fc1_b"])
    m = jax.nn.gelu(m, approximate=True)
    x = x + _linear(m, W["fc2_w"], W["fc2_b"])
    return x, ck, cv


def _logits(cfg, weights, x):
    x = _ln(x, weights["lnf_w"], weights["lnf_b"], cfg.layer_norm_eps)
    head = weights["lm_head"]
    if head is None:
        return x @ weights["wte"].T
    return x @ head


def _sample(logits, key, temperature, top_p):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits.astype(jnp.float32) / max(temperature, 1e-6)
    if top_p is not None:
        probs = jax.nn.softmax(lg, axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = (cum - sorted_p) < top_p
        filt = jnp.where(keep, sorted_p, 0.0)
        draw = jax.random.categorical(
            key, jnp.log(jnp.maximum(filt, 1e-30)), axis=-1)
        return jnp.take_along_axis(sort_idx, draw[..., None],
                                   axis=-1)[..., 0].astype(jnp.int32)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


def generate(model, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, name=None):
    """Greedy / temperature / nucleus decoding, fully compiled.

    Returns the generated token ids [batch, max_new_tokens] (prompt not
    included). ``temperature=0`` = greedy. Tokens after ``eos_token_id``
    are clamped to eos.
    """
    cfg = model.config
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids), jnp.int32)
    ids = ids.astype(jnp.int32)
    b, plen = ids.shape
    total = plen + max_new_tokens
    max_pos = getattr(cfg, "max_position_embeddings", None)
    if max_pos is not None and total > max_pos:
        raise ValueError(
            f"prompt length {plen} + max_new_tokens {max_new_tokens} = "
            f"{total} exceeds max_position_embeddings {max_pos}; XLA would "
            "silently clamp position-embedding gathers past the window")
    weights = _gpt_weights(model)
    L = cfg.num_layers
    nh, hd = cfg.num_heads, cfg.head_dim
    dt = weights["wte"].dtype

    # per-model compile cache (on the instance: dies with the model, and
    # id-reuse after gc can't serve a stale executable)
    cache = getattr(model, "_gen_cache", None)
    if cache is None:
        cache = model._gen_cache = {}
    key_cache = (b, plen, max_new_tokens, temperature, top_p,
                 eos_token_id)
    fn = cache.get(key_cache)
    if fn is None:

        def run(weights, ids, key):
            # ---- prefill: standard causal forward, write caches -------
            pos_ids = jnp.arange(plen)[None, :]
            x = weights["wte"][ids] + weights["wpe"][pos_ids]
            x = x.astype(dt)
            cks, cvs = [], []
            causal = jnp.tril(jnp.ones((plen, plen), bool))
            for W in weights["layers"]:
                h1 = _ln(x, W["ln1_w"], W["ln1_b"], cfg.layer_norm_eps)
                qkv = _linear(h1, W["qkv_w"], W["qkv_b"]) \
                    .reshape(b, plen, 3, nh, hd)
                q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
                ck = jnp.zeros((b, total, nh, hd), dt).at[:, :plen].set(k)
                cv = jnp.zeros((b, total, nh, hd), dt).at[:, :plen].set(v)
                sc = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                                preferred_element_type=jnp.float32) \
                    * (hd ** -0.5)
                sc = jnp.where(causal, sc, -1e30)
                wts = jax.nn.softmax(sc, axis=-1).astype(dt)
                att = jnp.einsum("bhqk,bkhd->bqhd", wts, v) \
                    .reshape(b, plen, nh * hd)
                x = x + _linear(att, W["out_w"], W["out_b"])
                h2 = _ln(x, W["ln2_w"], W["ln2_b"], cfg.layer_norm_eps)
                m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                                approximate=True)
                x = x + _linear(m, W["fc2_w"], W["fc2_b"])
                cks.append(ck)
                cvs.append(cv)
            ck = jnp.stack(cks)            # [L, b, total, nh, hd]
            cv = jnp.stack(cvs)
            lg0 = _logits(cfg, weights, x[:, -1])
            key, k0 = jax.random.split(key)
            tok0 = _sample(lg0, k0, temperature, top_p)

            # ---- decode: one scan step per new token ------------------
            def step(carry, _):
                tok, pos, ck, cv, key, alive = carry
                key, sk = jax.random.split(key)
                x = (weights["wte"][tok] + weights["wpe"][pos]).astype(dt)
                t_mask = jnp.arange(total) <= pos
                new_ck, new_cv = [], []
                for i, W in enumerate(weights["layers"]):
                    x, cki, cvi = _block_step(cfg, W, x, ck[i], cv[i],
                                              pos, t_mask)
                    new_ck.append(cki)
                    new_cv.append(cvi)
                ck = jnp.stack(new_ck)
                cv = jnp.stack(new_cv)
                lg = _logits(cfg, weights, x)
                nxt = _sample(lg, sk, temperature, top_p)
                if eos_token_id is not None:
                    nxt = jnp.where(alive, nxt, eos_token_id)
                    alive = alive & (nxt != eos_token_id)
                return (nxt, pos + 1, ck, cv, key, alive), nxt

            alive = jnp.ones((b,), bool)
            if eos_token_id is not None:
                alive = alive & (tok0 != eos_token_id)
            carry = (tok0, jnp.int32(plen), ck, cv, key, alive)
            if max_new_tokens > 1:
                _, rest = jax.lax.scan(step, carry, None,
                                       length=max_new_tokens - 1)
                toks = jnp.concatenate([tok0[None], rest], axis=0)
            else:
                toks = tok0[None]
            return jnp.swapaxes(toks, 0, 1)   # [b, max_new]

        fn = jax.jit(run)
        cache[key_cache] = fn

    key = _rng.next_key()
    out = fn(weights, ids, key)
    return Tensor(out)
