"""Fused decode path: whole-generation compiled autoregressive decoding
(reference: the serving fusion tier paddle/phi/kernels/fusion/gpu/ —
fused_multi_transformer_kernel.cu, masked_multihead_attention_kernel.cu —
and PaddleNLP's generate loop; beam search reconstructs sequences with
gather_tree exactly like the reference's gather_tree op).

TPU-native design: instead of per-op fused CUDA kernels driven by a host
loop, the ENTIRE decode runs as one XLA program — prefill fills a
fixed-size KV cache, then ``lax.scan`` iterates single-token steps with
``dynamic_update_slice`` cache writes and masked single-query attention.
Zero host round-trips per token (the 97ms tunnel dispatch would otherwise
dwarf the ~µs of decode math); XLA fuses ln/rope/proj into the matmuls
the way fused_multi_transformer does by hand.

The engine is MODEL-GENERIC: each CausalLM exposes ``decode_adapter()``
returning a DecodeAdapter (weight extraction + pure-array embed / prefill
/ single-token block step / logits), and this module drives sampling
(greedy / temperature / top-p) and beam search over any adapter.
"""
from __future__ import annotations

import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import observability as _obs
from ..core import random as _rng
from ..core.tensor import Tensor

__all__ = ["generate", "beam_search", "speculative_generate",
           "GPTDecodeAdapter", "LlamaDecodeAdapter"]


def _ln(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x.dtype)


def _rms(x, w, eps):
    x32 = x.astype(jnp.float32)
    nrm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (nrm * w.astype(jnp.float32)).astype(x.dtype)


def _linear(x, w, b=None):
    if isinstance(w, dict) and "q4" in w:
        # weight-only int4, group-wise scales (reference:
        # nn/quant/quantized_linear.py weight_only_linear
        # weight_dtype='int4'): w is {"q4": [G, gs, out] int4,
        # "s4": [G, out]}. The int4->bf16 convert fuses into the
        # grouped-dot operand read; the per-group scale contraction is
        # a [*, G, out] x [G, out] reduce — tiny next to the weight
        # stream, which drops to a QUARTER of bf16.
        G, gs, out_dim = w["q4"].shape
        xg = x.reshape(x.shape[:-1] + (G, gs))
        z = jnp.einsum("...gi,gio->...go", xg,
                       w["q4"].astype(x.dtype))
        y = jnp.einsum("...go,go->...o", z, w["s4"].astype(x.dtype))
    elif isinstance(w, dict) and "q8" in w:
        # weight-only int8: XLA fuses the int8->bf16 convert into the
        # matmul operand read, so HBM traffic halves vs bf16 weights —
        # decode is weight-bandwidth-bound, so this is ~2x tokens/s
        y = (x @ w["q8"].astype(x.dtype)) * w["s"].astype(x.dtype)
    else:
        y = x @ w
    return y if b is None else y + b


def _quantize_w(w):
    """Per-output-channel symmetric int8 for a [in, out] matmul weight."""
    s = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0,
                keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127) \
        .astype(jnp.int8)
    return {"q8": q, "s": s}


def _quantize_w4(w, group=128):
    """Group-wise symmetric int4 for a [in, out] matmul weight: scales
    per (input-group, out-channel), the standard weight-only-int4 recipe
    (reference: nn/quant/quantized_linear.py weight_only_linear,
    group_size arg). The nibbles are STORED as int8 ("q4i8") and
    converted to jnp.int4 on device inside the compiled program
    (_activate_q4): int4 arrays cannot cross the jit boundary on every
    platform plugin, but a convert placed inside the program
    materializes the packed copy once per dispatch, and the decode scan
    then streams the QUARTER-width weights from HBM every step."""
    din, dout = w.shape
    if din % group != 0:
        return _quantize_w(w)       # ragged in-dim: fall back to int8
    wg = w.astype(jnp.float32).reshape(din // group, group, dout)
    s = jnp.max(jnp.abs(wg), axis=1) / 7.0           # [G, out]
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(wg / s[:, None, :]), -7, 7).astype(jnp.int8)
    return {"q4i8": q, "s4": s.astype(jnp.bfloat16)}


def _activate_q4(w):
    """Inside-jit tree walk converting stored q4i8 nibbles to jnp.int4
    (values already in [-7, 7], so the convert is exact)."""
    if isinstance(w, dict):
        if "q4i8" in w:
            return {"q4": w["q4i8"].astype(jnp.int4), "s4": w["s4"]}
        return {k: _activate_q4(v) for k, v in w.items()}
    if isinstance(w, list):
        return [_activate_q4(v) for v in w]
    return w


_QUANT_SKIP = {"wte", "wpe"}  # embedding gathers stay full precision


def _quantized_weights(model, w_now, bits=8):
    """Per-model cached quantized weight tree (shared by generate /
    speculative target / speculative draft). Re-quantize after a weight
    update by clearing ``model._gen_quant_w`` / ``_gen_quant_w4``."""
    attr = "_gen_quant_w" if bits == 8 else "_gen_quant_w4"
    qw = getattr(model, attr, None)
    if qw is None:
        if w_now.get("lm_head") is None:
            w_now = dict(w_now)
            w_now["lm_head"] = w_now["wte"].T
        qw = _quantize_tree(w_now, bits=bits)
        setattr(model, attr, qw)
    return qw


def _resolve_weight_quant(model, w_now, weight_quant):
    if weight_quant is None:
        return w_now
    if weight_quant == "int8":
        return _quantized_weights(model, w_now, bits=8)
    if weight_quant == "int4":
        return _quantized_weights(model, w_now, bits=4)
    raise ValueError("weight_quant must be None, 'int8' or 'int4'")


def _quantize_tree(w, min_dim=256, bits=8):
    """Walk an adapter weight pytree, replacing big 2D matmul weights with
    int8 (or group-wise int4) quant dicts (reference analog:
    weight_only_linear / llm.int8 serving paths,
    phi/kernels/fusion/gpu/fused_weight_only_*). In int4 mode the
    lm_head stays int8: the argmax over the vocab is the single most
    quantization-sensitive matmul in the decode."""
    if isinstance(w, dict):
        out = {}
        for k, v in w.items():
            if k in _QUANT_SKIP:
                out[k] = v
            elif isinstance(v, (dict, list)):
                out[k] = _quantize_tree(v, min_dim, bits)
            elif (hasattr(v, "ndim") and v is not None and v.ndim == 2
                    and min(v.shape) >= min_dim):
                if bits == 4 and k != "lm_head":
                    out[k] = _quantize_w4(v)
                else:
                    out[k] = _quantize_w(v)
            else:
                out[k] = v
        return out
    if isinstance(w, list):
        return [_quantize_tree(v, min_dim, bits) for v in w]
    return w


def _quantize_kv(k):
    """Per-(position, head) symmetric int8 for a [..., nh, hd] K or V
    slab (reference analog: the cache_k_quant_scales /
    cache_v_quant_scales surface of
    python/paddle/incubate/nn/functional/masked_multihead_attention.py —
    there the scales are host-computed calibration inputs; here they are
    computed on the fly per written row, which is exact for the
    read side because each row's scale rides with it)."""
    s = jnp.max(jnp.abs(k.astype(jnp.float32)), axis=-1) / 127.0
    s = jnp.maximum(s, 1e-8)                        # [..., nh]
    q = jnp.clip(jnp.round(k.astype(jnp.float32) / s[..., None]),
                 -127, 127).astype(jnp.int8)
    return {"q8": q, "s": s}


# Cache layout is HEAD-MAJOR [b, nh, T, hd] (scales [b, nh, T]): the
# per-step attention then lowers to batched matmuls over (b, h) with a
# contiguous [T, hd] panel per head — the MXU-friendly orientation —
# instead of strided gathers over a [b, T, nh, hd] slab.

def _kv_prefill_store(k, b, total, plen, dt, quant):
    """Build a [b, nh, total, hd] cache from prefill rows
    k [b, plen, nh, hd]."""
    nh, hd = k.shape[-2], k.shape[-1]
    k = jnp.swapaxes(k, 1, 2)                       # [b, nh, plen, hd]
    if not quant:
        return jnp.zeros((b, nh, total, hd), dt).at[:, :, :plen].set(k)
    qk = _quantize_kv(k)
    return {"q8": jnp.zeros((b, nh, total, hd), jnp.int8)
            .at[:, :, :plen].set(qk["q8"]),
            "s": jnp.zeros((b, nh, total), jnp.float32)
            .at[:, :, :plen].set(qk["s"])}


def _kv_write(cache, k, pos):
    """Write one decode row k [b, nh, hd] at position pos."""
    if not isinstance(cache, dict):
        return jax.lax.dynamic_update_slice(cache, k[:, :, None],
                                            (0, 0, pos, 0))
    qk = _quantize_kv(k)
    return {"q8": jax.lax.dynamic_update_slice(
                cache["q8"], qk["q8"][:, :, None], (0, 0, pos, 0)),
            "s": jax.lax.dynamic_update_slice(
                cache["s"], qk["s"][:, :, None], (0, 0, pos))}


def _kv_write_rows(cache, k, pos):
    """Write g rows k [b, g, nh, hd] at per-row positions pos [b, g]
    (speculative verify writes land at different offsets per sequence).
    Out-of-window positions (finished rows still looping) are dropped.
    Advanced indices on axes 0 and 2 around the head slice produce
    [b, g, nh, hd] update slots — matching k's natural layout."""
    bidx = jnp.arange(k.shape[0])[:, None]
    if not isinstance(cache, dict):
        return cache.at[bidx, :, pos].set(k.astype(cache.dtype),
                                          mode="drop")
    qk = _quantize_kv(k)
    return {"q8": cache["q8"].at[bidx, :, pos].set(qk["q8"],
                                                   mode="drop"),
            "s": cache["s"].at[bidx, :, pos].set(qk["s"], mode="drop")}


def _kv_repeat(cache, rep):
    """GQA head replication for either cache representation."""
    if rep <= 1:
        return cache
    if not isinstance(cache, dict):
        return jnp.repeat(cache, rep, axis=1)
    return {"q8": jnp.repeat(cache["q8"], rep, axis=1),
            "s": jnp.repeat(cache["s"], rep, axis=1)}


def _rope(x, pos, base):
    """Rotate [..., nh, hd] by absolute positions pos (int array
    broadcastable to x.shape[:-2])."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[..., None, None] * freqs  # [..., 1, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


class DecodeAdapter:
    """Per-model weight-extraction + pure-array decode callbacks.

    Attributes: num_layers, num_kv_heads, head_dim, dtype, vocab_size,
    max_positions, weights (flat pytree of jax arrays).
    Methods (all pure over arrays, jit-safe):
      prefill(w, ids, total) -> (x [b, plen, h], ck, cv [L, b, total, kvh, hd])
      step(w, tok [b], pos, ck, cv, t_mask) -> (logits [b, V], ck, cv)
    """


class GPTDecodeAdapter(DecodeAdapter):
    """Learned-position GPT decoder (gpt.py GPTForCausalLM)."""

    def __init__(self, model):
        cfg = model.config
        self.num_layers = cfg.num_layers
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_heads
        self.head_dim = cfg.head_dim
        self.eps = cfg.layer_norm_eps
        self.vocab_size = cfg.vocab_size
        self.max_positions = getattr(cfg, "max_position_embeddings", None)
        g = model.gpt
        layers = []
        for blk in g.h:
            layers.append({
                "ln1_w": blk.ln_1.weight._data, "ln1_b": blk.ln_1.bias._data,
                "qkv_w": blk.attn.qkv_proj.weight._data,
                "qkv_b": (blk.attn.qkv_proj.bias._data
                          if blk.attn.qkv_proj.bias is not None else None),
                "out_w": blk.attn.out_proj.weight._data,
                "out_b": (blk.attn.out_proj.bias._data
                          if blk.attn.out_proj.bias is not None else None),
                "ln2_w": blk.ln_2.weight._data, "ln2_b": blk.ln_2.bias._data,
                "fc1_w": blk.mlp.fc1.weight._data,
                "fc1_b": (blk.mlp.fc1.bias._data
                          if blk.mlp.fc1.bias is not None else None),
                "fc2_w": blk.mlp.fc2.weight._data,
                "fc2_b": (blk.mlp.fc2.bias._data
                          if blk.mlp.fc2.bias is not None else None),
            })
        head = None if model.lm_head is None else model.lm_head.weight._data
        self.weights = {
            "wte": g.wte.weight._data, "wpe": g.wpe.weight._data,
            "lnf_w": g.ln_f.weight._data, "lnf_b": g.ln_f.bias._data,
            "layers": layers, "lm_head": head,
        }
        self.dtype = self.weights["wte"].dtype

    def logits(self, w, x):
        x = _ln(x, w["lnf_w"], w["lnf_b"], self.eps)
        head = w["lm_head"]
        if head is None:
            return x @ w["wte"].T
        return _linear(x, head)

    def prefill(self, w, ids, total, kv_quant=False):
        b, plen = ids.shape
        nh, hd, dt = self.num_heads, self.head_dim, self.dtype
        pos_ids = jnp.arange(plen)[None, :]
        x = (w["wte"][ids] + w["wpe"][pos_ids]).astype(dt)
        cks, cvs = [], []
        causal = jnp.tril(jnp.ones((plen, plen), bool))
        for W in w["layers"]:
            h1 = _ln(x, W["ln1_w"], W["ln1_b"], self.eps)
            qkv = _linear(h1, W["qkv_w"], W["qkv_b"]) \
                .reshape(b, plen, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            ck = _kv_prefill_store(k, b, total, plen, dt, kv_quant)
            cv = _kv_prefill_store(v, b, total, plen, dt, kv_quant)
            att = _causal_prefill_attn(q, k, v, causal, hd, dt)
            x = x + _linear(att, W["out_w"], W["out_b"])
            h2 = _ln(x, W["ln2_w"], W["ln2_b"], self.eps)
            m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                            approximate=True)
            x = x + _linear(m, W["fc2_w"], W["fc2_b"])
            cks.append(ck)
            cvs.append(cv)
        return x, tuple(cks), tuple(cvs)

    def step(self, w, tok, pos, ck, cv, t_mask):
        nh, hd, dt = self.num_heads, self.head_dim, self.dtype
        b = tok.shape[0]
        x = (w["wte"][tok] + w["wpe"][pos]).astype(dt)
        new_ck, new_cv = [], []
        for i, W in enumerate(w["layers"]):
            h1 = _ln(x, W["ln1_w"], W["ln1_b"], self.eps)
            qkv = _linear(h1, W["qkv_w"], W["qkv_b"]).reshape(b, 3, nh, hd)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            cki = _kv_write(ck[i], k, pos)
            cvi = _kv_write(cv[i], v, pos)
            att = _masked_sdpa(q, cki, cvi, t_mask, hd)
            x = x + _linear(att.reshape(b, nh * hd),
                            W["out_w"], W["out_b"])
            h2 = _ln(x, W["ln2_w"], W["ln2_b"], self.eps)
            m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                            approximate=True)
            x = x + _linear(m, W["fc2_w"], W["fc2_b"])
            new_ck.append(cki)
            new_cv.append(cvi)
        return self.logits(w, x), tuple(new_ck), tuple(new_cv)

    def chunk_step(self, w, toks, pos, ck, cv):
        """g tokens at per-row positions in one pass (speculative-decode
        draft/verify; the draft_model surface of the reference's
        fused_speculate_* serving ops). toks, pos [b, g]; returns
        logits [b, g, V] where slot j reflects the prefix through
        toks[:, j]."""
        nh, hd, dt = self.num_heads, self.head_dim, self.dtype
        b, g = toks.shape
        x = (w["wte"][toks] + w["wpe"][pos]).astype(dt)
        new_ck, new_cv = [], []
        for i, W in enumerate(w["layers"]):
            h1 = _ln(x, W["ln1_w"], W["ln1_b"], self.eps)
            qkv = _linear(h1, W["qkv_w"], W["qkv_b"]) \
                .reshape(b, g, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            cki = _kv_write_rows(ck[i], k, pos)
            cvi = _kv_write_rows(cv[i], v, pos)
            att = _chunk_sdpa(q, cki, cvi, pos, hd)
            x = x + _linear(att.reshape(b, g, nh * hd),
                            W["out_w"], W["out_b"])
            h2 = _ln(x, W["ln2_w"], W["ln2_b"], self.eps)
            m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                            approximate=True)
            x = x + _linear(m, W["fc2_w"], W["fc2_b"])
            new_ck.append(cki)
            new_cv.append(cvi)
        return self.logits(w, x), tuple(new_ck), tuple(new_cv)

    def paged_chunk(self, w, toks, pos, kpages, vpages, block_tables):
        """g tokens at per-row positions over PAGED KV pools (the
        continuous-batching step of serving/engine.py). toks/pos
        [b, g]; kpages/vpages: per-layer tuples of [n_kv, pages, page,
        d] pools (bf16 or int8 dicts); block_tables [b, P]. ``pos < 0``
        rows are inactive: their writes are dropped and their attention
        is zero. Returns (logits [b, g, V], kpages, vpages)."""
        from ..incubate.nn.pallas.paged_attention import \
            paged_kv_write_chunk

        nh, hd, dt = self.num_heads, self.head_dim, self.dtype
        b, g = toks.shape
        x = (w["wte"][toks] + w["wpe"][jnp.maximum(pos, 0)]).astype(dt)
        new_kp, new_vp = [], []
        for i, W in enumerate(w["layers"]):
            h1 = _ln(x, W["ln1_w"], W["ln1_b"], self.eps)
            qkv = _linear(h1, W["qkv_w"], W["qkv_b"]) \
                .reshape(b, g, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            kpi, vpi = paged_kv_write_chunk(kpages[i], vpages[i], k, v,
                                            block_tables, pos)
            att = _paged_attn_chunk(q, kpi, vpi, block_tables, pos, hd)
            x = x + _linear(att.reshape(b, g, nh * hd),
                            W["out_w"], W["out_b"])
            h2 = _ln(x, W["ln2_w"], W["ln2_b"], self.eps)
            m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                            approximate=True)
            x = x + _linear(m, W["fc2_w"], W["fc2_b"])
            new_kp.append(kpi)
            new_vp.append(vpi)
        return self.logits(w, x), tuple(new_kp), tuple(new_vp)

    def ragged_chunk(self, w, toks, pos, row_of, q_starts, query_lens,
                     context_lens, kpages, vpages, block_tables):
        """ONE ragged mixed prefill+decode step over paged pools (the
        single-dispatch serving step). Flat token axis [T] packed
        row-major: row r owns tokens q_starts[r] ..
        q_starts[r]+query_lens[r], row_of [T] maps each token to its
        row (-1 = padding). pos [T] is each token's absolute position
        (< 0 = padding: write dropped, output ignored); block_tables
        [n_rows, P] is per ROW; context_lens[r] counts the row's KV
        INCLUDING this step's tokens. Returns (logits [T, V], kpages,
        vpages)."""
        from ..incubate.nn.pallas.paged_attention import \
            paged_kv_write_chunk

        nh, hd, dt = self.num_heads, self.head_dim, self.dtype
        T = toks.shape[0]
        n_rows = block_tables.shape[0]
        bt_tok = jnp.take(block_tables,
                          jnp.clip(row_of, 0, n_rows - 1), axis=0)
        x = (w["wte"][toks] + w["wpe"][jnp.maximum(pos, 0)]).astype(dt)
        new_kp, new_vp = [], []
        for i, W in enumerate(w["layers"]):
            h1 = _ln(x, W["ln1_w"], W["ln1_b"], self.eps)
            qkv = _linear(h1, W["qkv_w"], W["qkv_b"]).reshape(T, 3, nh, hd)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            kpi, vpi = paged_kv_write_chunk(kpages[i], vpages[i],
                                            k[:, None], v[:, None],
                                            bt_tok, pos[:, None])
            att = _ragged_attn(q, kpi, vpi, block_tables, context_lens,
                               query_lens, q_starts, row_of, hd)
            x = x + _linear(att.reshape(T, nh * hd),
                            W["out_w"], W["out_b"])
            h2 = _ln(x, W["ln2_w"], W["ln2_b"], self.eps)
            m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                            approximate=True)
            x = x + _linear(m, W["fc2_w"], W["fc2_b"])
            new_kp.append(kpi)
            new_vp.append(vpi)
        return self.logits(w, x), tuple(new_kp), tuple(new_vp)


class LlamaDecodeAdapter(DecodeAdapter):
    """RMSNorm + rope + GQA + SwiGLU decoder (llama.py LlamaForCausalLM)."""

    def __init__(self, model):
        cfg = model.config
        self.num_layers = cfg.num_layers
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        self.head_dim = cfg.head_dim
        self.eps = cfg.rms_norm_eps
        self.rope_base = cfg.rope_base
        self.vocab_size = cfg.vocab_size
        self.max_positions = getattr(cfg, "max_position_embeddings", None)
        mdl = model.llama
        layers = []
        for blk in mdl.layers:
            layers.append({
                "in_ln": blk.input_layernorm.weight._data,
                "q_w": blk.self_attn.q_proj.weight._data,
                "k_w": blk.self_attn.k_proj.weight._data,
                "v_w": blk.self_attn.v_proj.weight._data,
                "o_w": blk.self_attn.o_proj.weight._data,
                "post_ln": blk.post_attention_layernorm.weight._data,
                "gate_w": blk.mlp.gate_proj.weight._data,
                "up_w": blk.mlp.up_proj.weight._data,
                "down_w": blk.mlp.down_proj.weight._data,
            })
        head = None if model.lm_head is None else model.lm_head.weight._data
        self.weights = {
            "wte": mdl.embed_tokens.weight._data,
            "norm": mdl.norm.weight._data,
            "layers": layers, "lm_head": head,
        }
        self.dtype = self.weights["wte"].dtype

    def logits(self, w, x):
        x = _rms(x, w["norm"], self.eps)
        head = w["lm_head"]
        if head is None:
            return x @ w["wte"].T
        return _linear(x, head)

    def _qkv(self, W, x, b, s):
        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        h1 = _rms(x, W["in_ln"], self.eps)
        q = _linear(h1, W["q_w"]).reshape(b, s, nh, hd)
        k = _linear(h1, W["k_w"]).reshape(b, s, kvh, hd)
        v = _linear(h1, W["v_w"]).reshape(b, s, kvh, hd)
        return q, k, v

    def prefill(self, w, ids, total, kv_quant=False):
        b, plen = ids.shape
        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        dt = self.dtype
        x = w["wte"][ids].astype(dt)
        pos = jnp.arange(plen)[None, :]
        cks, cvs = [], []
        causal = jnp.tril(jnp.ones((plen, plen), bool))
        rep = nh // kvh
        for W in w["layers"]:
            q, k, v = self._qkv(W, x, b, plen)
            q = _rope(q, pos, self.rope_base)
            k = _rope(k, pos, self.rope_base)
            ck = _kv_prefill_store(k, b, total, plen, dt, kv_quant)
            cv = _kv_prefill_store(v, b, total, plen, dt, kv_quant)
            kf = jnp.repeat(k, rep, axis=2) if rep > 1 else k
            vf = jnp.repeat(v, rep, axis=2) if rep > 1 else v
            att = _causal_prefill_attn(q, kf, vf, causal, hd, dt)
            x = x + _linear(att, W["o_w"])
            h2 = _rms(x, W["post_ln"], self.eps)
            m = jax.nn.silu(_linear(h2, W["gate_w"])) * _linear(h2, W["up_w"])
            x = x + _linear(m, W["down_w"])
            cks.append(ck)
            cvs.append(cv)
        return x, tuple(cks), tuple(cvs)

    def step(self, w, tok, pos, ck, cv, t_mask):
        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        dt = self.dtype
        b = tok.shape[0]
        x = w["wte"][tok].astype(dt)
        rep = nh // kvh
        pos_b = jnp.broadcast_to(jnp.asarray(pos), (b, 1))
        new_ck, new_cv = [], []
        for i, W in enumerate(w["layers"]):
            q, k, v = self._qkv(W, x[:, None], b, 1)
            q = _rope(q, pos_b, self.rope_base)[:, 0]
            k = _rope(k, pos_b, self.rope_base)[:, 0]
            v = v[:, 0]
            cki = _kv_write(ck[i], k, pos)
            cvi = _kv_write(cv[i], v, pos)
            kf = _kv_repeat(cki, rep)
            vf = _kv_repeat(cvi, rep)
            att = _masked_sdpa(q, kf, vf, t_mask, hd)
            x = x + _linear(att.reshape(b, nh * hd), W["o_w"])
            h2 = _rms(x, W["post_ln"], self.eps)
            m = jax.nn.silu(_linear(h2, W["gate_w"])) * _linear(h2, W["up_w"])
            x = x + _linear(m, W["down_w"])
            new_ck.append(cki)
            new_cv.append(cvi)
        return self.logits(w, x), tuple(new_ck), tuple(new_cv)

    def chunk_step(self, w, toks, pos, ck, cv):
        """g tokens at per-row positions in one pass (speculative
        draft/verify). toks, pos [b, g]; logits [b, g, V]."""
        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        dt = self.dtype
        b, g = toks.shape
        x = w["wte"][toks].astype(dt)
        rep = nh // kvh
        new_ck, new_cv = [], []
        for i, W in enumerate(w["layers"]):
            q, k, v = self._qkv(W, x, b, g)
            q = _rope(q, pos, self.rope_base)
            k = _rope(k, pos, self.rope_base)
            cki = _kv_write_rows(ck[i], k, pos)
            cvi = _kv_write_rows(cv[i], v, pos)
            att = _chunk_sdpa(q, _kv_repeat(cki, rep),
                              _kv_repeat(cvi, rep), pos, hd)
            x = x + _linear(att.reshape(b, g, nh * hd), W["o_w"])
            h2 = _rms(x, W["post_ln"], self.eps)
            m = jax.nn.silu(_linear(h2, W["gate_w"])) * _linear(h2, W["up_w"])
            x = x + _linear(m, W["down_w"])
            new_ck.append(cki)
            new_cv.append(cvi)
        return self.logits(w, x), tuple(new_ck), tuple(new_cv)

    def paged_chunk(self, w, toks, pos, kpages, vpages, block_tables):
        """Paged-pool analog of chunk_step for the serving engine —
        see GPTDecodeAdapter.paged_chunk. GQA pools carry num_kv_heads
        head panels; rope rotates by the per-row positions."""
        from ..incubate.nn.pallas.paged_attention import \
            paged_kv_write_chunk

        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        dt = self.dtype
        b, g = toks.shape
        x = w["wte"][toks].astype(dt)
        safe_pos = jnp.maximum(pos, 0)
        new_kp, new_vp = [], []
        for i, W in enumerate(w["layers"]):
            q, k, v = self._qkv(W, x, b, g)
            q = _rope(q, safe_pos, self.rope_base)
            k = _rope(k, safe_pos, self.rope_base)
            kpi, vpi = paged_kv_write_chunk(kpages[i], vpages[i], k, v,
                                            block_tables, pos)
            att = _paged_attn_chunk(q, kpi, vpi, block_tables, pos, hd)
            x = x + _linear(att.reshape(b, g, nh * hd), W["o_w"])
            h2 = _rms(x, W["post_ln"], self.eps)
            m = jax.nn.silu(_linear(h2, W["gate_w"])) \
                * _linear(h2, W["up_w"])
            x = x + _linear(m, W["down_w"])
            new_kp.append(kpi)
            new_vp.append(vpi)
        return self.logits(w, x), tuple(new_kp), tuple(new_vp)

    def ragged_chunk(self, w, toks, pos, row_of, q_starts, query_lens,
                     context_lens, kpages, vpages, block_tables):
        """Ragged single-dispatch serving step — see
        GPTDecodeAdapter.ragged_chunk. GQA pools carry num_kv_heads
        panels; rope rotates each token by its absolute position."""
        from ..incubate.nn.pallas.paged_attention import \
            paged_kv_write_chunk

        nh, hd = self.num_heads, self.head_dim
        dt = self.dtype
        T = toks.shape[0]
        n_rows = block_tables.shape[0]
        bt_tok = jnp.take(block_tables,
                          jnp.clip(row_of, 0, n_rows - 1), axis=0)
        x = w["wte"][toks].astype(dt)
        safe_pos = jnp.maximum(pos, 0)[:, None]           # [T, 1]
        new_kp, new_vp = [], []
        for i, W in enumerate(w["layers"]):
            q, k, v = self._qkv(W, x[:, None], T, 1)      # [T, 1, h, d]
            q = _rope(q, safe_pos, self.rope_base)
            k = _rope(k, safe_pos, self.rope_base)
            kpi, vpi = paged_kv_write_chunk(kpages[i], vpages[i], k, v,
                                            bt_tok, pos[:, None])
            att = _ragged_attn(q[:, 0], kpi, vpi, block_tables,
                               context_lens, query_lens, q_starts,
                               row_of, hd)
            x = x + _linear(att.reshape(T, nh * hd), W["o_w"])
            h2 = _rms(x, W["post_ln"], self.eps)
            m = jax.nn.silu(_linear(h2, W["gate_w"])) \
                * _linear(h2, W["up_w"])
            x = x + _linear(m, W["down_w"])
            new_kp.append(kpi)
            new_vp.append(vpi)
        return self.logits(w, x), tuple(new_kp), tuple(new_vp)


def _ragged_attn(q, kpages, vpages, block_tables, context_lens,
                 query_lens, q_starts, row_of, hd):
    """Ragged mixed prefill+decode attention over PAGED pools for the
    serving engine: q [T, nh, hd] flat token axis, per-row spans as in
    ragged_paged_attention. Off-TPU the Pallas kernel would run
    INTERPRETED per step — force the XLA composition there; on TPU let
    the wrapper pick."""
    from ..incubate.nn.pallas.paged_attention import ragged_paged_attention

    on_tpu = jax.default_backend() == "tpu"
    return ragged_paged_attention(
        q, kpages, vpages, block_tables, context_lens, query_lens,
        q_starts=q_starts, row_of=row_of, scale=hd ** -0.5,
        interpret=False, use_kernel=None if on_tpu else False)


def _paged_attn_chunk(q, kpages, vpages, block_tables, pos, hd):
    """Chunked causal attention over PAGED pools for the serving
    engine: q [b, g, nh, hd] at per-row positions pos [b, g] attends to
    page slots 0..pos (the chunk's own rows were written before this
    call, so within-chunk causality falls out of the per-query length).
    ``pos < 0`` rows (inactive slots / prefill padding) come back as
    zeros. Pools may be bf16 arrays or int8 {"q8","s"} dicts."""
    from ..incubate.nn.pallas.paged_attention import paged_attention

    b, g, nh, _ = q.shape
    pp = block_tables.shape[1]
    lens = jnp.maximum(pos + 1, 0).reshape(b * g)
    bt = jnp.broadcast_to(block_tables[:, None],
                          (b, g, pp)).reshape(b * g, pp)
    # off-TPU the Pallas kernel would run INTERPRETED per decode step —
    # force the XLA gather path there; on TPU let the wrapper pick
    on_tpu = jax.default_backend() == "tpu"
    out = paged_attention(q.reshape(b * g, nh, hd), kpages, vpages, bt,
                          lens, scale=hd ** -0.5, interpret=False,
                          use_kernel=None if on_tpu else False)
    return out.reshape(b, g, nh, hd)


def _chunk_sdpa(q, ck, cv, pos, hd):
    """Chunked causal attention over the cache for speculative verify:
    q [b, g, nh, hd] at per-row positions pos [b, g] attends to every
    cache slot t <= pos[b, g] (the chunk's own k/v were written before
    this call, so within-chunk causality falls out of the position
    mask). Handles bf16 and int8 cache representations like
    _masked_sdpa."""
    T = ck["q8"].shape[2] if isinstance(ck, dict) else ck.shape[2]
    mask = (jnp.arange(T)[None, None, :] <= pos[:, :, None])[:, None]
    if isinstance(ck, dict):
        sc = jnp.einsum("bghd,bhtd->bhgt", q, ck["q8"].astype(q.dtype),
                        preferred_element_type=jnp.float32)
        sc = sc * ck["s"][:, :, None, :] * (hd ** -0.5)
        sc = jnp.where(mask, sc, -1e30)
        w = jax.nn.softmax(sc, axis=-1)
        wv = (w * cv["s"][:, :, None, :]).astype(q.dtype)
        return jnp.einsum("bhgt,bhtd->bghd", wv, cv["q8"].astype(q.dtype))
    sc = jnp.einsum("bghd,bhtd->bhgt", q, ck,
                    preferred_element_type=jnp.float32) * (hd ** -0.5)
    sc = jnp.where(mask, sc, -1e30)
    w = jax.nn.softmax(sc, axis=-1).astype(q.dtype)
    return jnp.einsum("bhgt,bhtd->bghd", w, cv)


def _causal_prefill_attn(q, k, v, causal, hd, dt):
    """Full-prompt causal attention shared by the adapters' prefill."""
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                    preferred_element_type=jnp.float32) * (hd ** -0.5)
    sc = jnp.where(causal, sc, -1e30)
    wts = jax.nn.softmax(sc, axis=-1).astype(dt)
    att = jnp.einsum("bhqk,bkhd->bqhd", wts, v)
    b, plen = q.shape[0], q.shape[1]
    return att.reshape(b, plen, -1)


def _masked_sdpa(q, ck, cv, t_mask, hd):
    """Masked single-query attention over the cache — the
    masked_multihead_attention analog. q [b, nh, hd] is attended against
    the full cache [b, nh, T, hd] with invalid positions masked.

    int8 caches arrive as {"q8": [b,nh,T,hd] int8, "s": [b,nh,T] f32}.
    The dequant NEVER materializes a bf16 cache in HBM: the int8->bf16
    convert fuses into the dot operand read (same trick as the int8
    weight path), and the per-row scales — constant over the head dim —
    are applied on the score side (exact: scores_bht = s_bht * <q, q8>)
    and folded into the softmax weights for the V contraction."""
    if isinstance(ck, dict):
        scores = jnp.einsum("bhd,bhtd->bht", q, ck["q8"].astype(q.dtype),
                            preferred_element_type=jnp.float32)
        scores = scores * ck["s"] * (hd ** -0.5)
        scores = jnp.where(t_mask[None, None, :], scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        wv = (w * cv["s"]).astype(q.dtype)
        return jnp.einsum("bht,bhtd->bhd", wv, cv["q8"].astype(q.dtype))
    scores = jnp.einsum("bhd,bhtd->bht", q, ck,
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    scores = jnp.where(t_mask[None, None, :], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bht,bhtd->bhd", w, cv)


def _sample(logits, key, temperature, top_p):
    """Greedy / temperature / nucleus sampling over [b, V] logits.

    ``temperature`` and ``top_p`` accept Python scalars (whole-batch —
    the original path, kept bit-identical) OR per-row arrays [b] for
    mixed-request serving batches (serving/engine.py): each row scales
    by its own temperature, filters by its own nucleus (``top_p >= 1``
    keeps the full distribution), and rows with ``temperature == 0``
    take the greedy lane through a ``where`` select.
    """
    per_row_t = not isinstance(temperature, (int, float))
    per_row_p = top_p is not None and not isinstance(top_p, (int, float))
    if not per_row_t and temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if per_row_t:
        t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
        lg = logits.astype(jnp.float32) / t[..., None]
    else:
        lg = logits.astype(jnp.float32) / max(temperature, 1e-6)
    if top_p is not None:
        probs = jax.nn.softmax(lg, axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        tp = jnp.asarray(top_p, jnp.float32)[..., None] if per_row_p \
            else top_p
        keep = (cum - sorted_p) < tp
        filt = jnp.where(keep, sorted_p, 0.0)
        draw = jax.random.categorical(
            key, jnp.log(jnp.maximum(filt, 1e-30)), axis=-1)
        sampled = jnp.take_along_axis(sort_idx, draw[..., None],
                                      axis=-1)[..., 0].astype(jnp.int32)
    else:
        sampled = jax.random.categorical(key, lg, axis=-1) \
            .astype(jnp.int32)
    if per_row_t:
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jnp.where(jnp.asarray(temperature) == 0.0, greedy,
                         sampled)
    return sampled


def _check_window(ad, plen, max_new_tokens):
    total = plen + max_new_tokens
    if ad.max_positions is not None and total > ad.max_positions:
        raise ValueError(
            f"prompt length {plen} + max_new_tokens {max_new_tokens} = "
            f"{total} exceeds max_position_embeddings {ad.max_positions}; "
            "XLA would silently clamp position gathers past the window")
    return total


def _as_ids(input_ids):
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids), jnp.int32)
    return ids.astype(jnp.int32)


def _gen_cache(model):
    cache = getattr(model, "_gen_cache", None)
    if cache is None:
        cache = model._gen_cache = {}
    return cache


def _count_cache_lookup(miss: bool):
    """decode fn-cache hit/miss telemetry (generate / spec / beam share
    the counters — a miss is a fresh trace + XLA compile)."""
    if _obs.enabled():
        _obs.registry.counter(
            "decode.cache_miss" if miss else "decode.cache_hit").inc()
        if miss:
            _obs.flight_recorder.record("jit.cache_miss", site="decode")


def generate(model, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, weight_quant=None,
             kv_cache_quant=None, name=None):
    """Greedy / temperature / nucleus decoding, fully compiled, for any
    model exposing ``decode_adapter()``.

    Returns the generated token ids [batch, max_new_tokens] (prompt not
    included). ``temperature=0`` = greedy. Tokens after ``eos_token_id``
    are clamped to eos. ``weight_quant="int8"`` serves per-channel int8
    weights (half the HBM reads of the weight-bandwidth-bound decode);
    ``"int4"`` serves group-wise int4 blocks with an int8 lm_head
    (quarter-width weight stream — reference surface:
    nn/quant/quantized_linear.py weight_only_linear). Quantized copies
    are cached on the model — re-quantize by clearing
    ``model._gen_quant_w`` / ``_gen_quant_w4`` after a weight update.
    ``kv_cache_quant="int8"`` stores the KV cache as int8 with
    per-(position, head) scales computed at write time; the dequant is
    fused into the attention read (reference surface:
    masked_multihead_attention's cache_k/v_quant_scales args).
    """
    if kv_cache_quant not in (None, "int8"):
        raise ValueError("kv_cache_quant must be None or 'int8'")
    ad = model.decode_adapter()
    ids = _as_ids(input_ids)
    b, plen = ids.shape
    total = _check_window(ad, plen, max_new_tokens)
    # detach the weights from the adapter: the jitted fn's closure keeps
    # the adapter alive in _gen_cache, and pinning a stale copy of every
    # parameter array there would hold ~model-size HBM after updates
    w_now, ad.weights = ad.weights, None
    w_now = _resolve_weight_quant(model, w_now, weight_quant)

    kv_quant = kv_cache_quant == "int8"
    telemetry = _obs.enabled()

    def make_prefill():
        def run_prefill(weights, ids, key):
            weights = _activate_q4(weights)
            x, ck, cv = ad.prefill(weights, ids, total,
                                   kv_quant=kv_quant)
            lg0 = ad.logits(weights, x[:, -1])
            key, k0 = jax.random.split(key)
            tok0 = _sample(lg0, k0, temperature, top_p)
            alive = jnp.ones((b,), bool)
            if eos_token_id is not None:
                alive = alive & (tok0 != eos_token_id)
            return tok0, ck, cv, key, alive
        return run_prefill

    def make_decode():
        def run_decode(weights, tok0, ck, cv, key, alive):
            weights = _activate_q4(weights)

            def step(carry, _):
                tok, pos, ck, cv, key, alive = carry
                key, sk = jax.random.split(key)
                t_mask = jnp.arange(total) <= pos
                lg, ck, cv = ad.step(weights, tok, pos, ck, cv, t_mask)
                nxt = _sample(lg, sk, temperature, top_p)
                if eos_token_id is not None:
                    nxt = jnp.where(alive, nxt, eos_token_id)
                    alive = alive & (nxt != eos_token_id)
                return (nxt, pos + 1, ck, cv, key, alive), nxt

            carry = (tok0, jnp.int32(plen), ck, cv, key, alive)
            if max_new_tokens > 1:
                _, rest = jax.lax.scan(step, carry, None,
                                       length=max_new_tokens - 1)
                toks = jnp.concatenate([tok0[None], rest], axis=0)
            else:
                toks = tok0[None]
            return jnp.swapaxes(toks, 0, 1)   # [b, max_new]
        return run_decode

    cache = _gen_cache(model)
    # the telemetry flag is part of the key: the split two-dispatch path
    # and the fused one-dispatch path are distinct programs
    key_cache = ("sample", b, plen, max_new_tokens, temperature, top_p,
                 eos_token_id, weight_quant, kv_cache_quant, telemetry)
    entry = cache.get(key_cache)
    _count_cache_lookup(miss=entry is None)

    if not telemetry:
        # fused path: the WHOLE generation is one compiled dispatch
        if entry is None:
            run_prefill, run_decode = make_prefill(), make_decode()

            def run(weights, ids, key):
                tok0, ck, cv, key, alive = run_prefill(weights, ids, key)
                return run_decode(weights, tok0, ck, cv, key, alive)

            entry = cache[key_cache] = jax.jit(run)
        return Tensor(entry(w_now, ids, _rng.next_key()))

    # telemetry path: prefill and decode compile as SEPARATE dispatches
    # so the prefill/decode time split is an honest device-time split
    # (one extra host round-trip per generate call — accepted while
    # telemetry is on). AOT lower().compile() doubles as the
    # cost_analysis() source without compiling anything twice.
    key = _rng.next_key()
    with _obs.span("decode.generate", cat="decode",
                   args={"batch": b, "prompt": plen,
                         "max_new": max_new_tokens}):
        if entry is None:
            with _obs.span("jit.compile", cat="jit",
                           args={"site": "decode.prefill"}):
                pf = jax.jit(make_prefill()).lower(
                    w_now, ids, key).compile()
            _obs.record_cost_analysis("decode.prefill", pf)
        else:
            pf = entry[0]
        t0 = time.perf_counter()
        with _obs.span("decode.prefill", cat="decode",
                       args={"tokens": b * plen}):
            res = jax.block_until_ready(pf(w_now, ids, key))
        t_prefill = time.perf_counter() - t0
        if entry is None:
            with _obs.span("jit.compile", cat="jit",
                           args={"site": "decode.decode"}):
                df = jax.jit(make_decode()).lower(w_now, *res).compile()
            _obs.record_cost_analysis("decode.steps", df)
            cache[key_cache] = (pf, df)
        else:
            df = entry[1]
        t0 = time.perf_counter()
        with _obs.span("decode.decode", cat="decode",
                       args={"tokens": b * max_new_tokens}):
            out = jax.block_until_ready(df(w_now, *res))
        t_decode = time.perf_counter() - t0

    reg = _obs.registry
    reg.histogram("decode.prefill_time").observe(t_prefill)
    reg.histogram("decode.decode_time").observe(t_decode)
    reg.histogram("decode.token_latency").observe(
        t_decode / max_new_tokens)
    reg.counter("decode.prefill_tokens").inc(b * plen)
    reg.counter("decode.decode_tokens").inc(b * max_new_tokens)
    _obs.sample_device_memory()
    return Tensor(out)


def speculative_generate(model, input_ids, max_new_tokens: int = 32,
                         gamma: int = 4, draft_model=None,
                         draft_layers: Optional[int] = None,
                         eos_token_id: Optional[int] = None,
                         weight_quant=None, kv_cache_quant=None,
                         return_stats: bool = False):
    """Speculative greedy decoding, fully compiled (reference analog:
    the speculative serving tier — PaddleNLP's speculate_decoding and
    the fused_speculate_* ops feeding masked_multihead_attention with
    draft token chunks).

    A cheap draft proposes ``gamma`` tokens autoregressively; the target
    verifies all of them in ONE chunked forward pass (one weight read
    for up to gamma+1 emitted tokens — the weight-bandwidth win).
    Greedy acceptance makes the output IDENTICAL to ``generate(...,
    temperature=0)``: a proposal is accepted iff it equals the target's
    argmax given the accepted prefix, and the first mismatch is replaced
    by the target's own token. Acceptance is tracked PER ROW — batch
    rows advance at their own rate via per-row cache/output pointers.

    Draft choices: ``draft_model`` (a smaller CausalLM sharing the
    vocab) or ``draft_layers=k`` (self-speculative early exit: the
    target's first k blocks + its final norm/head, zero extra weights).

    TPU-native structure: the whole loop is one ``lax.while_loop`` on
    device — no host round-trip per iteration; out-of-window writes from
    finished rows are dropped by scatter mode="drop".
    """
    if (draft_model is None) == (draft_layers is None):
        raise ValueError("pass exactly one of draft_model / draft_layers")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    kv_quant = kv_cache_quant == "int8"
    if kv_cache_quant not in (None, "int8"):
        raise ValueError("kv_cache_quant must be None or 'int8'")

    ad = model.decode_adapter()
    ids = _as_ids(input_ids)
    b, plen = ids.shape
    # window slack: verify writes run up to gamma past the last commit
    total = _check_window(ad, plen, max_new_tokens + 2 * gamma + 2)

    w_now, ad.weights = ad.weights, None
    w_now = _resolve_weight_quant(model, w_now, weight_quant)

    if draft_model is not None:
        dad = draft_model.decode_adapter()
        if dad.vocab_size != ad.vocab_size:
            raise ValueError("draft vocab must match the target's")
        # the draft decodes over the same window — a shorter draft
        # position range would silently clamp wpe/rope gathers and
        # quietly zero the acceptance rate
        _check_window(dad, plen, max_new_tokens + 2 * gamma + 2)
        dw_now, dad.weights = dad.weights, None
        dw_now = _resolve_weight_quant(draft_model, dw_now, weight_quant)
        # structural key: the cached fn closes over dad's static config,
        # so two drafts may share it ONLY if every field the traced code
        # reads is identical (weights themselves are traced args)
        draft_key = ("model", type(dad).__name__, dad.num_layers,
                     dad.num_heads, dad.num_kv_heads, dad.head_dim,
                     dad.vocab_size, getattr(dad, "eps", None),
                     getattr(dad, "rope_base", None))
    else:
        if not 0 < draft_layers < ad.num_layers:
            raise ValueError("draft_layers must be in (0, num_layers)")
        dad = ad
        dw_now = dict(w_now)
        dw_now["layers"] = list(w_now["layers"])[:draft_layers]
        draft_key = ("self", draft_layers)

    cache = _gen_cache(model)
    key_cache = ("spec", b, plen, max_new_tokens, gamma, eos_token_id,
                 weight_quant, kv_cache_quant, draft_key)
    fn = cache.get(key_cache)
    _count_cache_lookup(miss=fn is None)
    if fn is None:
        W_out = max_new_tokens + gamma + 1

        def run(weights, dweights, ids):
            weights = _activate_q4(weights)
            dweights = _activate_q4(dweights)
            x, ck, cv = ad.prefill(weights, ids, total,
                                   kv_quant=kv_quant)
            _, dck, dcv = dad.prefill(dweights, ids, total,
                                      kv_quant=kv_quant)
            cur = jnp.argmax(ad.logits(weights, x[:, -1]),
                             axis=-1).astype(jnp.int32)       # [b]
            ptr = jnp.zeros((b,), jnp.int32)     # tokens committed to out
            ln = jnp.full((b,), plen, jnp.int32)  # committed cache length
            out = jnp.zeros((b, W_out), jnp.int32)
            n_iter = jnp.int32(0)
            n_acc = jnp.int32(0)

            def cond(carry):
                return jnp.min(carry[1]) < max_new_tokens

            def body(carry):
                out, ptr, cur, ln, ck, cv, dck, dcv, n_iter, n_acc = carry

                # -- draft proposes gamma tokens (one-token chunk steps)
                def dstep(c, j):
                    tok, dck, dcv = c
                    lg, dck, dcv = dad.chunk_step(
                        dweights, tok[:, None], (ln + j)[:, None],
                        dck, dcv)
                    nxt = jnp.argmax(lg[:, 0], -1).astype(jnp.int32)
                    return (nxt, dck, dcv), nxt

                (last_d, dck, dcv), props = jax.lax.scan(
                    dstep, (cur, dck, dcv), jnp.arange(gamma))
                props = jnp.swapaxes(props, 0, 1)        # [b, gamma]
                # write the final proposal's kv so the draft cache stays
                # complete when every proposal is accepted
                _, dck, dcv = dad.chunk_step(
                    dweights, last_d[:, None], (ln + gamma)[:, None],
                    dck, dcv)

                # -- target verifies the whole chunk in one pass
                chunk = jnp.concatenate([cur[:, None], props], 1)
                pos = ln[:, None] + jnp.arange(gamma + 1)[None, :]
                lg, ck, cv = ad.chunk_step(weights, chunk, pos, ck, cv)
                tgt = jnp.argmax(lg, -1).astype(jnp.int32)  # [b, g+1]

                # longest accepted prefix: props[:, j] must equal the
                # target token after chunk[:, :j+1]
                match = (props == tgt[:, :gamma]).astype(jnp.int32)
                acc = jnp.cumprod(match, axis=1).sum(axis=1)  # [b]

                # commit [cur, accepted...] — unaccepted tail slots get
                # overwritten next iteration (ptr only advances 1+acc)
                bidx = jnp.arange(b)[:, None]
                out = out.at[bidx, ptr[:, None]
                             + jnp.arange(gamma + 1)[None, :]].set(
                    chunk, mode="drop")
                new_cur = tgt[jnp.arange(b), acc]
                # stats only count rows still producing real tokens —
                # finished rows loop on a frozen cache (writes dropped)
                # and their phantom acceptances would skew the mean
                active = (ptr < max_new_tokens).astype(jnp.int32)
                return (out, ptr + 1 + acc, new_cur, ln + 1 + acc,
                        ck, cv, dck, dcv, n_iter + active.sum(),
                        n_acc + (acc * active).sum())

            carry = (out, ptr, cur, ln, ck, cv, dck, dcv, n_iter, n_acc)
            out, ptr, _, _, _, _, _, _, n_iter, n_acc = \
                jax.lax.while_loop(cond, body, carry)
            toks = out[:, :max_new_tokens]
            if eos_token_id is not None:
                seen = jnp.cumsum(toks == eos_token_id, 1) \
                    - (toks == eos_token_id)
                toks = jnp.where(seen > 0, eos_token_id, toks)
            return toks, n_iter, n_acc

        fn = jax.jit(run)
        cache[key_cache] = fn

    toks, n_iter, n_acc = fn(w_now, dw_now, ids)
    if _obs.enabled():
        it = max(int(n_iter), 1)
        reg = _obs.registry
        reg.gauge("decode.spec_acceptance_rate").set(
            float(n_acc) / (it * gamma))
        reg.gauge("decode.spec_tokens_per_pass").set(
            1.0 + float(n_acc) / it)
        reg.counter("decode.decode_tokens").inc(b * max_new_tokens)
    if return_stats:
        # n_iter = active (row, iteration) pairs; n_acc = accepted
        # proposals summed over those pairs
        it = max(int(n_iter), 1)
        stats = {"iterations": int(n_iter),
                 "mean_accepted": float(n_acc) / it,
                 "tokens_per_target_pass": 1.0 + float(n_acc) / it}
        return Tensor(toks), stats
    return Tensor(toks)


def _kv_rows(cache, idx_or_reps, gather):
    """Beam bookkeeping on either cache representation (plain array or
    quant dict — every leaf is batch-major): batch-axis gather
    (parent-beam reorder) or repeat (beam expansion)."""
    if gather:
        return jax.tree.map(lambda a: a[idx_or_reps], cache)
    return jax.tree.map(lambda a: jnp.repeat(a, idx_or_reps, axis=0),
                        cache)


def beam_search(model, input_ids, max_new_tokens: int = 32,
                num_beams: int = 4, length_penalty: float = 0.0,
                eos_token_id: Optional[int] = None, weight_quant=None,
                kv_cache_quant=None):
    """Compiled beam search over the fused decode path (reference: the
    gather_tree op exists exactly for this — beam parent pointers are
    resolved into sequences at the end, nn/functional/extend.py
    gather_tree). Supports the same serving quant tiers as generate()
    (weight_quant int8/int4, kv_cache_quant int8).

    Returns token ids [batch, max_new_tokens] of the best beam.
    """
    if kv_cache_quant not in (None, "int8"):
        raise ValueError("kv_cache_quant must be None or 'int8'")
    kv_quant = kv_cache_quant == "int8"
    ad = model.decode_adapter()
    ids = _as_ids(input_ids)
    b, plen = ids.shape
    total = _check_window(ad, plen, max_new_tokens)
    w_now, ad.weights = ad.weights, None  # see generate()
    w_now = _resolve_weight_quant(model, w_now, weight_quant)
    K = num_beams
    V = ad.vocab_size

    cache = _gen_cache(model)
    key_cache = ("beam", b, plen, max_new_tokens, K, length_penalty,
                 eos_token_id, weight_quant, kv_cache_quant)
    fn = cache.get(key_cache)
    _count_cache_lookup(miss=fn is None)
    if fn is None:

        def run(weights, ids):
            weights = _activate_q4(weights)
            x, ck, cv = ad.prefill(weights, ids, total,
                                   kv_quant=kv_quant)
            lg0 = jax.nn.log_softmax(
                ad.logits(weights, x[:, -1]).astype(jnp.float32), axis=-1)
            # seed the beams with the prompt's top-K continuations
            scores0, tok0 = jax.lax.top_k(lg0, K)      # [b, K]
            # expand caches to one row per beam: [L, b*K, T, ...]
            ck = tuple(_kv_rows(c, K, gather=False) for c in ck)
            cv = tuple(_kv_rows(c, K, gather=False) for c in cv)
            alive0 = jnp.ones((b, K), bool)
            if eos_token_id is not None:
                alive0 = tok0 != eos_token_id
            lens0 = jnp.ones((b, K), jnp.float32)  # seed token counts

            def step(carry, _):
                tok, pos, ck, cv, scores, alive, lens = carry
                t_mask = jnp.arange(total) <= pos
                lg, ck, cv = ad.step(weights, tok.reshape(b * K), pos,
                                     ck, cv, t_mask)
                logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
                logp = logp.reshape(b, K, V)
                # finished beams only extend with EOS at zero cost
                if eos_token_id is not None:
                    eos_only = jnp.full((V,), -jnp.inf).at[
                        eos_token_id].set(0.0)
                    logp = jnp.where(alive[..., None], logp,
                                     eos_only[None, None, :])
                cand = scores[..., None] + logp        # [b, K, V]
                flat = cand.reshape(b, K * V)
                new_scores, idx = jax.lax.top_k(flat, K)   # [b, K]
                parent = (idx // V).astype(jnp.int32)
                nxt = (idx % V).astype(jnp.int32)
                # reorder caches by parent beam (per batch row)
                gidx = (jnp.arange(b)[:, None] * K + parent) \
                    .reshape(b * K)
                ck = tuple(_kv_rows(c, gidx, gather=True) for c in ck)
                cv = tuple(_kv_rows(c, gidx, gather=True) for c in cv)
                alive = jnp.take_along_axis(alive, parent, axis=1)
                lens = jnp.take_along_axis(lens, parent, axis=1)
                # a live beam grows by its new token (incl. a fresh EOS)
                lens = lens + alive.astype(jnp.float32)
                if eos_token_id is not None:
                    alive = alive & (nxt != eos_token_id)
                return (nxt, pos + 1, ck, cv, new_scores, alive, lens), \
                    (nxt, parent)

            carry = (tok0, jnp.int32(plen), ck, cv, scores0, alive0,
                     lens0)
            if max_new_tokens > 1:
                carry, (toks, parents) = jax.lax.scan(
                    step, carry, None, length=max_new_tokens - 1)
                final_scores = carry[4]
                final_lens = carry[6]
                # [T, b, K] including the seeded first token (parent = own
                # beam index by construction of the seed)
                all_toks = jnp.concatenate([tok0[None], toks], axis=0)
                all_parents = jnp.concatenate(
                    [jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32),
                                      (1, b, K)), parents], axis=0)
            else:
                final_scores = scores0
                final_lens = lens0
                all_toks = tok0[None]
                all_parents = jnp.broadcast_to(
                    jnp.arange(K, dtype=jnp.int32), (1, b, K))
            # resolve parent pointers into sequences (gather_tree)
            from ..nn.functional.extend import gather_tree

            seqs = gather_tree(Tensor(all_toks),
                               Tensor(all_parents))._data  # [T, b, K]
            if length_penalty:
                # GNMT-style: each beam normalized by ITS OWN finished
                # length (frozen at EOS), not a shared constant
                final_scores = final_scores / (
                    final_lens ** length_penalty)
            best = jnp.argmax(final_scores, axis=1)      # [b]
            out = jnp.take_along_axis(
                seqs, best[None, :, None], axis=2)[..., 0]  # [T, b]
            return jnp.swapaxes(out, 0, 1)               # [b, T]

        fn = jax.jit(run)
        cache[key_cache] = fn

    return Tensor(fn(w_now, ids))
