"""Fused decode path: whole-generation compiled autoregressive decoding
(reference: the serving fusion tier paddle/phi/kernels/fusion/gpu/ —
fused_multi_transformer_kernel.cu, masked_multihead_attention_kernel.cu —
and PaddleNLP's generate loop; beam search reconstructs sequences with
gather_tree exactly like the reference's gather_tree op).

TPU-native design: instead of per-op fused CUDA kernels driven by a host
loop, the ENTIRE decode runs as one XLA program — prefill fills a
fixed-size KV cache, then ``lax.scan`` iterates single-token steps with
``dynamic_update_slice`` cache writes and masked single-query attention.
Zero host round-trips per token (the 97ms tunnel dispatch would otherwise
dwarf the ~µs of decode math); XLA fuses ln/rope/proj into the matmuls
the way fused_multi_transformer does by hand.

The engine is MODEL-GENERIC: each CausalLM exposes ``decode_adapter()``
returning a DecodeAdapter (weight extraction + pure-array embed / prefill
/ single-token block step / logits), and this module drives sampling
(greedy / temperature / top-p) and beam search over any adapter.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import random as _rng
from ..core.tensor import Tensor

__all__ = ["generate", "beam_search", "GPTDecodeAdapter",
           "LlamaDecodeAdapter"]


def _ln(x, w, b, eps):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + eps) * w.astype(jnp.float32)
            + b.astype(jnp.float32)).astype(x.dtype)


def _rms(x, w, eps):
    x32 = x.astype(jnp.float32)
    nrm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (nrm * w.astype(jnp.float32)).astype(x.dtype)


def _linear(x, w, b=None):
    if isinstance(w, dict) and "q8" in w:
        # weight-only int8: XLA fuses the int8->bf16 convert into the
        # matmul operand read, so HBM traffic halves vs bf16 weights —
        # decode is weight-bandwidth-bound, so this is ~2x tokens/s
        y = (x @ w["q8"].astype(x.dtype)) * w["s"].astype(x.dtype)
    else:
        y = x @ w
    return y if b is None else y + b


def _quantize_w(w):
    """Per-output-channel symmetric int8 for a [in, out] matmul weight."""
    s = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0,
                keepdims=True) / 127.0
    s = jnp.maximum(s, 1e-8)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127) \
        .astype(jnp.int8)
    return {"q8": q, "s": s}


_QUANT_SKIP = {"wte", "wpe"}  # embedding gathers stay full precision


def _quantize_tree(w, min_dim=256):
    """Walk an adapter weight pytree, replacing big 2D matmul weights with
    int8 quant dicts (reference analog: weight_only_linear /
    llm.int8 serving paths, phi/kernels/fusion/gpu/fused_weight_only_*)."""
    if isinstance(w, dict):
        out = {}
        for k, v in w.items():
            if k in _QUANT_SKIP:
                out[k] = v
            elif isinstance(v, (dict, list)):
                out[k] = _quantize_tree(v, min_dim)
            elif (hasattr(v, "ndim") and v is not None and v.ndim == 2
                    and min(v.shape) >= min_dim):
                out[k] = _quantize_w(v)
            else:
                out[k] = v
        return out
    if isinstance(w, list):
        return [_quantize_tree(v, min_dim) for v in w]
    return w


def _rope(x, pos, base):
    """Rotate [..., nh, hd] by absolute positions pos (int array
    broadcastable to x.shape[:-2])."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = pos.astype(jnp.float32)[..., None, None] * freqs  # [..., 1, half]
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], -1)
    return out.astype(x.dtype)


class DecodeAdapter:
    """Per-model weight-extraction + pure-array decode callbacks.

    Attributes: num_layers, num_kv_heads, head_dim, dtype, vocab_size,
    max_positions, weights (flat pytree of jax arrays).
    Methods (all pure over arrays, jit-safe):
      prefill(w, ids, total) -> (x [b, plen, h], ck, cv [L, b, total, kvh, hd])
      step(w, tok [b], pos, ck, cv, t_mask) -> (logits [b, V], ck, cv)
    """


class GPTDecodeAdapter(DecodeAdapter):
    """Learned-position GPT decoder (gpt.py GPTForCausalLM)."""

    def __init__(self, model):
        cfg = model.config
        self.num_layers = cfg.num_layers
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_heads
        self.head_dim = cfg.head_dim
        self.eps = cfg.layer_norm_eps
        self.vocab_size = cfg.vocab_size
        self.max_positions = getattr(cfg, "max_position_embeddings", None)
        g = model.gpt
        layers = []
        for blk in g.h:
            layers.append({
                "ln1_w": blk.ln_1.weight._data, "ln1_b": blk.ln_1.bias._data,
                "qkv_w": blk.attn.qkv_proj.weight._data,
                "qkv_b": (blk.attn.qkv_proj.bias._data
                          if blk.attn.qkv_proj.bias is not None else None),
                "out_w": blk.attn.out_proj.weight._data,
                "out_b": (blk.attn.out_proj.bias._data
                          if blk.attn.out_proj.bias is not None else None),
                "ln2_w": blk.ln_2.weight._data, "ln2_b": blk.ln_2.bias._data,
                "fc1_w": blk.mlp.fc1.weight._data,
                "fc1_b": (blk.mlp.fc1.bias._data
                          if blk.mlp.fc1.bias is not None else None),
                "fc2_w": blk.mlp.fc2.weight._data,
                "fc2_b": (blk.mlp.fc2.bias._data
                          if blk.mlp.fc2.bias is not None else None),
            })
        head = None if model.lm_head is None else model.lm_head.weight._data
        self.weights = {
            "wte": g.wte.weight._data, "wpe": g.wpe.weight._data,
            "lnf_w": g.ln_f.weight._data, "lnf_b": g.ln_f.bias._data,
            "layers": layers, "lm_head": head,
        }
        self.dtype = self.weights["wte"].dtype

    def logits(self, w, x):
        x = _ln(x, w["lnf_w"], w["lnf_b"], self.eps)
        head = w["lm_head"]
        if head is None:
            return x @ w["wte"].T
        return _linear(x, head)

    def prefill(self, w, ids, total):
        b, plen = ids.shape
        nh, hd, dt = self.num_heads, self.head_dim, self.dtype
        pos_ids = jnp.arange(plen)[None, :]
        x = (w["wte"][ids] + w["wpe"][pos_ids]).astype(dt)
        cks, cvs = [], []
        causal = jnp.tril(jnp.ones((plen, plen), bool))
        for W in w["layers"]:
            h1 = _ln(x, W["ln1_w"], W["ln1_b"], self.eps)
            qkv = _linear(h1, W["qkv_w"], W["qkv_b"]) \
                .reshape(b, plen, 3, nh, hd)
            q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
            ck = jnp.zeros((b, total, nh, hd), dt).at[:, :plen].set(k)
            cv = jnp.zeros((b, total, nh, hd), dt).at[:, :plen].set(v)
            att = _causal_prefill_attn(q, k, v, causal, hd, dt)
            x = x + _linear(att, W["out_w"], W["out_b"])
            h2 = _ln(x, W["ln2_w"], W["ln2_b"], self.eps)
            m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                            approximate=True)
            x = x + _linear(m, W["fc2_w"], W["fc2_b"])
            cks.append(ck)
            cvs.append(cv)
        return x, tuple(cks), tuple(cvs)

    def step(self, w, tok, pos, ck, cv, t_mask):
        nh, hd, dt = self.num_heads, self.head_dim, self.dtype
        b = tok.shape[0]
        x = (w["wte"][tok] + w["wpe"][pos]).astype(dt)
        new_ck, new_cv = [], []
        for i, W in enumerate(w["layers"]):
            h1 = _ln(x, W["ln1_w"], W["ln1_b"], self.eps)
            qkv = _linear(h1, W["qkv_w"], W["qkv_b"]).reshape(b, 3, nh, hd)
            q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
            cki = jax.lax.dynamic_update_slice(ck[i], k[:, None],
                                               (0, pos, 0, 0))
            cvi = jax.lax.dynamic_update_slice(cv[i], v[:, None],
                                               (0, pos, 0, 0))
            att = _masked_sdpa(q, cki, cvi, t_mask, hd)
            x = x + _linear(att.reshape(b, nh * hd),
                            W["out_w"], W["out_b"])
            h2 = _ln(x, W["ln2_w"], W["ln2_b"], self.eps)
            m = jax.nn.gelu(_linear(h2, W["fc1_w"], W["fc1_b"]),
                            approximate=True)
            x = x + _linear(m, W["fc2_w"], W["fc2_b"])
            new_ck.append(cki)
            new_cv.append(cvi)
        return self.logits(w, x), tuple(new_ck), tuple(new_cv)


class LlamaDecodeAdapter(DecodeAdapter):
    """RMSNorm + rope + GQA + SwiGLU decoder (llama.py LlamaForCausalLM)."""

    def __init__(self, model):
        cfg = model.config
        self.num_layers = cfg.num_layers
        self.num_heads = cfg.num_heads
        self.num_kv_heads = cfg.num_kv_heads
        self.head_dim = cfg.head_dim
        self.eps = cfg.rms_norm_eps
        self.rope_base = cfg.rope_base
        self.vocab_size = cfg.vocab_size
        self.max_positions = getattr(cfg, "max_position_embeddings", None)
        mdl = model.llama
        layers = []
        for blk in mdl.layers:
            layers.append({
                "in_ln": blk.input_layernorm.weight._data,
                "q_w": blk.self_attn.q_proj.weight._data,
                "k_w": blk.self_attn.k_proj.weight._data,
                "v_w": blk.self_attn.v_proj.weight._data,
                "o_w": blk.self_attn.o_proj.weight._data,
                "post_ln": blk.post_attention_layernorm.weight._data,
                "gate_w": blk.mlp.gate_proj.weight._data,
                "up_w": blk.mlp.up_proj.weight._data,
                "down_w": blk.mlp.down_proj.weight._data,
            })
        head = None if model.lm_head is None else model.lm_head.weight._data
        self.weights = {
            "wte": mdl.embed_tokens.weight._data,
            "norm": mdl.norm.weight._data,
            "layers": layers, "lm_head": head,
        }
        self.dtype = self.weights["wte"].dtype

    def logits(self, w, x):
        x = _rms(x, w["norm"], self.eps)
        head = w["lm_head"]
        if head is None:
            return x @ w["wte"].T
        return _linear(x, head)

    def _qkv(self, W, x, b, s):
        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        h1 = _rms(x, W["in_ln"], self.eps)
        q = _linear(h1, W["q_w"]).reshape(b, s, nh, hd)
        k = _linear(h1, W["k_w"]).reshape(b, s, kvh, hd)
        v = _linear(h1, W["v_w"]).reshape(b, s, kvh, hd)
        return q, k, v

    def prefill(self, w, ids, total):
        b, plen = ids.shape
        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        dt = self.dtype
        x = w["wte"][ids].astype(dt)
        pos = jnp.arange(plen)[None, :]
        cks, cvs = [], []
        causal = jnp.tril(jnp.ones((plen, plen), bool))
        rep = nh // kvh
        for W in w["layers"]:
            q, k, v = self._qkv(W, x, b, plen)
            q = _rope(q, pos, self.rope_base)
            k = _rope(k, pos, self.rope_base)
            ck = jnp.zeros((b, total, kvh, hd), dt).at[:, :plen].set(k)
            cv = jnp.zeros((b, total, kvh, hd), dt).at[:, :plen].set(v)
            kf = jnp.repeat(k, rep, axis=2) if rep > 1 else k
            vf = jnp.repeat(v, rep, axis=2) if rep > 1 else v
            att = _causal_prefill_attn(q, kf, vf, causal, hd, dt)
            x = x + _linear(att, W["o_w"])
            h2 = _rms(x, W["post_ln"], self.eps)
            m = jax.nn.silu(_linear(h2, W["gate_w"])) * _linear(h2, W["up_w"])
            x = x + _linear(m, W["down_w"])
            cks.append(ck)
            cvs.append(cv)
        return x, tuple(cks), tuple(cvs)

    def step(self, w, tok, pos, ck, cv, t_mask):
        nh, kvh, hd = self.num_heads, self.num_kv_heads, self.head_dim
        dt = self.dtype
        b = tok.shape[0]
        x = w["wte"][tok].astype(dt)
        rep = nh // kvh
        pos_b = jnp.broadcast_to(jnp.asarray(pos), (b, 1))
        new_ck, new_cv = [], []
        for i, W in enumerate(w["layers"]):
            q, k, v = self._qkv(W, x[:, None], b, 1)
            q = _rope(q, pos_b, self.rope_base)[:, 0]
            k = _rope(k, pos_b, self.rope_base)[:, 0]
            v = v[:, 0]
            cki = jax.lax.dynamic_update_slice(ck[i], k[:, None],
                                               (0, pos, 0, 0))
            cvi = jax.lax.dynamic_update_slice(cv[i], v[:, None],
                                               (0, pos, 0, 0))
            kf = jnp.repeat(cki, rep, axis=2) if rep > 1 else cki
            vf = jnp.repeat(cvi, rep, axis=2) if rep > 1 else cvi
            att = _masked_sdpa(q, kf, vf, t_mask, hd)
            x = x + _linear(att.reshape(b, nh * hd), W["o_w"])
            h2 = _rms(x, W["post_ln"], self.eps)
            m = jax.nn.silu(_linear(h2, W["gate_w"])) * _linear(h2, W["up_w"])
            x = x + _linear(m, W["down_w"])
            new_ck.append(cki)
            new_cv.append(cvi)
        return self.logits(w, x), tuple(new_ck), tuple(new_cv)


def _causal_prefill_attn(q, k, v, causal, hd, dt):
    """Full-prompt causal attention shared by the adapters' prefill."""
    sc = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                    preferred_element_type=jnp.float32) * (hd ** -0.5)
    sc = jnp.where(causal, sc, -1e30)
    wts = jax.nn.softmax(sc, axis=-1).astype(dt)
    att = jnp.einsum("bhqk,bkhd->bqhd", wts, v)
    b, plen = q.shape[0], q.shape[1]
    return att.reshape(b, plen, -1)


def _masked_sdpa(q, ck, cv, t_mask, hd):
    """Masked single-query attention over the cache — the
    masked_multihead_attention analog. q [b, nh, hd] is attended against
    the full cache [b, T, nh, hd] with invalid positions masked."""
    scores = jnp.einsum("bhd,bthd->bht", q, ck,
                        preferred_element_type=jnp.float32) * (hd ** -0.5)
    scores = jnp.where(t_mask[None, None, :], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bht,bthd->bhd", w, cv)


def _sample(logits, key, temperature, top_p):
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    lg = logits.astype(jnp.float32) / max(temperature, 1e-6)
    if top_p is not None:
        probs = jax.nn.softmax(lg, axis=-1)
        sort_idx = jnp.argsort(-probs, axis=-1)
        sorted_p = jnp.take_along_axis(probs, sort_idx, axis=-1)
        cum = jnp.cumsum(sorted_p, axis=-1)
        keep = (cum - sorted_p) < top_p
        filt = jnp.where(keep, sorted_p, 0.0)
        draw = jax.random.categorical(
            key, jnp.log(jnp.maximum(filt, 1e-30)), axis=-1)
        return jnp.take_along_axis(sort_idx, draw[..., None],
                                   axis=-1)[..., 0].astype(jnp.int32)
    return jax.random.categorical(key, lg, axis=-1).astype(jnp.int32)


def _check_window(ad, plen, max_new_tokens):
    total = plen + max_new_tokens
    if ad.max_positions is not None and total > ad.max_positions:
        raise ValueError(
            f"prompt length {plen} + max_new_tokens {max_new_tokens} = "
            f"{total} exceeds max_position_embeddings {ad.max_positions}; "
            "XLA would silently clamp position gathers past the window")
    return total


def _as_ids(input_ids):
    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(np.asarray(input_ids), jnp.int32)
    return ids.astype(jnp.int32)


def _gen_cache(model):
    cache = getattr(model, "_gen_cache", None)
    if cache is None:
        cache = model._gen_cache = {}
    return cache


def generate(model, input_ids, max_new_tokens: int = 32,
             temperature: float = 0.0, top_p: Optional[float] = None,
             eos_token_id: Optional[int] = None, weight_quant=None,
             name=None):
    """Greedy / temperature / nucleus decoding, fully compiled, for any
    model exposing ``decode_adapter()``.

    Returns the generated token ids [batch, max_new_tokens] (prompt not
    included). ``temperature=0`` = greedy. Tokens after ``eos_token_id``
    are clamped to eos. ``weight_quant="int8"`` serves per-channel int8
    weights (half the HBM reads of the weight-bandwidth-bound decode;
    quantized copies are cached on the model — re-quantize by clearing
    ``model._gen_quant_w`` after a weight update).
    """
    ad = model.decode_adapter()
    ids = _as_ids(input_ids)
    b, plen = ids.shape
    total = _check_window(ad, plen, max_new_tokens)
    # detach the weights from the adapter: the jitted fn's closure keeps
    # the adapter alive in _gen_cache, and pinning a stale copy of every
    # parameter array there would hold ~model-size HBM after updates
    w_now, ad.weights = ad.weights, None
    if weight_quant == "int8":
        qw = getattr(model, "_gen_quant_w", None)
        if qw is None:
            if w_now.get("lm_head") is None:
                w_now = dict(w_now)
                w_now["lm_head"] = w_now["wte"].T
            qw = model._gen_quant_w = jax.tree.map(
                lambda a: a, _quantize_tree(w_now))
        w_now = qw
    elif weight_quant is not None:
        raise ValueError("weight_quant must be None or 'int8'")

    cache = _gen_cache(model)
    key_cache = ("sample", b, plen, max_new_tokens, temperature, top_p,
                 eos_token_id, weight_quant)
    fn = cache.get(key_cache)
    if fn is None:

        def run(weights, ids, key):
            x, ck, cv = ad.prefill(weights, ids, total)
            lg0 = ad.logits(weights, x[:, -1])
            key, k0 = jax.random.split(key)
            tok0 = _sample(lg0, k0, temperature, top_p)

            def step(carry, _):
                tok, pos, ck, cv, key, alive = carry
                key, sk = jax.random.split(key)
                t_mask = jnp.arange(total) <= pos
                lg, ck, cv = ad.step(weights, tok, pos, ck, cv, t_mask)
                nxt = _sample(lg, sk, temperature, top_p)
                if eos_token_id is not None:
                    nxt = jnp.where(alive, nxt, eos_token_id)
                    alive = alive & (nxt != eos_token_id)
                return (nxt, pos + 1, ck, cv, key, alive), nxt

            alive = jnp.ones((b,), bool)
            if eos_token_id is not None:
                alive = alive & (tok0 != eos_token_id)
            carry = (tok0, jnp.int32(plen), ck, cv, key, alive)
            if max_new_tokens > 1:
                _, rest = jax.lax.scan(step, carry, None,
                                       length=max_new_tokens - 1)
                toks = jnp.concatenate([tok0[None], rest], axis=0)
            else:
                toks = tok0[None]
            return jnp.swapaxes(toks, 0, 1)   # [b, max_new]

        fn = jax.jit(run)
        cache[key_cache] = fn

    key = _rng.next_key()
    out = fn(w_now, ids, key)
    return Tensor(out)


def beam_search(model, input_ids, max_new_tokens: int = 32,
                num_beams: int = 4, length_penalty: float = 0.0,
                eos_token_id: Optional[int] = None):
    """Compiled beam search over the fused decode path (reference: the
    gather_tree op exists exactly for this — beam parent pointers are
    resolved into sequences at the end, nn/functional/extend.py
    gather_tree).

    Returns token ids [batch, max_new_tokens] of the best beam.
    """
    ad = model.decode_adapter()
    ids = _as_ids(input_ids)
    b, plen = ids.shape
    total = _check_window(ad, plen, max_new_tokens)
    w_now, ad.weights = ad.weights, None  # see generate()
    K = num_beams
    V = ad.vocab_size

    cache = _gen_cache(model)
    key_cache = ("beam", b, plen, max_new_tokens, K, length_penalty,
                 eos_token_id)
    fn = cache.get(key_cache)
    if fn is None:

        def run(weights, ids):
            x, ck, cv = ad.prefill(weights, ids, total)
            lg0 = jax.nn.log_softmax(
                ad.logits(weights, x[:, -1]).astype(jnp.float32), axis=-1)
            # seed the beams with the prompt's top-K continuations
            scores0, tok0 = jax.lax.top_k(lg0, K)      # [b, K]
            # expand caches to one row per beam: [L, b*K, T, ...]
            ck = tuple(jnp.repeat(c, K, axis=0) for c in ck)
            cv = tuple(jnp.repeat(c, K, axis=0) for c in cv)
            alive0 = jnp.ones((b, K), bool)
            if eos_token_id is not None:
                alive0 = tok0 != eos_token_id
            lens0 = jnp.ones((b, K), jnp.float32)  # seed token counts

            def step(carry, _):
                tok, pos, ck, cv, scores, alive, lens = carry
                t_mask = jnp.arange(total) <= pos
                lg, ck, cv = ad.step(weights, tok.reshape(b * K), pos,
                                     ck, cv, t_mask)
                logp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
                logp = logp.reshape(b, K, V)
                # finished beams only extend with EOS at zero cost
                if eos_token_id is not None:
                    eos_only = jnp.full((V,), -jnp.inf).at[
                        eos_token_id].set(0.0)
                    logp = jnp.where(alive[..., None], logp,
                                     eos_only[None, None, :])
                cand = scores[..., None] + logp        # [b, K, V]
                flat = cand.reshape(b, K * V)
                new_scores, idx = jax.lax.top_k(flat, K)   # [b, K]
                parent = (idx // V).astype(jnp.int32)
                nxt = (idx % V).astype(jnp.int32)
                # reorder caches by parent beam (per batch row)
                gidx = (jnp.arange(b)[:, None] * K + parent) \
                    .reshape(b * K)
                ck = tuple(c[gidx] for c in ck)
                cv = tuple(c[gidx] for c in cv)
                alive = jnp.take_along_axis(alive, parent, axis=1)
                lens = jnp.take_along_axis(lens, parent, axis=1)
                # a live beam grows by its new token (incl. a fresh EOS)
                lens = lens + alive.astype(jnp.float32)
                if eos_token_id is not None:
                    alive = alive & (nxt != eos_token_id)
                return (nxt, pos + 1, ck, cv, new_scores, alive, lens), \
                    (nxt, parent)

            carry = (tok0, jnp.int32(plen), ck, cv, scores0, alive0,
                     lens0)
            if max_new_tokens > 1:
                carry, (toks, parents) = jax.lax.scan(
                    step, carry, None, length=max_new_tokens - 1)
                final_scores = carry[4]
                final_lens = carry[6]
                # [T, b, K] including the seeded first token (parent = own
                # beam index by construction of the seed)
                all_toks = jnp.concatenate([tok0[None], toks], axis=0)
                all_parents = jnp.concatenate(
                    [jnp.broadcast_to(jnp.arange(K, dtype=jnp.int32),
                                      (1, b, K)), parents], axis=0)
            else:
                final_scores = scores0
                final_lens = lens0
                all_toks = tok0[None]
                all_parents = jnp.broadcast_to(
                    jnp.arange(K, dtype=jnp.int32), (1, b, K))
            # resolve parent pointers into sequences (gather_tree)
            from ..nn.functional.extend import gather_tree

            seqs = gather_tree(Tensor(all_toks),
                               Tensor(all_parents))._data  # [T, b, K]
            if length_penalty:
                # GNMT-style: each beam normalized by ITS OWN finished
                # length (frozen at EOS), not a shared constant
                final_scores = final_scores / (
                    final_lens ** length_penalty)
            best = jnp.argmax(final_scores, axis=1)      # [b]
            out = jnp.take_along_axis(
                seqs, best[None, :, None], axis=2)[..., 0]  # [T, b]
            return jnp.swapaxes(out, 0, 1)               # [b, T]

        fn = jax.jit(run)
        cache[key_cache] = fn

    return Tensor(fn(w_now, ids))
