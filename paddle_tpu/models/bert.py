"""BERT encoder family, TPU-first (BASELINE config #3: BERT-base
pretraining under sharding stage-2/3).

Reference analog: the BERT models PaddleNLP supplies on top of the
reference framework; in-repo the pretraining workload is exercised by
test/collective/fleet/dygraph_group_sharded_stage3.py. Sharding annotation
scheme matches models/gpt.py: Megatron column/row splits on "mp", data on
"dp"; GSPMD places collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.auto_parallel.constraint import annotate_param, shard_activation
from ..nn import functional as F
from ..ops._helpers import run_op

__all__ = ["BertConfig", "BertModel", "BertForPreTraining",
           "BertForSequenceClassification", "BertPretrainingCriterion",
           "bert_tiny", "bert_base", "bert_large"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    initializer_range: float = 0.02

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def bert_tiny(**kw) -> BertConfig:
    return BertConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                      num_heads=4, intermediate_size=256,
                      max_position_embeddings=128, **kw)


def bert_base(**kw) -> BertConfig:
    return BertConfig(**kw)


def bert_large(**kw) -> BertConfig:
    return BertConfig(hidden_size=1024, num_layers=24, num_heads=16,
                      intermediate_size=4096, **kw)


class BertEmbeddings(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.word_embeddings = nn.Embedding(
            config.vocab_size, config.hidden_size, weight_attr=init)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=init)
        self.token_type_embeddings = nn.Embedding(
            config.type_vocab_size, config.hidden_size, weight_attr=init)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout)
        annotate_param(self.word_embeddings.weight, ("mp", None))

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        b, s = input_ids.shape
        if position_ids is None:
            position_ids = Tensor(jnp.arange(s, dtype=jnp.int32)[None, :]
                                  + jnp.zeros((b, 1), dtype=jnp.int32))
        if token_type_ids is None:
            token_type_ids = Tensor(jnp.zeros((b, s), dtype=jnp.int32))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class BertSelfAttention(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.q_proj = nn.Linear(h, h, weight_attr=init)
        self.k_proj = nn.Linear(h, h, weight_attr=init)
        self.v_proj = nn.Linear(h, h, weight_attr=init)
        self.out_proj = nn.Linear(h, h, weight_attr=nn.initializer.Normal(
            0.0, config.initializer_range / math.sqrt(2 * config.num_layers)))
        for p in (self.q_proj.weight, self.k_proj.weight, self.v_proj.weight):
            annotate_param(p, (None, "mp"))
        annotate_param(self.out_proj.weight, ("mp", None))
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, x, attention_mask=None):
        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        q = self.q_proj(x).reshape([b, s, cfg.num_heads, cfg.head_dim])
        k = self.k_proj(x).reshape([b, s, cfg.num_heads, cfg.head_dim])
        v = self.v_proj(x).reshape([b, s, cfg.num_heads, cfg.head_dim])
        q = shard_activation(q, ("dp", None, "mp", None))
        out = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attention_mask, is_causal=False,
            dropout_p=cfg.attention_dropout if self.training else 0.0,
            training=self.training)
        out = out.reshape([b, s, cfg.hidden_size])
        return self.dropout(self.out_proj(out))


class BertLayer(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        h = config.hidden_size
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.attention = BertSelfAttention(config)
        self.ln1 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.linear1 = nn.Linear(h, config.intermediate_size,
                                 weight_attr=init)
        self.linear2 = nn.Linear(config.intermediate_size, h,
                                 weight_attr=init)
        annotate_param(self.linear1.weight, (None, "mp"))
        annotate_param(self.linear2.weight, ("mp", None))
        self.ln2 = nn.LayerNorm(h, epsilon=config.layer_norm_eps)
        self.dropout = nn.Dropout(config.hidden_dropout)

    def forward(self, x, attention_mask=None):
        # post-LN residual blocks (original BERT)
        x = self.ln1(x + self.attention(x, attention_mask))
        ff = self.linear2(F.gelu(self.linear1(x)))
        return self.ln2(x + self.dropout(ff))


class BertPooler(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, x):
        return F.tanh(self.dense(x[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig, with_pool: bool = True):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_layers)])
        self.pooler = BertPooler(config) if with_pool else None

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [b, s] key-padding mask -> additive [b, 1, 1, s]
            attention_mask = run_op(
                lambda m: (1.0 - m.astype(jnp.float32))[:, None, None, :]
                * jnp.finfo(jnp.float32).min,
                [attention_mask], name="bert_attn_mask")
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        x = shard_activation(x, ("dp", None, None))
        for layer in self.encoder:
            x = layer(x, attention_mask)
        if self.pooler is not None:
            return x, self.pooler(x)
        return x


class BertLMPredictionHead(nn.Layer):
    """MLM head: transform + decoder tied to word embeddings."""

    def __init__(self, config: BertConfig, embedding_weights):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.layer_norm = nn.LayerNorm(config.hidden_size,
                                       epsilon=config.layer_norm_eps)
        self.decoder_weight = embedding_weights  # [vocab, hidden] (tied)
        self.decoder_bias = self.create_parameter(
            [config.vocab_size], is_bias=True)

    def forward(self, x):
        x = self.layer_norm(F.gelu(self.transform(x)))
        logits = run_op(
            lambda a, w, bias: a @ w.T + bias,
            [x, self.decoder_weight, self.decoder_bias], name="mlm_decode")
        return logits


class BertForPreTraining(nn.Layer):
    """MLM + NSP pretraining model."""

    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.bert = BertModel(config, with_pool=True)
        self.cls = BertLMPredictionHead(
            config, self.bert.embeddings.word_embeddings.weight)
        self.nsp = nn.Linear(config.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        seq, pooled = self.bert(input_ids, token_type_ids, position_ids,
                                attention_mask)
        prediction_scores = self.cls(seq)
        seq_relationship = self.nsp(pooled)
        return prediction_scores, seq_relationship


class BertPretrainingCriterion(nn.Layer):
    """Masked-LM + next-sentence loss; masked positions marked by
    labels == ignore_index (-100)."""

    def __init__(self, vocab_size: int, ignore_index: int = -100):
        super().__init__()
        self.vocab_size = vocab_size
        self.ignore_index = ignore_index

    def forward(self, prediction_scores, seq_relationship, masked_lm_labels,
                next_sentence_labels=None):
        ii = self.ignore_index

        def mlm_loss(logits, labels):
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            safe = jnp.where(labels == ii, 0, labels)
            nll = -jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32),
                                       axis=-1)[..., 0]
            mask = (labels != ii).astype(jnp.float32)
            return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        loss = run_op(mlm_loss, [prediction_scores, masked_lm_labels],
                      name="mlm_loss")
        if next_sentence_labels is not None:
            nsp = F.cross_entropy(seq_relationship, next_sentence_labels)
            loss = loss + nsp.mean()
        return loss


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig, num_classes: int = 2):
        super().__init__()
        self.bert = BertModel(config, with_pool=True)
        self.dropout = nn.Dropout(config.hidden_dropout)
        self.classifier = nn.Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        return self.classifier(self.dropout(pooled))
