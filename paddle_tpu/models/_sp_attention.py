"""Sequence-parallel attention dispatch for the flagship models.

``sequence_parallel_mode`` on the model configs selects how attention
handles a seq-sharded ("sp") activation layout under jit:

- "gspmd" (default): leave it to GSPMD — the sharding annotations make
  XLA all-gather K/V over the sp axis.
- "ring": explicit ring attention (distributed.sequence_parallel) — K/V
  chunks rotate via collective-permute on ICI, O(S/P) memory.
- "ulysses": all-to-all head<->seq exchange, full-seq flash attention on
  heads/P heads per chip.

Falls back to the caller's default attention when no mesh with a
non-trivial "sp" axis is active (eager mode, single chip, decode).
"""
from __future__ import annotations

import functools

from ..core.tensor import Tensor
from ..ops._helpers import run_op

SP_AXIS = "sp"


def _active_sp_mesh():
    from ..distributed.auto_parallel.process_mesh import get_mesh

    pm = get_mesh()
    if pm is None:
        return None
    jmesh = pm.get_jax_mesh() if hasattr(pm, "get_jax_mesh") else pm
    if SP_AXIS not in jmesh.axis_names or jmesh.shape[SP_AXIS] <= 1:
        return None
    return jmesh


def sp_attention(q: Tensor, k: Tensor, v: Tensor, mode: str,
                 causal: bool) -> Tensor | None:
    """Ring/Ulysses attention over the active mesh's sp axis, or None if
    not applicable (caller then uses its default sdpa path)."""
    if mode not in ("ring", "ulysses") or not causal:
        return None
    jmesh = _active_sp_mesh()
    if jmesh is None:
        return None
    import jax
    from jax.sharding import PartitionSpec as P

    from ..distributed.sequence_parallel import (ring_attention,
                                                 ulysses_attention)

    names = jmesh.axis_names
    dp_ax = "dp" if "dp" in names else None
    mp_ax = "mp" if "mp" in names else None
    spec = P(dp_ax, SP_AXIS, mp_ax, None)
    inner = ring_attention if mode == "ring" else ulysses_attention
    fn = jax.shard_map(
        functools.partial(inner, axis_name=SP_AXIS, causal=True),
        mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return run_op(lambda qa, ka, va: fn(qa, ka, va), [q, k, v],
                  name=f"{mode}_attention")
