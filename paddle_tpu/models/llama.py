"""Llama-2 family decoder-only LM (GQA + RoPE + SwiGLU + RMSNorm), TPU-first.

Reference analog: the semi-auto Llama model the reference tests end-to-end
(test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py) and
BASELINE config #5 (Llama-2 7B, semi-auto parallel + recompute).

Same sharding-annotation scheme as models/gpt.py: Megatron column/row splits
on "mp", data on "dp", sequence on "sp"; GSPMD places the collectives.
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.auto_parallel.constraint import annotate_param, shard_activation
from ..incubate.nn.functional import fused_rotary_position_embedding
from ..nn import functional as F
from ..ops._helpers import run_op

__all__ = ["LlamaConfig", "LlamaModel", "LlamaForCausalLM", "llama_tiny",
           "llama2_7B"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: Optional[int] = None  # None -> MHA
    intermediate_size: Optional[int] = None  # None -> llama 8/3 rule
    max_position_embeddings: int = 4096
    rope_base: float = 10000.0
    rms_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = False
    recompute: bool = False
    # fused chunked lm_head+CE (same treatment as GPTConfig.lm_ce_chunks,
    # via paddle_tpu.fusion.chunked): >0 computes the training loss in
    # this many token chunks without materializing [tokens, vocab] logits
    lm_ce_chunks: int = 0
    # "gspmd" | "ring" | "ulysses" (see models/_sp_attention.py)
    sequence_parallel_mode: str = "gspmd"

    def __post_init__(self):
        if self.num_kv_heads is None:
            self.num_kv_heads = self.num_heads
        if self.intermediate_size is None:
            m = int(8 * self.hidden_size / 3)
            self.intermediate_size = 256 * ((m + 255) // 256)

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def llama_tiny(**kw) -> LlamaConfig:
    return LlamaConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                       num_heads=4, num_kv_heads=2,
                       max_position_embeddings=256, **kw)


def llama2_7B(**kw) -> LlamaConfig:
    return LlamaConfig(hidden_size=4096, num_layers=32, num_heads=32,
                       intermediate_size=11008, **kw)


class LlamaAttention(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h, hd = config.hidden_size, config.head_dim
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.q_proj = nn.Linear(h, config.num_heads * hd, weight_attr=init,
                                bias_attr=False)
        self.k_proj = nn.Linear(h, config.num_kv_heads * hd, weight_attr=init,
                                bias_attr=False)
        self.v_proj = nn.Linear(h, config.num_kv_heads * hd, weight_attr=init,
                                bias_attr=False)
        self.o_proj = nn.Linear(
            config.num_heads * hd, h, weight_attr=nn.initializer.Normal(
                0.0, config.initializer_range / math.sqrt(2 * config.num_layers)),
            bias_attr=False)
        for p in (self.q_proj.weight, self.k_proj.weight, self.v_proj.weight):
            annotate_param(p, (None, "mp"))
        annotate_param(self.o_proj.weight, ("mp", None))

    def forward(self, x, position_ids=None, cache=None):
        from .. import fusion

        cfg = self.config
        b, s = x.shape[0], x.shape[1]

        def _proj(lin, op, heads):
            # column-parallel projection through the decomposed-overlap
            # path when routed (overlap off -> verbatim serial linear)
            out = fusion.overlap_linear(x, lin.weight, lin.bias, op=op)
            if out is None:
                out = lin(x)
            return out.reshape([b, s, heads, cfg.head_dim])

        q = _proj(self.q_proj, "llama_q", cfg.num_heads)
        k = _proj(self.k_proj, "llama_k", cfg.num_kv_heads)
        v = _proj(self.v_proj, "llama_v", cfg.num_kv_heads)
        past = cache[0].shape[1] if cache is not None else 0
        if position_ids is None and past:
            # incremental decode: rotate by absolute position, not 0
            position_ids = Tensor(jnp.arange(past, past + s,
                                             dtype=jnp.int32)[None, :]
                                  + jnp.zeros((b, 1), dtype=jnp.int32))
        q, k, _ = fused_rotary_position_embedding(
            q, k, None, position_ids=position_ids,
            rotary_emb_base=cfg.rope_base)
        if cache is not None:
            from ..ops.manipulation import concat

            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            cache = (k, v)
        if cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads
            k = run_op(lambda a: jnp.repeat(a, rep, axis=2), [k], name="gqa_rep")
            v = run_op(lambda a: jnp.repeat(a, rep, axis=2), [v], name="gqa_rep")
        q = shard_activation(q, ("dp", "sp", "mp", None))
        from .gpt import _offset_causal_mask

        out = None
        if cache is None and s > 1:
            from ._sp_attention import sp_attention

            out = sp_attention(q, k, v, cfg.sequence_parallel_mode,
                               causal=True)
        if out is None:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=s > 1 and past == 0,
                attn_mask=_offset_causal_mask(s, past),
                training=self.training)
        out = out.reshape([b, s, cfg.num_heads * cfg.head_dim])
        # row-parallel projection: per-chunk partial-sum collectives ride
        # the GEMM loop instead of one psum after it
        proj = fusion.overlap_linear(out, self.o_proj.weight,
                                     self.o_proj.bias, op="llama_o_proj")
        out = proj if proj is not None else self.o_proj(out)
        if cache is not None:
            return out, cache
        return out


class LlamaMLP(nn.Layer):
    """SwiGLU (reference analog: incubate/nn/functional/swiglu.py)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, ffn = config.hidden_size, config.intermediate_size
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.gate_proj = nn.Linear(h, ffn, weight_attr=init, bias_attr=False)
        self.up_proj = nn.Linear(h, ffn, weight_attr=init, bias_attr=False)
        self.down_proj = nn.Linear(
            ffn, h, weight_attr=nn.initializer.Normal(
                0.0, config.initializer_range / math.sqrt(2 * config.num_layers)),
            bias_attr=False)
        annotate_param(self.gate_proj.weight, (None, "mp"))
        annotate_param(self.up_proj.weight, (None, "mp"))
        annotate_param(self.down_proj.weight, ("mp", None))

    def forward(self, x):
        from .. import fusion

        if fusion.route("swiglu"):
            # gate/up projections + silu gate as one traced region;
            # quantized matmuls when requested
            qm = fusion.quant_route("llama_mlp")
            h = fusion.swiglu_linear(x, self.gate_proj.weight,
                                     self.up_proj.weight,
                                     shard_axes=("dp", "sp", "mp"),
                                     quant_mode=qm)
            out = fusion.overlap_linear(h, self.down_proj.weight,
                                        op="llama_down_proj", quant_mode=qm)
            if out is not None:
                return out
            if qm != "off":
                return fusion.quantized_linear(h, self.down_proj.weight,
                                               mode=qm)
            return self.down_proj(h)
        g = self.gate_proj(x)
        u = self.up_proj(x)
        g = shard_activation(g, ("dp", "sp", "mp"))
        return self.down_proj(F.silu(g) * u)


class LlamaBlock(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = nn.RMSNorm(config.hidden_size,
                                          config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = nn.RMSNorm(config.hidden_size,
                                                   config.rms_norm_eps)
        self.mlp = LlamaMLP(config)
        self._recompute = config.recompute

    def _body(self, x, position_ids=None, cache=None):
        from .. import fusion

        if cache is None and fusion.route("add_rms_norm"):
            a = self.self_attn(self.input_layernorm(x),
                               position_ids=position_ids)
            # residual add + post-attention RMSNorm as one region; the
            # residual stream and the normed branch come out of the same
            # fp32 compute scope (one upcast, one downcast)
            ln = self.post_attention_layernorm
            h, x = fusion.add_rms_norm(a, x, ln.weight, ln._epsilon)
            x = x + self.mlp(h)
            x = shard_activation(x, ("dp", "sp", None))
            return x
        if cache is None:
            x = x + self.self_attn(self.input_layernorm(x),
                                   position_ids=position_ids)
        else:
            a, cache = self.self_attn(self.input_layernorm(x),
                                      position_ids=position_ids, cache=cache)
            x = x + a
        x = x + self.mlp(self.post_attention_layernorm(x))
        x = shard_activation(x, ("dp", "sp", None))
        return x if cache is None else (x, cache)

    def forward(self, x, position_ids=None, cache=None):
        if self._recompute and self.training and cache is None:
            import jax

            params = [p for _, p in self.named_parameters()]

            def fn(xa, *pa):
                from ..incubate.nn.functional.flash_attention import (
                    _entering_recompute)

                saved = [p._data for p in params]
                for p, a in zip(params, pa):
                    p._data = a
                try:
                    with _entering_recompute():
                        out = self._body(Tensor(xa, stop_gradient=False),
                                         position_ids=position_ids)
                finally:
                    for p, a in zip(params, saved):
                        p._data = a
                return out._data

            return run_op(jax.checkpoint(fn), [x] + params,
                          name="llama_block_rc")
        return self._body(x, position_ids=position_ids, cache=cache)


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size, weight_attr=init)
        annotate_param(self.embed_tokens.weight, ("mp", None))
        self.layers = nn.LayerList([LlamaBlock(config)
                                    for _ in range(config.num_layers)])
        self.norm = nn.RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, position_ids=None, caches=None):
        x = self.embed_tokens(input_ids)
        x = shard_activation(x, ("dp", "sp", None))
        new_caches = [] if caches is not None else None
        for i, block in enumerate(self.layers):
            if caches is not None:
                x, c = block(x, position_ids=position_ids, cache=caches[i])
                new_caches.append(c)
            else:
                x = block(x, position_ids=position_ids)
        x = self.norm(x)
        if caches is not None:
            return x, new_caches
        return x


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
            annotate_param(self.lm_head.weight, (None, "mp"))

    def forward(self, input_ids, position_ids=None, labels=None, caches=None):
        if caches is not None:
            x, new_caches = self.llama(input_ids, position_ids, caches=caches)
        else:
            x = self.llama(input_ids, position_ids)
        chunks = int(getattr(self.config, "lm_ce_chunks", 0) or 0)
        if labels is not None and chunks > 1 \
                and math.prod(x.shape[:-1]) % chunks == 0:
            from .. import fusion

            if fusion.route("lm_ce"):
                tied = self.lm_head is None
                w = self.llama.embed_tokens.weight if tied \
                    else self.lm_head.weight
                return fusion.lm_head_chunked_ce(x, w, labels, chunks,
                                                 transpose_weight=tied)
        if self.lm_head is not None:
            logits = self.lm_head(x)
        else:
            logits = run_op(lambda a, w: jnp.matmul(a, w.T),
                            [x, self.llama.embed_tokens.weight],
                            name="lm_head_tied")
        logits = shard_activation(logits, ("dp", "sp", "mp"))
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, logits.shape[-1]]),
                labels.reshape([-1]), reduction="mean")
            return loss
        if caches is not None:
            return logits, new_caches
        return logits

    def init_caches(self, batch_size: int):
        from ..ops.creation import zeros

        cfg = self.config
        return [(zeros([batch_size, 0, cfg.num_kv_heads, cfg.head_dim]),
                 zeros([batch_size, 0, cfg.num_kv_heads, cfg.head_dim]))
                for _ in range(cfg.num_layers)]

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_p=None, eos_token_id=None, weight_quant=None,
                 kv_cache_quant=None):
        """Fully-compiled autoregressive decoding via the model-generic
        fused decode engine (models/generation.py)."""
        from .generation import generate as _gen

        return _gen(self, input_ids, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_p=top_p,
                    eos_token_id=eos_token_id, weight_quant=weight_quant,
                    kv_cache_quant=kv_cache_quant)

    def beam_search(self, input_ids, max_new_tokens=32, num_beams=4,
                    length_penalty=0.0, eos_token_id=None,
                    weight_quant=None, kv_cache_quant=None):
        """Compiled beam search over the fused decode path (gather_tree
        backtrace). Returns the best beam's ids [b, max_new_tokens]."""
        from .generation import beam_search as _beam

        return _beam(self, input_ids, max_new_tokens=max_new_tokens,
                     num_beams=num_beams, length_penalty=length_penalty,
                     eos_token_id=eos_token_id, weight_quant=weight_quant,
                     kv_cache_quant=kv_cache_quant)

    def decode_adapter(self):
        """Weight-extraction protocol for the model-generic fused decode
        engine (models/generation.py)."""
        from .generation import LlamaDecodeAdapter

        return LlamaDecodeAdapter(self)
