"""Flagship model families (reference analogs: GPT-3/Llama configs used by
the reference's hybrid-parallel and semi-auto tests —
test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py and the
PaddleNLP GPT models the Fleet pipeline tests exercise).

All models are built from ``paddle_tpu.nn`` layers and carry mesh-axis
sharding annotations (dp/mp/sp) consumed by the jit train-step builder, so
the same model runs single-chip eager, jit single-chip, and jit SPMD over a
multi-chip mesh.
"""
from .gpt import (  # noqa: F401
    GPTConfig,
    GPTForCausalLM,
    GPTModel,
    GPTPretrainingCriterion,
    gpt3_13B,
    gpt3_125M,
    gpt3_1p3B,
    gpt3_6p7B,
    gpt_tiny,
)
from .llama import (  # noqa: F401
    LlamaConfig,
    LlamaForCausalLM,
    LlamaModel,
    llama2_7B,
    llama_tiny,
)
from .bert import (  # noqa: F401
    BertConfig,
    BertForPreTraining,
    BertForSequenceClassification,
    BertModel,
    BertPretrainingCriterion,
    bert_base,
    bert_large,
    bert_tiny,
)
from .generation import (  # noqa: F401
    beam_search,
    generate,
    speculative_generate,
)
