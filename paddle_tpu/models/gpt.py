"""GPT-3 family decoder-only LM, TPU-first.

Reference analogs: the GPT models driven by the reference's hybrid-parallel
tests (test/collective/fleet/hybrid_parallel_*; PaddleNLP GPT) and BASELINE
config #4 (GPT-3 1.3B/6.7B/13B, mp×pp×sharding 1F1B).

TPU-native design notes:
  - Megatron-style tensor parallel is expressed as *sharding annotations*
    (qkv/fc1 column-split on "mp", out/fc2 row-split on "mp", embedding
    vocab-split on "mp"); GSPMD inserts the all-reduces the reference does
    explicitly in fleet/layers/mpu/mp_layers.py:336,543.
  - Sequence parallel = activations sharded on "sp" along the seq dim
    (reference: fleet/utils/sequence_parallel_utils.py) — GSPMD turns the
    mp all-reduces into reduce-scatter/all-gather pairs automatically.
  - Attention runs through F.scaled_dot_product_attention which dispatches
    to the Pallas flash-attention kernel on TPU.
  - Everything is static-shape, bfloat16-friendly, and jit-traceable.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..distributed.auto_parallel.constraint import annotate_param, shard_activation
from ..nn import functional as F
import numpy as np

from ..ops._helpers import as_tensor, run_op, unwrap

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM",
           "GPTPretrainingCriterion", "gpt_tiny", "gpt3_125M", "gpt3_1p3B",
           "gpt3_6p7B", "gpt3_13B"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: Optional[int] = None  # default 4*hidden
    max_position_embeddings: int = 2048
    dropout: float = 0.0
    attention_dropout: float = 0.0
    layer_norm_eps: float = 1e-5
    initializer_range: float = 0.02
    tie_word_embeddings: bool = True
    use_bias: bool = True
    # recompute (reference: fleet/recompute) — rematerialize each block
    recompute: bool = False
    # selective remat: skip rematerialization on every k-th block (its
    # activations are saved instead). 1 = full per-block remat; 2 halves
    # the recompute FLOPs at the cost of saving every other block's
    # activations. The 6N-credited MFU ceiling with full remat is
    # 6/8 = 0.75 of hardware util — this knob buys back most of it.
    recompute_interval: int = 1
    # fused chunked lm_head+CE (reference analog: the fused softmax-CE
    # kernels under phi/kernels/fusion/): >0 computes the training loss in
    # this many token chunks under jax.checkpoint, never materializing the
    # full [tokens, vocab] logits (1.6GB at b16 s1024) nor its gradient
    lm_ce_chunks: int = 0
    # "gspmd" | "ring" | "ulysses" — how attention handles a seq-sharded
    # layout over the "sp" mesh axis (see models/_sp_attention.py)
    sequence_parallel_mode: str = "gspmd"
    # MoE: >0 replaces every block's MLP with a top-2 GShard mixture of
    # this many experts (expert weights sharded over the "ep" mesh axis;
    # GSPMD places the dispatch/combine all-to-alls — the jit analog of
    # incubate/distributed/models/moe, reference moe_layer.py:263)
    moe_num_experts: int = 0
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        if self.intermediate_size is None:
            self.intermediate_size = 4 * self.hidden_size

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def gpt_tiny(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                     num_heads=4, max_position_embeddings=256, **kw)


def gpt3_125M(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=768, num_layers=12, num_heads=12, **kw)


def gpt3_1p3B(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=2048, num_layers=24, num_heads=16, **kw)


def gpt3_6p7B(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=4096, num_layers=32, num_heads=32, **kw)


def gpt3_13B(**kw) -> GPTConfig:
    return GPTConfig(hidden_size=5120, num_layers=40, num_heads=40, **kw)


def _offset_causal_mask(q_len: int, past: int):
    """Bool mask [1,1,q,past+q] for chunked prefill (q>1 with a non-empty
    cache): query t may attend keys <= past+t. None when is_causal or the
    single-token decode path already gives the right semantics."""
    if q_len <= 1 or past == 0:
        return None
    kv = past + q_len
    qi = jnp.arange(q_len)[:, None]
    ki = jnp.arange(kv)[None, :]
    return Tensor((ki <= qi + past)[None, None])


class GPTAttention(nn.Layer):
    """Causal self-attention; qkv fused column-parallel, out row-parallel."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        h = config.hidden_size
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.qkv_proj = nn.Linear(
            h, 3 * h, weight_attr=init,
            bias_attr=None if config.use_bias else False)
        self.out_proj = nn.Linear(
            h, h, weight_attr=nn.initializer.Normal(
                0.0, config.initializer_range / math.sqrt(2 * config.num_layers)),
            bias_attr=None if config.use_bias else False)
        annotate_param(self.qkv_proj.weight, (None, "mp"))
        annotate_param(self.out_proj.weight, ("mp", None))
        if config.use_bias:
            annotate_param(self.qkv_proj.bias, ("mp",))
            annotate_param(self.out_proj.bias, (None,))

    def forward(self, x, cache=None):
        from .. import fusion

        cfg = self.config
        b, s = x.shape[0], x.shape[1]
        # column-parallel projection: decomposed chunks let the bwd
        # input-grad psum ride inside the GEMM loop (overlap off -> None)
        qkv = fusion.overlap_linear(x, self.qkv_proj.weight,
                                    self.qkv_proj.bias, op="gpt_qkv")
        if qkv is None:
            qkv = self.qkv_proj(x)  # [b, s, 3h]
        qkv = qkv.reshape([b, s, 3, cfg.num_heads, cfg.head_dim])
        q, k, v = (qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2])
        past = 0
        if cache is not None:
            from ..ops.manipulation import concat

            past = cache[0].shape[1]
            k = concat([cache[0], k], axis=1)
            v = concat([cache[1], v], axis=1)
            cache = (k, v)
        q = shard_activation(q, ("dp", "sp", "mp", None))
        out = None
        dropout_p = cfg.attention_dropout if self.training else 0.0
        if cache is None and s > 1 and dropout_p == 0.0:
            # ring/ulysses paths carry no dropout; keep gspmd semantics
            # when attention dropout is active
            from ._sp_attention import sp_attention

            out = sp_attention(q, k, v, cfg.sequence_parallel_mode,
                               causal=True)
        if out is None:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=s > 1 and past == 0,
                attn_mask=_offset_causal_mask(s, past),
                dropout_p=dropout_p,
                training=self.training)  # [b, s, heads, head_dim]
        out = out.reshape([b, s, cfg.num_heads * cfg.head_dim])
        # row-parallel projection: per-chunk partial-sum collectives ride
        # the GEMM loop instead of one psum after it
        proj = fusion.overlap_linear(out, self.out_proj.weight,
                                     self.out_proj.bias, op="gpt_out_proj")
        out = proj if proj is not None else self.out_proj(out)
        if cache is not None:
            return out, cache
        return out


class GPTMLP(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h, ffn = config.hidden_size, config.intermediate_size
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.fc1 = nn.Linear(h, ffn, weight_attr=init,
                             bias_attr=None if config.use_bias else False)
        self.fc2 = nn.Linear(
            ffn, h, weight_attr=nn.initializer.Normal(
                0.0, config.initializer_range / math.sqrt(2 * config.num_layers)),
            bias_attr=None if config.use_bias else False)
        annotate_param(self.fc1.weight, (None, "mp"))
        annotate_param(self.fc2.weight, ("mp", None))
        if config.use_bias:
            annotate_param(self.fc1.bias, ("mp",))
            annotate_param(self.fc2.bias, (None,))

    def forward(self, x):
        from .. import fusion

        if fusion.route("bias_gelu"):
            # fc1 + bias + gelu as one traced region (one tape node, one
            # XLA fusion candidate); quantized matmuls when requested
            qm = fusion.quant_route("gpt_mlp")
            h = fusion.linear_gelu(x, self.fc1.weight, self.fc1.bias,
                                   approximate=True,
                                   shard_axes=("dp", "sp", "mp"),
                                   quant_mode=qm)
            out = fusion.overlap_linear(h, self.fc2.weight, self.fc2.bias,
                                        op="gpt_fc2", quant_mode=qm)
            if out is not None:
                return out
            if qm != "off":
                return fusion.quantized_linear(h, self.fc2.weight,
                                               self.fc2.bias, mode=qm)
            return self.fc2(h)
        x = self.fc1(x)
        x = shard_activation(x, ("dp", "sp", "mp"))
        x = F.gelu(x, approximate=True)
        return self.fc2(x)


class GPTMoEMLP(nn.Layer):
    """jit/SPMD mixture-of-experts FFN: stacked expert weights [E, ...]
    sharded over the "ep" mesh axis; top-2 GShard capacity routing with
    one-hot einsum dispatch/combine (static shapes — GSPMD emits the
    expert all-to-alls on the mesh). Aux load-balance loss is exposed via
    ``last_aux_loss`` and summed into the LM loss by GPTForCausalLM.
    Reference analog: incubate/distributed/models/moe/moe_layer.py:263 +
    phi spmd rules moe_gate_dispatch.cc (here: GSPMD)."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        h, ffn = config.hidden_size, config.intermediate_size
        E = config.moe_num_experts
        self.config = config
        self.num_experts = E
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.gate_weight = self.create_parameter(
            [h, E], default_initializer=init)
        self.w1 = self.create_parameter([E, h, ffn],
                                        default_initializer=init)
        self.b1 = self.create_parameter([E, ffn], is_bias=True)
        self.w2 = self.create_parameter(
            [E, ffn, h], default_initializer=nn.initializer.Normal(
                0.0, config.initializer_range
                / math.sqrt(2 * config.num_layers)))
        self.b2 = self.create_parameter([E, h], is_bias=True)
        annotate_param(self.w1, ("ep", None, "mp"))
        annotate_param(self.b1, ("ep", "mp"))
        annotate_param(self.w2, ("ep", "mp", None))
        annotate_param(self.b2, ("ep", None))
        self.last_aux_loss = None

    def forward(self, x):
        from .. import fusion

        cfg = self.config
        b, s, d = x.shape[0], x.shape[1], x.shape[2]
        E = self.num_experts
        cap = max(4, int(cfg.moe_capacity_factor * b * s * 2 / E))

        if fusion.route("moe_dispatch"):
            # scatter/gather dispatch — no [S, E, C] one-hot tensors
            y, aux = fusion.fused_moe_mlp(x, self.gate_weight, self.w1,
                                          self.b1, self.w2, self.b2, E, cap)
            self.last_aux_loss = aux
            return y

        def fn(xa, gw, w1, b1, w2, b2):
            S = b * s
            xf = xa.reshape(S, d)
            gates = jax.nn.softmax(
                (xf @ gw).astype(jnp.float32), axis=-1)
            idx1 = jnp.argmax(gates, -1)
            m1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
            g1 = jnp.sum(gates * m1, -1)
            gates2 = gates * (1.0 - m1)
            idx2 = jnp.argmax(gates2, -1)
            m2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)
            g2 = jnp.sum(gates2 * m2, -1)
            aux = jnp.sum(jnp.mean(m1, 0) * jnp.mean(gates, 0)) * E

            pos1 = jnp.cumsum(m1, 0) * m1 - m1
            pos2 = (jnp.cumsum(m2, 0) - 1.0 + jnp.sum(m1, 0)[None]) * m2
            m1 = m1 * (pos1 < cap)
            m2 = m2 * (pos2 < cap)
            p1 = jnp.sum(pos1, -1).astype(jnp.int32)
            p2 = jnp.sum(pos2, -1).astype(jnp.int32)
            g1 = g1 * jnp.sum(m1, -1)
            g2 = g2 * jnp.sum(m2, -1)
            denom = jnp.where(g1 + g2 > 0, g1 + g2, 1.0)
            g1, g2 = g1 / denom, g2 / denom
            oh1 = jax.nn.one_hot(p1, cap, dtype=jnp.float32)
            oh2 = jax.nn.one_hot(p2, cap, dtype=jnp.float32)
            cw = (g1[:, None, None] * m1[:, :, None] * oh1[:, None, :]
                  + g2[:, None, None] * m2[:, :, None] * oh2[:, None, :])
            dm = (cw > 0).astype(xf.dtype)
            cw = cw.astype(xf.dtype)

            xe = jnp.einsum("sec,sm->ecm", dm, xf)
            h1 = jax.nn.gelu(
                jnp.einsum("ecm,emh->ech", xe, w1) + b1[:, None, :],
                approximate=True)
            ye = jnp.einsum("ech,ehm->ecm", h1, w2) + b2[:, None, :]
            y = jnp.einsum("sec,ecm->sm", cw, ye)
            return y.reshape(b, s, d), aux.astype(jnp.float32)

        y, aux = run_op(fn, [x, self.gate_weight, self.w1, self.b1,
                             self.w2, self.b2], name="moe_mlp")
        self.last_aux_loss = aux
        return y


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig, layer_idx: int = 0):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.mlp = (GPTMoEMLP(config) if config.moe_num_experts
                    else GPTMLP(config))
        self.dropout = nn.Dropout(config.dropout)
        interval = int(getattr(config, "recompute_interval", 1)) or 1
        # selective recompute: interval k>0 skips remat on every k-th
        # block; k<0 remats ONLY every (-k)-th block (saves the rest)
        if interval > 0:
            remat_this = interval == 1 or \
                layer_idx % interval != interval - 1
        else:
            remat_this = layer_idx % (-interval) == 0
        self._recompute = config.recompute and remat_this

    def _body(self, x, cache=None):
        from .. import fusion

        fused = cache is None and fusion.route("dropout_add")
        if cache is None:
            a = self.attn(self.ln_1(x))
            x = fusion.dropout_add(a, x, self.dropout.p, self.training) \
                if fused else x + self.dropout(a)
        else:
            a, cache = self.attn(self.ln_1(x), cache=cache)
            x = x + self.dropout(a)
        m = self.mlp(self.ln_2(x))
        x = fusion.dropout_add(m, x, self.dropout.p, self.training) \
            if fused else x + self.dropout(m)
        x = shard_activation(x, ("dp", "sp", None))
        return x if cache is None else (x, cache)

    def forward(self, x, cache=None):
        if self._recompute and self.training and cache is None:
            # jax.checkpoint = the reference's fleet/recompute/recompute.py:124
            import jax

            params = [p for _, p in self.named_parameters()]

            is_moe = isinstance(self.mlp, GPTMoEMLP)

            def fn(xa, *pa):
                from ..incubate.nn.functional.flash_attention import (
                    _entering_recompute)

                saved = [p._data for p in params]
                for p, a in zip(params, pa):
                    p._data = a
                try:
                    with _entering_recompute():
                        out = self._body(Tensor(xa, stop_gradient=False))
                finally:
                    for p, a in zip(params, saved):
                        p._data = a
                if is_moe:
                    # thread the aux loss out of the checkpointed graph —
                    # the inner-trace Tensor on last_aux_loss must not leak
                    return out._data, self.mlp.last_aux_loss._data
                return out._data

            outs = run_op(jax.checkpoint(fn), [x] + params,
                          name="gpt_block_rc")
            if is_moe:
                out, aux = outs
                self.mlp.last_aux_loss = aux
                return out
            return outs
        return self._body(x, cache=cache)


class GPTModel(nn.Layer):
    """Embeddings + N blocks + final LN."""

    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        init = nn.initializer.Normal(0.0, config.initializer_range)
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=init)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size, weight_attr=init)
        annotate_param(self.wte.weight, ("mp", None))
        annotate_param(self.wpe.weight, (None, None))
        self.drop = nn.Dropout(config.dropout)
        self.h = nn.LayerList([GPTBlock(config, layer_idx=i)
                               for i in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size, config.layer_norm_eps)

    def forward(self, input_ids, position_ids=None, caches=None):
        b, s = input_ids.shape[0], input_ids.shape[1]
        if position_ids is None:
            past = caches[0][0].shape[1] if caches is not None else 0
            position_ids = Tensor(
                jnp.arange(past, past + s, dtype=jnp.int32)[None, :]
                + jnp.zeros((b, 1), dtype=jnp.int32))
        x = self.wte(input_ids) + self.wpe(position_ids)
        x = self.drop(x)
        x = shard_activation(x, ("dp", "sp", None))
        new_caches = [] if caches is not None else None
        for i, block in enumerate(self.h):
            if caches is not None:
                x, c = block(x, cache=caches[i])
                new_caches.append(c)
            else:
                x = block(x)
        x = self.ln_f(x)
        if caches is not None:
            return x, new_caches
        return x


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)
            annotate_param(self.lm_head.weight, (None, "mp"))

    def forward(self, input_ids, position_ids=None, labels=None, caches=None):
        if caches is not None:
            x, new_caches = self.gpt(input_ids, position_ids, caches=caches)
        else:
            x = self.gpt(input_ids, position_ids)
        chunks = int(getattr(self.config, "lm_ce_chunks", 0) or 0)
        if labels is not None and chunks > 1 \
                and int(np.prod(x.shape[:-1])) % chunks == 0:
            loss = self._chunked_lm_ce(x, labels, chunks)
        else:
            if self.lm_head is not None:
                logits = self.lm_head(x)
            else:
                logits = run_op(lambda a, w: jnp.matmul(a, w.T),
                                [x, self.gpt.wte.weight],
                                name="lm_head_tied")
            logits = shard_activation(logits, ("dp", "sp", "mp"))
            if labels is None:
                if caches is not None:
                    return logits, new_caches
                return logits
            loss = GPTPretrainingCriterion()(logits, labels)
        if self.config.moe_num_experts:
            for blk in self.gpt.h:
                aux = getattr(blk.mlp, "last_aux_loss", None)
                if aux is not None:
                    loss = loss + aux * self.config.moe_aux_weight
        return loss

    def _chunked_lm_ce(self, x, labels, chunks, ignore_index=-100):
        """Fused lm_head + softmax-CE in token chunks: each chunk's
        [T/C, vocab] logits live only inside a jax.checkpoint scope
        (forward keeps per-chunk scalars; backward recomputes the chunk
        matmul). The TPU rendering of the reference's fused CE kernels
        (phi/kernels/fusion/) — the full logits tensor and its gradient
        never hit HBM."""
        import jax

        from .. import fusion

        tied = self.lm_head is None
        w = self.gpt.wte.weight if tied else self.lm_head.weight
        if fusion.route("lm_ce"):
            # shared chunked-epilogue path (fusion/chunked.py), also used
            # by the Llama head; mirrors F.cross_entropy op for op so the
            # loss is invariant to the chunk count
            return fusion.lm_head_chunked_ce(x, w, labels, chunks,
                                             transpose_weight=tied,
                                             ignore_index=ignore_index)
        lab = unwrap(as_tensor(labels)).reshape(-1)

        def fn(a, wa):
            h = a.shape[-1]
            t = math.prod(a.shape[:-1])
            xc = a.reshape(chunks, t // chunks, h)
            lc = lab.astype(jnp.int32).reshape(chunks, t // chunks)

            def chunk(args):
                xi, li = args
                logits = (xi @ (wa.T if tied else wa)).astype(jnp.float32)
                lse = jax.scipy.special.logsumexp(logits, axis=-1)
                valid = li != ignore_index
                safe = jnp.where(valid, li, 0)
                tgt = jnp.take_along_axis(
                    logits, safe[:, None], axis=-1)[:, 0]
                nll = jnp.where(valid, lse - tgt, 0.0)
                return nll.sum(), valid.sum()

            sums, counts = jax.lax.map(jax.checkpoint(chunk), (xc, lc))
            return sums.sum() / jnp.maximum(counts.sum(), 1).astype(
                jnp.float32)

        return run_op(fn, [x, w], name="fused_lm_ce")

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_p=None, eos_token_id=None, weight_quant=None,
                 kv_cache_quant=None):
        """Fully-compiled autoregressive decoding (fused decode path,
        models/generation.py — the fused_multi_transformer/masked-MHA
        serving analog). Returns new token ids [b, max_new_tokens]."""
        from .generation import generate as _gen

        return _gen(self, input_ids, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_p=top_p,
                    eos_token_id=eos_token_id, weight_quant=weight_quant,
                    kv_cache_quant=kv_cache_quant)

    def beam_search(self, input_ids, max_new_tokens=32, num_beams=4,
                    length_penalty=0.0, eos_token_id=None,
                    weight_quant=None, kv_cache_quant=None):
        """Compiled beam search over the fused decode path (gather_tree
        backtrace). Returns the best beam's ids [b, max_new_tokens]."""
        from .generation import beam_search as _beam

        return _beam(self, input_ids, max_new_tokens=max_new_tokens,
                     num_beams=num_beams, length_penalty=length_penalty,
                     eos_token_id=eos_token_id, weight_quant=weight_quant,
                     kv_cache_quant=kv_cache_quant)

    def decode_adapter(self):
        """Weight-extraction protocol for the model-generic fused decode
        engine (models/generation.py)."""
        from .generation import GPTDecodeAdapter

        return GPTDecodeAdapter(self)

    def init_caches(self, batch_size: int):
        from ..ops.creation import zeros

        cfg = self.config
        return [(zeros([batch_size, 0, cfg.num_heads, cfg.head_dim]),
                 zeros([batch_size, 0, cfg.num_heads, cfg.head_dim]))
                for _ in range(cfg.num_layers)]


class GPTPretrainingCriterion(nn.Layer):
    """Token-level cross entropy, mean over non-ignored positions. Labels
    must already be shifted (labels[t] = next token after input_ids[t]) —
    no shift happens here (reference analog: the GPT pretraining criterion
    in the Fleet tests, which also takes pre-shifted labels)."""

    def __init__(self, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        loss = F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]),
            labels.reshape([-1]),
            reduction="mean", ignore_index=self.ignore_index)
        return loss
