"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,
adamw,...}.py). Stateful eager step + pure functional update for jit."""
from __future__ import annotations

import jax.numpy as jnp

import numpy as np

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
           "Adamax", "RMSProp", "Lamb", "NAdam", "RAdam", "ASGD", "Rprop",
           "LBFGS"]


class SGD(Optimizer):
    def _append_optimize_op(self, p, grad):
        from ..core.selected_rows import SelectedRows

        if isinstance(grad, SelectedRows):
            if self._weight_decay:
                # dense SGD decays EVERY row each step; a rows-only decay
                # would silently diverge — densify to keep equivalence
                grad = grad.to_dense() + self._weight_decay * p._data
                p._data = (p._data - self._param_lr(p) * grad).astype(
                    p._data.dtype)
                return
            # row-sparse update: touch only the looked-up rows (reference:
            # phi/kernels/selected_rows/ sgd kernel)
            sr = grad.merged()
            p._data = _sgd_sparse_apply(
                p._data, sr.rows, sr.values,
                jnp.float32(self._param_lr(p)))
            return
        grad = self._decayed(p, grad)
        p._data = (p._data - self._param_lr(p) * grad).astype(p._data.dtype)

    def init_state(self, params):
        return {}

    def update(self, params, grads, state, lr=None):
        lr = lr if lr is not None else self.get_lr()
        wd = self._weight_decay or 0.0
        new = [p - lr * (g + wd * p) for p, g in zip(params, grads)]
        return new, state


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _append_optimize_op(self, p, grad):
        grad = self._decayed(p, grad)
        v = self._get_accumulator("velocity", p)
        v = self._momentum * v + grad
        if self._use_nesterov:
            upd = grad + self._momentum * v
        else:
            upd = v
        self._set_accumulator("velocity", p, v)
        p._data = (p._data - self._param_lr(p) * upd).astype(p._data.dtype)

    def init_state(self, params):
        return {"velocity": [jnp.zeros_like(p) for p in params]}

    def update(self, params, grads, state, lr=None):
        lr = lr if lr is not None else self.get_lr()
        wd = self._weight_decay or 0.0
        newv, newp = [], []
        for p, g, v in zip(params, grads, state["velocity"]):
            g = g + wd * p
            v = self._momentum * v + g
            upd = g + self._momentum * v if self._use_nesterov else v
            newv.append(v)
            newp.append(p - lr * upd)
        return newp, {"velocity": newv}


import functools

import jax


@functools.partial(jax.jit, donate_argnums=(0,))
def _sgd_sparse_apply(p, rows, vals, lr):
    """In-place (donated) row-sparse SGD: O(touched rows) — eager .at[]
    without donation would copy the whole table per step."""
    upd = lr * vals.astype(jnp.float32)
    return p.at[rows].add((-upd).astype(p.dtype), mode="drop")


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _adam_sparse_apply(p, m, v, rows, g32, t, lr, b1, b2, eps, wd_c, wd_d):
    """In-place (donated) lazy sparse Adam over the touched rows."""
    g32 = g32 + wd_c * p[rows].astype(jnp.float32)
    mr = b1 * m[rows] + (1 - b1) * g32
    vr = b2 * v[rows] + (1 - b2) * (g32 * g32)
    mhat = mr / (1 - b1 ** t)
    vhat = vr / (1 - b2 ** t)
    pr = p[rows].astype(jnp.float32)
    pr = pr * (1 - lr * wd_d)
    pr = pr - lr * mhat / (jnp.sqrt(vhat) + eps)
    return (p.at[rows].set(pr.astype(p.dtype), mode="drop"),
            m.at[rows].set(mr, mode="drop"),
            v.at[rows].set(vr, mode="drop"))


_QBLOCK = 256  # blockwise-quantization block size (8-bit moments)


def _q8_quantize(x, signed, key=None):
    """Blockwise 8-bit quantization with 4th-root companding (the
    dynamic-map idea of 8-bit Adam, Dettmers et al. 2022): per-256-elem
    fp32 absmax scale; codes resolve small magnitudes finely. With a PRNG
    key, rounding is STOCHASTIC (unbiased): a beta2=0.999 decay step is
    smaller than one code step, so round-to-nearest would ratchet the
    second moment upward forever — SR preserves the EMA in expectation.
    Returns (codes int8/uint8 [nb, B], absmax fp32 [nb, 1])."""
    import jax

    n = x.size
    nb = -(-n // _QBLOCK)
    xp = jnp.zeros((nb * _QBLOCK,), jnp.float32).at[:n].set(
        x.reshape(-1).astype(jnp.float32)).reshape(nb, _QBLOCK)
    ax = jnp.max(jnp.abs(xp), axis=1, keepdims=True)
    u = xp / jnp.maximum(ax, 1e-30)
    root = jnp.sqrt(jnp.sqrt(jnp.abs(u)))
    scale = 127.0 if signed else 255.0
    mag = scale * root
    if key is not None:
        noise = jax.random.uniform(key, mag.shape, jnp.float32)
        qmag = jnp.clip(jnp.floor(mag + noise), 0.0, scale)
    else:
        qmag = jnp.round(mag)
    if signed:
        q = (jnp.sign(u) * qmag).astype(jnp.int8)
    else:
        q = qmag.astype(jnp.uint8)
    return q, ax


def _q8_dequantize(q, ax, shape, signed):
    scale = 127.0 if signed else 255.0
    u = q.astype(jnp.float32) / scale
    x = jnp.sign(u) * (jnp.abs(u) ** 4) * ax
    n = int(np.prod(shape)) if shape else 1
    return x.reshape(-1)[:n].reshape(shape)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, moment_dtype="float32",
                 moment_quant=None, factored_v=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # lazy_mode: sparse (SelectedRows) grads update moments/params
        # only at touched rows (reference Adam lazy_mode semantics);
        # default False = dense-equivalent math
        self._lazy_mode = bool(lazy_mode)
        # moment storage dtype applies to the FIRST moment only: bf16's
        # ~0.4% ulp cannot represent a beta2=0.999 decay step (0.1%), so a
        # bf16 second moment would ratchet up after gradient spikes and
        # never decay — v always stays fp32; update math runs in fp32
        self._moment_dtype = jnp.dtype(moment_dtype)
        # "8bit": both moments stored blockwise-quantized (1 byte/elem +
        # fp32 absmax per 256) in the functional/jit path — 2.6GB instead
        # of 7.9GB on a 1.3B model. Update math stays fp32 (the 8-bit
        # Adam recipe). Eager step() keeps fp32 moments regardless.
        if moment_quant not in (None, "none", "8bit"):
            raise ValueError(f"moment_quant: unknown mode {moment_quant!r}")
        self._moment_quant = moment_quant if moment_quant != "none" else None
        # Adafactor-style factored second moment (Shazeer & Stern 2018):
        # for >=2-D params store row/col EMAs of g^2 instead of the full
        # matrix — v memory goes from O(rc) to O(r+c) with the published
        # quality of Adafactor-with-momentum. 1-D params keep full v.
        self._factored_v = bool(factored_v)
        if self._factored_v and self._moment_quant:
            raise ValueError("factored_v and moment_quant are exclusive")

    def _append_optimize_op(self, p, grad):
        from ..core.selected_rows import SelectedRows

        if isinstance(grad, SelectedRows):
            # dispatch BEFORE _decayed (dense arithmetic); coupled decay
            # folds into the sparse/dense update paths
            return self._adam_update(p, grad)
        grad = self._decayed(p, grad)
        self._adam_update(p, grad)

    def _adam_update(self, p, grad, decoupled_wd=0.0):
        from ..core.selected_rows import SelectedRows

        if isinstance(grad, SelectedRows):
            if getattr(self, "_lazy_mode", False):
                return self._adam_update_sparse(p, grad, decoupled_wd)
            # non-lazy (reference default): moments of ALL rows decay
            # every step — mathematically the dense update
            grad = grad.to_dense()
            if self._weight_decay:
                grad = grad + self._weight_decay * p._data
        f32 = jnp.float32
        m = self._get_accumulator("moment1", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        v = self._get_accumulator("moment2", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        t = self._get_accumulator("step", p, jnp.zeros((), f32)) + 1
        g32 = grad.astype(f32)
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * (g32 * g32)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        lr = self._param_lr(p)
        p32 = p._data.astype(f32)
        if decoupled_wd:
            p32 = p32 * (1 - lr * decoupled_wd)
        p32 = p32 - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)
        self._set_accumulator("step", p, t)
        p._data = p32.astype(p._data.dtype)

    def _adam_update_sparse(self, p, grad, decoupled_wd=0.0):
        """Lazy sparse Adam (reference: Adam lazy_mode + the
        selected_rows adam kernel): moments and the parameter are updated
        ONLY at the touched rows — update cost scales with the number of
        looked-up ids, not the vocabulary."""
        f32 = jnp.float32
        sr = grad.merged()
        m = self._get_accumulator("moment1", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        v = self._get_accumulator("moment2", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        t = self._get_accumulator("step", p, jnp.zeros((), f32)) + 1
        new_p, new_m, new_v = _adam_sparse_apply(
            p._data, m, v, sr.rows, sr.values.astype(f32), t,
            jnp.float32(self._param_lr(p)),
            jnp.float32(self._beta1), jnp.float32(self._beta2),
            jnp.float32(self._epsilon),
            jnp.float32(self._weight_decay or 0.0),
            jnp.float32(decoupled_wd))
        self._set_accumulator("moment1", p, new_m)
        self._set_accumulator("moment2", p, new_v)
        self._set_accumulator("step", p, t)
        p._data = new_p

    def init_state(self, params):
        md = getattr(self, "_moment_dtype", jnp.float32)
        if getattr(self, "_factored_v", False):
            state = {"m": [jnp.zeros_like(p, dtype=md) for p in params],
                     "v": [], "vr": [], "vc": [],
                     "t": jnp.zeros((), jnp.float32)}
            for p in params:
                if p.ndim >= 2:
                    r, c = p.shape[0], int(np.prod(p.shape[1:]))
                    state["v"].append(jnp.zeros((0,), jnp.float32))
                    state["vr"].append(jnp.zeros((r,), jnp.float32))
                    state["vc"].append(jnp.zeros((c,), jnp.float32))
                else:
                    state["v"].append(jnp.zeros_like(p, dtype=jnp.float32))
                    state["vr"].append(jnp.zeros((0,), jnp.float32))
                    state["vc"].append(jnp.zeros((0,), jnp.float32))
            return state
        if getattr(self, "_moment_quant", None) == "8bit":
            state = {"m": [], "m_ax": [], "v": [], "v_ax": [],
                     "t": jnp.zeros((), jnp.float32)}
            for p in params:
                mq, max_ = _q8_quantize(jnp.zeros_like(p, jnp.float32),
                                        signed=True)
                vq, vax = _q8_quantize(jnp.zeros_like(p, jnp.float32),
                                       signed=False)
                state["m"].append(mq)
                state["m_ax"].append(max_)
                state["v"].append(vq)
                state["v_ax"].append(vax)
            return state
        return {
            "m": [jnp.zeros_like(p, dtype=md) for p in params],
            "v": [jnp.zeros_like(p, dtype=jnp.float32) for p in params],
            "t": jnp.zeros((), jnp.float32),
        }

    def update(self, params, grads, state, lr=None):
        return self._functional_update(
            params, grads, state, lr,
            coupled_wd=self._weight_decay or 0.0, decoupled_wd=0.0)

    def _functional_update(self, params, grads, state, lr, coupled_wd,
                           decoupled_wd):
        """Shared quant-aware Adam/AdamW functional update."""
        lr = lr if lr is not None else self.get_lr()
        f32 = jnp.float32
        md = getattr(self, "_moment_dtype", jnp.float32)
        quant = getattr(self, "_moment_quant", None) == "8bit"
        factored = getattr(self, "_factored_v", False)
        t = state["t"] + 1
        nm, nv, np_ = [], [], []
        nmax, nvax = [], []
        nvr, nvc = [], []
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        for i, (p, g) in enumerate(zip(params, grads)):
            g32 = g.astype(f32) + coupled_wd * p.astype(f32)
            if quant:
                m = _q8_dequantize(state["m"][i], state["m_ax"][i],
                                   p.shape, signed=True)
                v = _q8_dequantize(state["v"][i], state["v_ax"][i],
                                   p.shape, signed=False)
            else:
                m = state["m"][i]
                v = state["v"][i]
            m = b1 * m.astype(f32) + (1 - b1) * g32
            mhat = m / (1 - b1 ** t)
            if factored and p.ndim >= 2:
                # Adafactor rank-1 second moment: V ~ outer(R, C)/sum(R)
                g2 = (g32 * g32).reshape(p.shape[0], -1) + 1e-30
                vr = b2 * state["vr"][i] + (1 - b2) * g2.sum(axis=1)
                vc = b2 * state["vc"][i] + (1 - b2) * g2.sum(axis=0)
                vhat2d = (vr[:, None] * vc[None, :]) / \
                    jnp.maximum(vr.sum(), 1e-30)
                vhat = (vhat2d / (1 - b2 ** t)).reshape(p.shape)
                nvr.append(vr)
                nvc.append(vc)
                nv.append(state["v"][i])
            else:
                v = b2 * v.astype(f32) + (1 - b2) * g32 * g32
                vhat = v / (1 - b2 ** t)
                if factored:
                    nvr.append(state["vr"][i])
                    nvc.append(state["vc"][i])
            p32 = p.astype(f32)
            if decoupled_wd:
                p32 = p32 * (1 - lr * decoupled_wd)
            out = p32 - lr * mhat / (jnp.sqrt(vhat) + eps)
            if quant:
                import jax

                kb = jax.random.fold_in(
                    jax.random.PRNGKey(0x51ab), t.astype(jnp.int32))
                k_m, k_v = jax.random.split(jax.random.fold_in(kb, i))
                mq, max_ = _q8_quantize(m, signed=True, key=k_m)
                vq, vax = _q8_quantize(v, signed=False, key=k_v)
                nm.append(mq)
                nmax.append(max_)
                nv.append(vq)
                nvax.append(vax)
            else:
                nm.append(m.astype(md))
                if not (factored and p.ndim >= 2):
                    nv.append(v)
            np_.append(out.astype(p.dtype))
        if quant:
            return np_, {"m": nm, "m_ax": nmax, "v": nv, "v_ax": nvax,
                         "t": t}
        if factored:
            return np_, {"m": nm, "v": nv, "vr": nvr, "vc": nvc, "t": t}
        return np_, {"m": nm, "v": nv, "t": t}


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False,
                 moment_dtype="float32", moment_quant=None,
                 factored_v=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         moment_dtype=moment_dtype, moment_quant=moment_quant,
                         factored_v=factored_v)
        self._coeff = float(weight_decay) if not hasattr(weight_decay, "_coeff") \
            else float(weight_decay._coeff)
        self._apply_decay_param_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _append_optimize_op(self, p, grad):
        wd = self._coeff
        if self._apply_decay_param_fun is not None and \
                not self._apply_decay_param_fun(p.name):
            wd = 0.0
        self._adam_update(p, grad, decoupled_wd=wd)

    def update(self, params, grads, state, lr=None):
        return self._functional_update(
            params, grads, state, lr, coupled_wd=0.0,
            decoupled_wd=self._coeff)


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _append_optimize_op(self, p, grad):
        grad = self._decayed(p, grad)
        acc = self._get_accumulator(
            "moment", p, jnp.full_like(p._data, self._init_acc))
        acc = acc + grad * grad
        self._set_accumulator("moment", p, acc)
        p._data = (p._data - self._param_lr(p) * grad
                   / (jnp.sqrt(acc) + self._epsilon)).astype(p._data.dtype)


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._epsilon = epsilon
        self._rho = rho

    def _append_optimize_op(self, p, grad):
        grad = self._decayed(p, grad)
        avg_sq = self._get_accumulator("avg_squared_grad", p)
        avg_upd = self._get_accumulator("avg_squared_update", p)
        avg_sq = self._rho * avg_sq + (1 - self._rho) * grad * grad
        upd = (jnp.sqrt(avg_upd + self._epsilon)
               / jnp.sqrt(avg_sq + self._epsilon)) * grad
        avg_upd = self._rho * avg_upd + (1 - self._rho) * upd * upd
        self._set_accumulator("avg_squared_grad", p, avg_sq)
        self._set_accumulator("avg_squared_update", p, avg_upd)
        p._data = (p._data - self._param_lr(p) * upd).astype(p._data.dtype)


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _append_optimize_op(self, p, grad):
        grad = self._decayed(p, grad)
        m = self._get_accumulator("moment", p)
        u = self._get_accumulator("inf_norm", p)
        t = self._get_accumulator("step", p, jnp.zeros((), jnp.float32)) + 1
        m = self._beta1 * m + (1 - self._beta1) * grad
        u = jnp.maximum(self._beta2 * u, jnp.abs(grad))
        lr = self._param_lr(p) / (1 - self._beta1 ** t)
        self._set_accumulator("moment", p, m)
        self._set_accumulator("inf_norm", p, u)
        self._set_accumulator("step", p, t)
        p._data = (p._data - lr * m / (u + self._epsilon)).astype(p._data.dtype)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _append_optimize_op(self, p, grad):
        grad = self._decayed(p, grad)
        ms = self._get_accumulator("mean_square", p)
        mom = self._get_accumulator("momentum", p)
        ms = self._rho * ms + (1 - self._rho) * grad * grad
        if self._centered:
            mg = self._get_accumulator("mean_grad", p)
            mg = self._rho * mg + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            self._set_accumulator("mean_grad", p, mg)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * mom + self._param_lr(p) * grad / denom
        self._set_accumulator("mean_square", p, ms)
        self._set_accumulator("momentum", p, mom)
        p._data = (p._data - mom).astype(p._data.dtype)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, p, grad):
        f32 = jnp.float32
        m = self._get_accumulator("moment1", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        v = self._get_accumulator("moment2", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        t = self._get_accumulator("step", p, jnp.zeros((), f32)) + 1
        g32 = grad.astype(f32)
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * g32 * g32
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        wd = self._wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd * p._data.astype(f32)
        w_norm = jnp.linalg.norm(p._data.astype(f32))
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)
        self._set_accumulator("step", p, t)
        p._data = (p._data.astype(f32) - self._param_lr(p) * trust * r
                   ).astype(p._data.dtype)


class NAdam(Adam):
    def _append_optimize_op(self, p, grad):
        f32 = jnp.float32
        grad = self._decayed(p, grad)
        m = self._get_accumulator("moment1", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        v = self._get_accumulator("moment2", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        t = self._get_accumulator("step", p, jnp.zeros((), f32)) + 1
        g32 = grad.astype(f32)
        m = self._beta1 * m + (1 - self._beta1) * g32
        v = self._beta2 * v + (1 - self._beta2) * g32 * g32
        mhat = (self._beta1 * m / (1 - self._beta1 ** (t + 1))
                + (1 - self._beta1) * g32 / (1 - self._beta1 ** t))
        vhat = v / (1 - self._beta2 ** t)
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)
        self._set_accumulator("step", p, t)
        p._data = (p._data.astype(f32) - self._param_lr(p) * mhat
                   / (jnp.sqrt(vhat) + self._epsilon)).astype(p._data.dtype)


class RAdam(Adam):
    def _append_optimize_op(self, p, grad):
        f32 = jnp.float32
        grad = self._decayed(p, grad)
        m = self._get_accumulator("moment1", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        v = self._get_accumulator("moment2", p,
                                  jnp.zeros_like(p._data, dtype=f32))
        t = self._get_accumulator("step", p, jnp.zeros((), f32)) + 1
        g32 = grad.astype(f32)
        b1, b2 = self._beta1, self._beta2
        m = b1 * m + (1 - b1) * g32
        v = b2 * v + (1 - b2) * g32 * g32
        mhat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1
        rho_t = rho_inf - 2 * t * b2 ** t / (1 - b2 ** t)
        lr = self._param_lr(p)

        def rectified():
            r = jnp.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                         / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            vhat = jnp.sqrt(v / (1 - b2 ** t))
            return lr * r * mhat / (vhat + self._epsilon)

        upd = jnp.where(rho_t > 5.0, rectified(), lr * mhat)
        self._set_accumulator("moment1", p, m)
        self._set_accumulator("moment2", p, v)
        self._set_accumulator("step", p, t)
        p._data = (p._data.astype(f32) - upd).astype(p._data.dtype)


class ASGD(SGD):
    pass


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _append_optimize_op(self, p, grad):
        prev = self._get_accumulator("prev_grad", p)
        lr = self._get_accumulator("lr", p,
                                   jnp.full_like(p._data, self.get_lr()))
        sign = jnp.sign(grad * prev)
        lr = jnp.where(sign > 0, jnp.minimum(lr * self._etas[1],
                                             self._lr_range[1]),
                       jnp.where(sign < 0,
                                 jnp.maximum(lr * self._etas[0],
                                             self._lr_range[0]), lr))
        g = jnp.where(sign < 0, 0.0, grad)
        self._set_accumulator("prev_grad", p, g)
        self._set_accumulator("lr", p, lr)
        p._data = (p._data - lr * jnp.sign(g)).astype(p._data.dtype)


class LBFGS(Optimizer):
    """L-BFGS with strong-wolfe-free backtracking (reference:
    python/paddle/optimizer/lbfgs.py). Requires a closure."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9, history_size=100,
                 line_search_fn=None, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._max_iter = max_iter
        self._history_size = history_size
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._s_list = []
        self._y_list = []
        self._prev_flat_grad = None

    def _flat_grad(self):
        return jnp.concatenate(
            [(p._grad._data if p._grad is not None
              else jnp.zeros_like(p._data)).reshape(-1)
             for p in self._parameter_list])

    def _apply_flat(self, upd):
        off = 0
        for p in self._parameter_list:
            n = p.size
            p._data = (p._data + upd[off:off + n].reshape(p._data.shape)
                       ).astype(p._data.dtype)
            off += n

    def step(self, closure=None):
        if closure is None:
            raise ValueError("LBFGS.step requires a closure")
        loss = closure()
        for _ in range(self._max_iter):
            g = self._flat_grad()
            if jnp.max(jnp.abs(g)) < self._tol_grad:
                break
            # two-loop recursion
            q = g
            alphas = []
            for s, y in zip(reversed(self._s_list), reversed(self._y_list)):
                rho = 1.0 / jnp.maximum(jnp.dot(y, s), 1e-10)
                a = rho * jnp.dot(s, q)
                q = q - a * y
                alphas.append((a, rho, s, y))
            if self._y_list:
                y_last, s_last = self._y_list[-1], self._s_list[-1]
                gamma = jnp.dot(s_last, y_last) / jnp.maximum(
                    jnp.dot(y_last, y_last), 1e-10)
                q = q * gamma
            for a, rho, s, y in reversed(alphas):
                b = rho * jnp.dot(y, q)
                q = q + (a - b) * s
            d = -q
            lr = self.get_lr()
            upd = lr * d
            self._apply_flat(upd)
            for p in self._parameter_list:
                p.clear_grad()
            new_loss = closure()
            new_g = self._flat_grad()
            s = upd
            y = new_g - g
            if jnp.dot(s, y) > 1e-10:
                self._s_list.append(s)
                self._y_list.append(y)
                if len(self._s_list) > self._history_size:
                    self._s_list.pop(0)
                    self._y_list.pop(0)
            if jnp.abs(new_loss._data - loss._data) < self._tol_change:
                loss = new_loss
                break
            loss = new_loss
        return loss
