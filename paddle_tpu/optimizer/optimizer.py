"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:127).

Stateful eager optimizers over jax arrays. Each optimizer also exposes a pure
functional ``update(params, grads, state) -> (new_params, new_state)`` used by
the jit/train-step path (and by sharded optimizers), so the same math runs
inside compiled programs.
"""
from __future__ import annotations

import warnings
from typing import Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer.layers import Parameter
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
        elif weight_decay is None:
            self._weight_decay = None
        else:  # L2Decay-like object with a coeff
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay, "coeff", 0.0)))
        self._accumulators: Dict[str, Dict[int, jnp.ndarray]] = {}
        self._global_step = 0
        # support param_groups: list of dicts with 'params' and overrides
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for grp in self._param_groups:
                flat.extend(grp["params"])
            self._parameter_list = flat

    # ----------------------------------------------------------- lr plumbing
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return float(self._learning_rate())
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError(
                "cannot set_lr when the lr is an LRScheduler; call "
                "scheduler.step() instead")
        self._learning_rate = value

    def _param_lr(self, p) -> float:
        base = self.get_lr()
        scale = getattr(p, "optimize_attr", {}).get("learning_rate", 1.0) \
            if hasattr(p, "optimize_attr") else 1.0
        return base * scale

    # ----------------------------------------------------------- accumulators
    def _get_accumulator(self, name: str, p: Tensor, init=None):
        slot = self._accumulators.setdefault(name, {})
        key = id(p)
        if key not in slot:
            slot[key] = jnp.zeros_like(p._data) if init is None else init
        return slot[key]

    def _set_accumulator(self, name: str, p: Tensor, value):
        self._accumulators[name][id(p)] = value

    # ----------------------------------------------------------- step
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer constructed without parameters")
        params_grads = [(p, p._grad) for p in params
                        if not p.stop_gradient and p._grad is not None]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        self._global_step += 1
        for p, g in params_grads:
            if g is None:
                continue
            self._append_optimize_op(p, g._data if isinstance(g, Tensor) else g)

    def _append_optimize_op(self, p, grad):
        raise NotImplementedError

    def clear_grad(self, set_to_zero: bool = False):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_gradient(set_to_zero)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..core import static_flags

        if static_flags.enabled:
            # static capture: register a train op; the Executor
            # differentiates the captured program and applies `update`
            from .. import static as _static

            _static.append_train_op(loss, self)
            return None, None
        loss.backward()
        self.step()
        return None, None

    # ----------------------------------------------------------- state dict
    def state_dict(self):
        out = {}
        id2name = {}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                id2name[id(p)] = p.name or f"param_{i}"
        for accname, slot in self._accumulators.items():
            for pid, arr in slot.items():
                pname = id2name.get(pid, str(pid))
                out[f"{pname}.{accname}"] = Tensor(arr)
        out["global_step"] = self._global_step
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        id2name = {}
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                id2name[f"{p.name or f'param_{i}'}"] = p
        self._global_step = int(state_dict.get("global_step", 0))
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate,
                                                       LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        # saved names may not match this process's auto-generated param
        # names (a fresh model continues the global name counter). Only
        # when name matching fails WHOLESALE fall back to positional
        # mapping (state-dict key order preserves the saving optimizer's
        # parameter order); mixing the two could cross-load moments on a
        # partial name overlap.
        saved_order: List[str] = []
        for key in state_dict:
            if key in ("global_step", "LR_Scheduler"):
                continue
            pname = key.rsplit(".", 1)[0]
            if pname not in saved_order:
                saved_order.append(pname)
        any_name_match = any(n in id2name for n in saved_order)
        by_pos = {}
        if not any_name_match and self._parameter_list and \
                len(saved_order) == len(self._parameter_list):
            # Positional fallback is only safe if EVERY saved slot agrees
            # in shape with its positional parameter — a key-order-
            # perturbing serializer would otherwise cross-load moments
            # between same-shaped params silently.
            candidate = dict(zip(saved_order, self._parameter_list))
            for key, val in state_dict.items():
                if key in ("global_step", "LR_Scheduler"):
                    continue
                pname = key.rsplit(".", 1)[0]
                p = candidate.get(pname)
                shp = tuple(val.shape) if hasattr(val, "shape") else \
                    np.shape(val)
                if p is not None and shp not in ((), tuple(p.shape)):
                    raise ValueError(
                        f"optimizer.set_state_dict: positional fallback "
                        f"rejected — saved state '{key}' shape "
                        f"{shp} does not match positional "
                        f"parameter shape {tuple(p.shape)}")
            by_pos = candidate
            warnings.warn(
                    "optimizer.set_state_dict: no saved state name matched "
                    "any parameter; falling back to POSITIONAL mapping "
                    "(saved key order -> parameter order). Verify the "
                    "checkpoint came from an identically-ordered model.")
        for key, val in state_dict.items():
            if key in ("global_step", "LR_Scheduler"):
                continue
            pname, accname = key.rsplit(".", 1)
            p = id2name.get(pname)
            if p is None:
                p = by_pos.get(pname)
            if p is None:
                continue
            arr = val._data if isinstance(val, Tensor) else jnp.asarray(val)
            if tuple(arr.shape) not in ((), tuple(p.shape)):
                raise ValueError(
                    f"optimizer state '{key}' shape {tuple(arr.shape)} "
                    f"does not match parameter shape {tuple(p.shape)}")
            self._accumulators.setdefault(accname, {})[id(p)] = arr

    # ----------------------------------------------------------- functional
    def init_state(self, params: List[jnp.ndarray]):
        """Pure functional state init for the jit path."""
        raise NotImplementedError

    def update(self, params, grads, state, lr=None):
        """Pure functional update for the jit path."""
        raise NotImplementedError

    def _decayed(self, p, grad):
        """Apply decoupled L2 weight decay is optimizer-specific; helper for
        coupled L2 (adds wd*param to grad)."""
        if self._weight_decay:
            return grad + self._weight_decay * p._data
        return grad
