"""Fused MoE dispatch/combine: gather -> expert matmul -> scatter as one
traced region, with no materialized one-hot dispatch tensors.

The fallback (models/gpt.py GPTMoEMLP) builds [S, E, C] combine/dispatch
one-hots and moves tokens with two einsums — O(S*E*C*M) memory traffic for
what is really a permutation. This region keeps the identical GShard top-2
routing arithmetic (same gates/argmax/cumsum-position/capacity math on
[S, E] tensors only), then dispatches by scatter-add into a dense
[E*cap, M] slot buffer and combines by two gathers. Dropped tokens route
to a trash row past the buffer (scatter) / a zero row (gather).

Every kept slot is written exactly once (positions are unique per expert
and second-choice positions start after the first-choice count), so the
dispatched expert inputs are bit-identical to the fallback's (its dispatch
einsum reduces one nonzero term against exact zeros). The combine is
tolerance-exact, not bitwise: the fallback's combine einsum accumulates
its two nonzero products through a fused-multiply-add chain (one rounding)
while the gather path rounds each product separately — a 1-ulp
difference. tests/test_fusion.py pins expert inputs and the aux
load-balance loss (same expression verbatim) bit-exact and the output
within float32 ulp tolerance.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import run_op
from ..ops._helpers import as_tensor

__all__ = ["fused_moe_mlp"]


def fused_moe_mlp(x, gate_weight, w1, b1, w2, b2, num_experts, capacity):
    """Fused top-2 GShard MoE FFN over [b, s, d] tokens.

    Returns ``(y, aux_loss)`` matching GPTMoEMLP's fallback region.
    """
    E, cap = int(num_experts), int(capacity)
    x = as_tensor(x)
    b, s, d = x.shape[0], x.shape[1], x.shape[2]

    def fn(xa, gw, w1a, b1a, w2a, b2a):
        S = b * s
        xf = xa.reshape(S, d)
        # --- routing: identical arithmetic to the fallback ([S, E] only)
        gates = jax.nn.softmax((xf @ gw).astype(jnp.float32), axis=-1)
        idx1 = jnp.argmax(gates, -1)
        m1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)
        g1 = jnp.sum(gates * m1, -1)
        gates2 = gates * (1.0 - m1)
        idx2 = jnp.argmax(gates2, -1)
        m2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)
        g2 = jnp.sum(gates2 * m2, -1)
        aux = jnp.sum(jnp.mean(m1, 0) * jnp.mean(gates, 0)) * E

        pos1 = jnp.cumsum(m1, 0) * m1 - m1
        pos2 = (jnp.cumsum(m2, 0) - 1.0 + jnp.sum(m1, 0)[None]) * m2
        m1 = m1 * (pos1 < cap)
        m2 = m2 * (pos2 < cap)
        p1 = jnp.sum(pos1, -1).astype(jnp.int32)
        p2 = jnp.sum(pos2, -1).astype(jnp.int32)
        g1 = g1 * jnp.sum(m1, -1)
        g2 = g2 * jnp.sum(m2, -1)
        denom = jnp.where(g1 + g2 > 0, g1 + g2, 1.0)
        g1, g2 = g1 / denom, g2 / denom

        # --- dispatch: scatter tokens into [E*cap (+1 trash), d] slots
        keep1 = jnp.sum(m1, -1) > 0
        keep2 = jnp.sum(m2, -1) > 0
        slot1 = jnp.where(keep1, idx1.astype(jnp.int32) * cap + p1, E * cap)
        slot2 = jnp.where(keep2, idx2.astype(jnp.int32) * cap + p2, E * cap)
        buf = jnp.zeros((E * cap + 1, d), xf.dtype)
        buf = buf.at[slot1].add(jnp.where(keep1[:, None], xf, 0))
        buf = buf.at[slot2].add(jnp.where(keep2[:, None], xf, 0))
        xe = buf[:E * cap].reshape(E, cap, d)

        # --- expert FFN: same grouped einsums as the fallback
        h1 = jax.nn.gelu(
            jnp.einsum("ecm,emh->ech", xe, w1a) + b1a[:, None, :],
            approximate=True)
        ye = jnp.einsum("ech,ehm->ecm", h1, w2a) + b2a[:, None, :]

        # --- combine: gather each token's two slots, weight, add
        yf = jnp.concatenate(
            [ye.reshape(E * cap, d), jnp.zeros((1, d), ye.dtype)], axis=0)
        c1 = g1.astype(xf.dtype)[:, None] * yf[slot1]
        c2 = g2.astype(xf.dtype)[:, None] * yf[slot2]
        y = c1 + c2
        return y.reshape(b, s, d), aux.astype(jnp.float32)

    return run_op(fn, [x, as_tensor(gate_weight), as_tensor(w1),
                       as_tensor(b1), as_tensor(w2), as_tensor(b2)],
                  name="fused_moe_mlp")
