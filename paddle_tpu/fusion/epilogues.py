"""Fused epilogue regions: bias+gelu(+dropout), residual-add+RMSNorm,
SwiGLU gate.

Each helper collapses what the fallback expresses as several ``run_op``
calls (matmul, add, activation, dropout, norm — each its own tape node)
into ONE traced region, so XLA's fusion pass sees the producing matmul and
its memory-bound epilogue together and the tape records one node instead of
three to five.

Exactness contract: every region composes exactly the same jax primitives
in the same order as the fallback composition it replaces (same key for
dropout, same fp32 upcast discipline for the norm via
``nn.functional.norm.rms_norm_ref``), so fused == fallback bit-for-bit.
tests/test_fusion.py enforces this per epilogue.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding

from ..core import random as _rng
from ..core.autograd import run_op
from ..distributed.auto_parallel.constraint import (_active_jax_mesh,
                                                    filtered_spec)
from ..ops._helpers import as_tensor
from .overlap_mm import region_mm

__all__ = ["linear_gelu", "dropout_add", "add_rms_norm", "swiglu_linear"]


def _shard_in_region(h, mesh, axes):
    """with_sharding_constraint inside a fused region — same placement the
    fallback gets from shard_activation() between its run_ops."""
    if mesh is None or axes is None:
        return h
    return jax.lax.with_sharding_constraint(
        h, NamedSharding(mesh, filtered_spec(axes, mesh)))


def linear_gelu(x, weight, bias=None, approximate=True, shard_axes=None,
                quant_mode="off"):
    """Fused y = gelu(x @ W (+ b)): the fc1 epilogue of a transformer MLP.

    Fallback composition this mirrors bitwise (quant_mode == "off"):
    ``F.gelu(shard_activation(F.linear(x, W, b), shard_axes))``.
    """
    mesh = _active_jax_mesh()
    ts = [as_tensor(x), as_tensor(weight)]
    has_bias = bias is not None
    if has_bias:
        ts.append(as_tensor(bias))

    def fn(a, w, *b):
        # overlap-aware producing GEMM (decomposed chunks when routed —
        # bitwise equal to the plain matmul/qmm either way)
        h = region_mm(a, w, quant_mode, op="linear_gelu")
        if has_bias:
            h = h + b[0]
        h = _shard_in_region(h, mesh, shard_axes)
        return jax.nn.gelu(h, approximate=approximate)

    return run_op(fn, ts, name="fused_linear_gelu",
                  attrs={"approximate": bool(approximate),
                         "quant": quant_mode})


def dropout_add(y, residual, p=0.0, training=True):
    """Fused residual + dropout(y) — the block-output epilogue.

    Mirrors ``residual + F.dropout(y, p, training=training)`` bitwise: same
    ``_rng.next_key()`` draw at the same sequence position, same bernoulli
    mask and upscale arithmetic, same add operand order.
    """
    y, residual = as_tensor(y), as_tensor(residual)
    if not training or p == 0.0:
        return run_op(lambda a, r: r + a, [y, residual],
                      name="fused_dropout_add", attrs={"p": 0.0})
    key = _rng.next_key()

    def fn(a, r):
        keep = jax.random.bernoulli(key, 1.0 - p, a.shape)
        dropped = jnp.where(keep, a / (1.0 - p), 0.0).astype(a.dtype)
        return r + dropped

    return run_op(fn, [y, residual], name="fused_dropout_add",
                  attrs={"p": p, "key": key})


def add_rms_norm(y, residual, weight, epsilon=1e-6):
    """Fused (residual + y) -> RMSNorm: returns (normed, new_residual).

    One region computes the residual stream update and its normalization
    with the canonical dtype contract (``rms_norm_ref``): the add happens
    in the residual dtype, the norm upcasts to fp32 ONCE, applies the
    scale in fp32, and downcasts ONCE. Mirrors
    ``F.rms_norm(residual + y, weight, epsilon=epsilon)`` bitwise.
    """
    from ..nn.functional.norm import rms_norm_ref

    ts = [as_tensor(y), as_tensor(residual), as_tensor(weight)]

    def fn(a, r, w):
        res = r + a
        normed = rms_norm_ref(res, weight=w, epsilon=epsilon,
                              axes=(res.ndim - 1,))
        return normed, res

    return run_op(fn, ts, name="fused_add_rms_norm",
                  attrs={"epsilon": epsilon})


def swiglu_linear(x, gate_weight, up_weight, shard_axes=None,
                  quant_mode="off"):
    """Fused SwiGLU gate: silu(x @ Wg) * (x @ Wu) in one region.

    Fallback composition this mirrors bitwise (quant_mode == "off"):
    ``F.silu(shard_activation(F.linear(x, Wg), shard_axes)) *
    F.linear(x, Wu)``.
    """
    mesh = _active_jax_mesh()
    ts = [as_tensor(x), as_tensor(gate_weight), as_tensor(up_weight)]

    def fn(a, wg, wu):
        # overlap-aware producing GEMMs (bitwise equal either way)
        g = region_mm(a, wg, quant_mode, op="swiglu")
        u = region_mm(a, wu, quant_mode, op="swiglu")
        g = _shard_in_region(g, mesh, shard_axes)
        return jax.nn.silu(g) * u

    return run_op(fn, ts, name="fused_swiglu", attrs={"quant": quant_mode})
