"""Chunked-epilogue treatment, generalized from models/gpt.py lm_ce_chunks.

``chunked_epilogue`` is the reusable shape: a per-token epilogue whose
intermediate (e.g. the [tokens, vocab] logits of an LM loss head) must
never be materialized in full runs under ``jax.lax.map(jax.checkpoint(.))``
over equal token chunks — the forward keeps only per-token outputs, the
backward rematerializes one chunk at a time.

``lm_head_chunked_ce`` is the LM-loss instantiation shared by the GPT and
Llama heads: it mirrors ``F.cross_entropy(logits, labels, "mean")`` op for
op (same ``log_softmax`` / take-along-axis / masked-mean arithmetic), so
the per-token NLLs — and therefore the loss — are invariant to the chunk
count, including the unchunked full-logits path.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.autograd import run_op
from ..ops._helpers import as_tensor, unwrap

__all__ = ["chunked_epilogue", "lm_head_chunked_ce"]


def chunked_epilogue(fn, arrays, chunks, checkpoint=True):
    """Apply per-token ``fn(*arrays)`` in ``chunks`` equal token chunks.

    Raw-jax helper for use inside traced regions. ``arrays`` share a
    leading token dim T divisible by ``chunks``; ``fn`` maps chunk slices
    to a pytree of per-token outputs (leading dim = chunk length), which
    are re-flattened to the full token dim. ``chunks <= 1`` calls ``fn``
    once over the full arrays — the unchunked reference the chunked paths
    are property-tested against.
    """
    arrays = tuple(arrays)
    if chunks <= 1:
        return fn(*arrays)
    t = arrays[0].shape[0]
    if t % chunks:
        raise ValueError(f"token dim {t} not divisible by chunks={chunks}")
    split = tuple(a.reshape((chunks, t // chunks) + a.shape[1:])
                  for a in arrays)
    body = (lambda xs: fn(*xs))
    if checkpoint:
        body = jax.checkpoint(body)
    outs = jax.lax.map(body, split)
    return jax.tree_util.tree_map(
        lambda o: o.reshape((o.shape[0] * o.shape[1],) + o.shape[2:]), outs)


def lm_head_chunked_ce(x, weight, labels, chunks, transpose_weight,
                       ignore_index=-100):
    """Fused lm_head + softmax-CE over token chunks (Tensor-level).

    ``x``: hidden states [..., h]; ``weight``: lm-head weight, used as
    ``x @ W.T`` when ``transpose_weight`` (tied embeddings, [vocab, h])
    else ``x @ W`` ([h, vocab]). Loss = mean NLL over non-ignored tokens,
    with one canonical global reduction so the value is independent of the
    chunk count.
    """
    x = as_tensor(x)
    lab = unwrap(as_tensor(labels)).reshape(-1)

    def fn(a, wa):
        h = a.shape[-1]
        t = math.prod(a.shape[:-1])
        xt = a.reshape(t, h)
        lc = lab.astype(jnp.int32)

        def per_token(xi, li):
            logits = (xi @ (wa.T if transpose_weight else wa)).astype(
                jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            valid = li != ignore_index
            safe = jnp.where(valid, li, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, -1), axis=-1).squeeze(-1)
            nll = -jnp.where(valid, picked, 0.0)
            return nll, valid

        nll, valid = chunked_epilogue(per_token, (xt, lc), chunks)
        denom = jnp.sum(valid.astype(nll.dtype))
        return jnp.sum(nll) / jnp.maximum(denom, 1.0)

    return run_op(fn, [x, as_tensor(weight)], name="fused_lm_ce",
                  attrs={"chunks": int(chunks)})
