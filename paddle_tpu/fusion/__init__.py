"""Fusion-aware rewrite layer over the jit/train_step pipeline.

The MFU story (ROADMAP "operator-fusion pass", Neptune arxiv 2510.08726):
after the matmuls are placed well, what keeps the chip idle is memory-bound
epilogue traffic — bias+gelu between the two MLP GEMMs, the residual-add
feeding every RMSNorm, the SwiGLU gate, the one-hot MoE dispatch einsums,
and the [tokens, vocab] logits of the LM loss. This package rewrites those
call sites into single traced regions (one ``run_op`` each) so XLA sees the
producing matmul and its epilogue as one fusion candidate, and adds an
int8/fp8 quantized-matmul hot path for the MLP blocks.

Knobs (read at trace time, captured per train-step build):

  - ``PADDLE_TPU_FUSION=auto|on|off`` — ``auto`` (default) behaves as
    ``on``. ``off`` routes every call site through the original unfused
    composition, restoring pre-fusion numerics byte-for-byte.
  - ``PADDLE_TPU_MM_QUANT=off|int8|fp8`` — quantized matmul for the MLP
    blocks (per-channel weight scales, per-token activation scales,
    straight-through full-precision gradients). Only consulted when
    fusion is enabled; never applied to attention or the LM head.
  - ``PADDLE_TPU_TP_OVERLAP=auto|on|pallas|off`` — decomposed
    computation–collective overlap for sharded matmuls (see
    :mod:`.overlap_mm`); its chunk count rides on
    ``PADDLE_TPU_TP_OVERLAP_CHUNKS``.

Bit-exactness contract: every fused epilogue in ``epilogues`` and the
chunked LM-CE path compose exactly the same jax ops in the same order as
their fallback, so fused == fallback bitwise (asserted by
tests/test_fusion.py). The fused MoE dispatch and the quantized matmul
path are tolerance-bound, not bitwise (see their module docs).
"""
from __future__ import annotations

import contextlib
import contextvars

from ..config import knobs
from . import chunked, epilogues, moe, overlap_mm, quant  # noqa: F401
from .chunked import chunked_epilogue, lm_head_chunked_ce
from .epilogues import add_rms_norm, dropout_add, linear_gelu, swiglu_linear
from .moe import fused_moe_mlp
from .overlap_mm import (all_gather_matmul, matmul_reduce_scatter,
                         overlap_linear)
from .quant import quantized_linear

__all__ = [
    "mode", "enabled", "mm_quant", "override", "route",
    "chunked_epilogue", "lm_head_chunked_ce",
    "add_rms_norm", "dropout_add", "linear_gelu", "swiglu_linear",
    "fused_moe_mlp", "quantized_linear",
    "all_gather_matmul", "matmul_reduce_scatter", "overlap_linear",
]

_FUSION_MODES = ("auto", "on", "off")
_QUANT_MODES = ("off", "int8", "fp8")

# Per-context override so a train-step build can pin the mode for the whole
# trace (distributed/auto_parallel/engine.py captures it at build time, the
# same way health/amp knobs are captured) and tests can force either path.
_forced: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_fusion_forced", default=(None, None))


def mode() -> str:
    """Resolved fusion mode: "on" or "off" ("auto" resolves to "on")."""
    forced = _forced.get()[0]
    if forced is not None:
        return "on" if forced == "auto" else forced
    raw = knobs.get_str("PADDLE_TPU_FUSION").strip().lower()
    if raw not in _FUSION_MODES:
        raise ValueError(
            f"PADDLE_TPU_FUSION={raw!r}: expected one of {_FUSION_MODES}")
    return "off" if raw == "off" else "on"


def enabled() -> bool:
    return mode() == "on"


def mm_quant() -> str:
    """Resolved quantized-matmul mode: "off", "int8" or "fp8"."""
    forced = _forced.get()[1]
    raw = forced if forced is not None else \
        knobs.get_str("PADDLE_TPU_MM_QUANT").strip().lower()
    if raw not in _QUANT_MODES:
        raise ValueError(
            f"PADDLE_TPU_MM_QUANT={raw!r}: expected one of {_QUANT_MODES}")
    if raw == "fp8" and not quant.fp8_supported():
        return "int8"
    return raw


@contextlib.contextmanager
def override(fusion=None, quant_mode=None):
    """Pin fusion / quant modes for the current context (trace scope)."""
    prev = _forced.get()
    tok = _forced.set((fusion if fusion is not None else prev[0],
                       quant_mode if quant_mode is not None else prev[1]))
    try:
        yield
    finally:
        _forced.reset(tok)


def route(op: str) -> bool:
    """Per-call-site dispatch decision + telemetry: True means take the
    fused path for ``op``, False means the verbatim fallback composition."""
    fused = enabled()
    from .. import observability as _obs

    if _obs.enabled():
        _obs.registry.counter(
            "fusion.fused_calls" if fused else "fusion.fallback_calls",
            tags={"op": op}).inc()
    return fused


def quant_route(op: str) -> str:
    """Quantized-matmul dispatch for an MLP matmul site: returns the
    resolved mode and counts the decision."""
    qm = mm_quant()
    if qm != "off":
        from .. import observability as _obs

        if _obs.enabled():
            _obs.registry.counter("fusion.quantized_matmuls",
                                  tags={"mode": qm, "op": op}).inc()
    return qm
