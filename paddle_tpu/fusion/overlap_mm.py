"""Decomposed computation–collective overlap for sharded matmuls
(PADDLE_TPU_TP_OVERLAP).

The TP/DP axes run matmul-then-collective serially: every column/row
parallel linear pays full collective latency after (or before) its GEMM.
Following T3 (arxiv 2401.16677) and fused computation-collective operations
(arxiv 2305.06942), this module decomposes those matmuls into ring/chunk
steps so the communication of one chunk rides inside the computation of the
next:

* :func:`all_gather_matmul` — column-parallel forward: instead of
  ``matmul(all_gather(x), w)``, the locally-held activation block is
  multiplied while the next rank's block arrives over a ``lax.ppermute``
  ring (one step per rank, each step itself row-chunked). Its custom VJP
  reproduces the monolithic gradient DAG: dx is the decomposed
  matmul-reduce-scatter of ``g @ w.T`` (the transpose of all-gather is
  reduce-scatter) and dw contracts the ring-regathered activations in one
  2D dot — bitwise equal to ``jax.vjp`` of the monolithic composition.
* :func:`matmul_reduce_scatter` — row-parallel forward: instead of
  ``psum_scatter(matmul(x, w))``, each destination block's partial product
  is computed just-in-time and added into an accumulator that rides the
  reverse ring, so every step overlaps one block GEMM with one permute.
  Its VJP runs the dual decomposed all-gather-matmul.

Numerics contract: splitting a matmul by output ROWS is bitwise-exact (each
output row is an independent dot product), and the ring all-gather is pure
data movement, so ``all_gather_matmul`` == monolithic composition bitwise
at any ring size. ``matmul_reduce_scatter`` splits only the already-sharded
contraction the monolithic ``psum_scatter`` also splits: the per-block sums
add the same operands in the same rank order, so it is bitwise vs the
monolithic sharded composition at 2 ranks and tolerance-equal beyond
(reduction association). tests/test_tp_overlap.py enforces both.

Knobs (read at trace time, same discipline as the fusion/quant knobs):

  - ``PADDLE_TPU_TP_OVERLAP=auto|on|pallas|off`` — ``auto`` (default)
    behaves as ``on``; ``off`` routes every wired call site through the
    original serial composition, restoring pre-overlap numerics
    byte-for-byte; ``pallas`` additionally fuses the ring step's remote
    DMA into a Pallas matmul kernel on TPU backends (elsewhere it falls
    back to the ``ppermute`` ring).
  - ``PADDLE_TPU_TP_OVERLAP_CHUNKS`` — row chunks per ring step
    (default 2). More chunks = finer overlap granularity, more launch
    overhead; chunk counts are clamped to divisors of the token dim.

The quantized path (PADDLE_TPU_MM_QUANT) composes: per-token activation
scales and per-channel weight scales are chunk-independent, so the chunked
int8/fp8 GEMM is bitwise equal to the unchunked one and overlap keeps the
PR 7 drift contract unchanged.
"""
from __future__ import annotations

import contextlib
import contextvars
import math

import jax
import jax.numpy as jnp

from ..config import knobs
from .quant import qmm

__all__ = [
    "mode", "enabled", "impl", "default_chunks", "override", "route",
    "all_gather_matmul", "matmul_reduce_scatter",
    "sharded_all_gather_matmul", "sharded_matmul_reduce_scatter",
    "chunked_mm", "region_mm", "overlap_linear",
]

_MODES = ("auto", "on", "pallas", "off")

# Per-context override so a trace scope (train-step build, test) can pin the
# overlap mode / chunk count, mirroring fusion._forced.
_forced: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_tp_overlap_forced", default=(None, None))


# ------------------------------------------------------------------ knobs
def mode() -> str:
    """Resolved overlap mode: "on", "pallas" or "off" ("auto" -> "on")."""
    forced = _forced.get()[0]
    raw = forced if forced is not None else \
        knobs.get_str("PADDLE_TPU_TP_OVERLAP").strip().lower()
    if raw not in _MODES:
        raise ValueError(
            f"PADDLE_TPU_TP_OVERLAP={raw!r}: expected one of {_MODES}")
    return "on" if raw == "auto" else raw


def enabled() -> bool:
    return mode() != "off"


def _raw_mode() -> str:
    """Unresolved mode: distinguishes explicit "on"/"pallas" from "auto"."""
    forced = _forced.get()[0]
    raw = forced if forced is not None else \
        knobs.get_str("PADDLE_TPU_TP_OVERLAP").strip().lower()
    return raw if raw in _MODES else "auto"


def impl() -> str:
    """Ring-step implementation: "pallas" only on TPU backends."""
    if mode() == "pallas" and jax.default_backend() == "tpu":
        return "pallas"
    return "ppermute"


def default_chunks() -> int:
    """Row chunks per ring step (PADDLE_TPU_TP_OVERLAP_CHUNKS, default 2)."""
    forced = _forced.get()[1]
    if forced is not None:
        return max(1, int(forced))
    return max(1, knobs.get_int("PADDLE_TPU_TP_OVERLAP_CHUNKS"))


@contextlib.contextmanager
def override(tp_overlap=None, chunks=None):
    """Pin overlap mode / chunk count for the current context. Forcing
    ``chunks`` also engages the model-level chunked path without an active
    mp mesh (how tests exercise overlap-on == off parity on one device)."""
    prev = _forced.get()
    tok = _forced.set((tp_overlap if tp_overlap is not None else prev[0],
                       chunks if chunks is not None else prev[1]))
    try:
        yield
    finally:
        _forced.reset(tok)


def route(op: str) -> bool:
    """Per-call-site overlap dispatch + telemetry: True means take the
    decomposed-overlap path for ``op``, False the serial composition."""
    m = mode()
    from .. import observability as _obs

    if _obs.enabled():
        _obs.registry.counter("tp.overlap_calls",
                              tags={"op": op, "mode": m}).inc()
    return m != "off"


def _note_chunks(chunks: int) -> None:
    from .. import observability as _obs

    if _obs.enabled():
        _obs.registry.gauge("tp.overlap_chunks").set(int(chunks))  # ptlint: disable=jit-purity (static chunk count)


def _note_ring_geometry(op: str, x, w, size: int) -> None:
    """Trace-time TP overlap-geometry note for the step profiler: each
    of the ring's ``size-1`` permute hops moves one x-sized buffer and
    rides inside one per-block GEMM. Static shapes only — never touches
    tracer values."""
    from ..observability import profiler as _profiler

    if not _profiler.profiling_enabled() or size <= 1:  # ptlint: disable=jit-purity (static profiling gate)
        return
    elems = 1
    for d in x.shape:
        elems *= int(d)
    hop_bytes = elems * jnp.dtype(x.dtype).itemsize
    gemm_flops = 2.0 * elems * int(w.shape[-1])  # ptlint: disable=jit-purity (static weight shape)
    _profiler.note_ring_overlap("tp", hop_bytes, gemm_flops, size - 1,
                                detail={"op": op})


# ------------------------------------------------------- chunked local GEMM
def _clamp_chunks(t: int, chunks: int) -> int:
    # largest divisor of the token dim not exceeding the requested count —
    # chunking must never change shapes, only split them
    return max(1, math.gcd(int(t), max(1, int(chunks))))  # ptlint: disable=jit-purity (trace-time shape/chunk config, never a tracer)


def _mm(a, w, quant_mode):
    return qmm(a, w, quant_mode) if quant_mode != "off" else jnp.matmul(a, w)


def _chunked_rows_mm(x, w, chunks, quant_mode="off"):
    """``x @ w`` split by leading-dim row chunks — bitwise equal to the
    monolithic matmul (each output row is an independent dot product)."""
    chunks = _clamp_chunks(x.shape[0], chunks)
    if chunks <= 1:
        return _mm(x, w, quant_mode)
    return jnp.concatenate(
        [_mm(c, w, quant_mode) for c in jnp.split(x, chunks, axis=0)], axis=0)


def _flat_dw(x, g):
    """dw = x^T g contracted over all leading dims as ONE 2D dot — the
    form that is bitwise equal to ``jax.vjp(jnp.matmul)``'s dw."""
    k, n = x.shape[-1], g.shape[-1]
    return jnp.matmul(x.reshape(-1, k).T, g.reshape(-1, n))


def _chunked_dx(g, w, chunks):
    """dx = g @ w^T split by row chunks (bitwise equal to the vjp dx)."""
    return _chunked_rows_mm(g, jnp.swapaxes(w, -1, -2), chunks)


# ------------------------------------------------------------- ring steps
def _ppermute_step(x, axis_name, size):
    # forward ring step: rank r receives rank (r-1)'s buffer
    return jax.lax.ppermute(
        x, axis_name, perm=[(i, (i + 1) % size) for i in range(size)])


def _pallas_mm_step(buf, w, axis_name, size):
    """One fused ring step as a Pallas kernel (TPU only): kick off the
    remote DMA of ``buf`` to the next rank, compute ``buf @ w`` while the
    transfer is in flight, then wait. Returns ``(partial, next_buf)``.

    PR 6 ring-kernel house style (pipeline/transport.py): logical device
    ids, ANY memory space for the DMA operands, DMA semaphore scratch, one
    shared ``collective_id``. The activation block is staged HBM->VMEM
    with a local async copy so the MXU reads VMEM while the ICI transfer
    proceeds from HBM.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k, n = w.shape[-2], w.shape[-1]
    part_shape = buf.shape[:-1] + (n,)
    out_dtype = jnp.result_type(buf.dtype, w.dtype)

    def kernel(x_ref, w_ref, out_ref, nxt_ref, x_vmem, send_sem, recv_sem,
               copy_sem):
        my_id = jax.lax.axis_index(axis_name)
        neighbor = jax.lax.rem(my_id + 1, size)
        rdma = pltpu.make_async_remote_copy(
            x_ref, nxt_ref, send_sem, recv_sem,
            device_id=(neighbor,),
            device_id_type=pltpu.DeviceIdType.LOGICAL)
        rdma.start()
        # stage the local block into VMEM and run the GEMM while the
        # remote transfer is in flight
        stage = pltpu.make_async_copy(x_ref, x_vmem, copy_sem)
        stage.start()
        stage.wait()
        out_ref[...] = jnp.dot(
            x_vmem[...].reshape(-1, k), w_ref[...],
            preferred_element_type=jnp.float32,
        ).astype(out_ref.dtype).reshape(part_shape)
        rdma.wait()

    return pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct(part_shape, out_dtype),
                   jax.ShapeDtypeStruct(buf.shape, buf.dtype)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=(pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)),
        scratch_shapes=[pltpu.VMEM(buf.shape, buf.dtype),
                        pltpu.SemaphoreType.DMA, pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA],
        compiler_params=pltpu.TPUCompilerParams(collective_id=0),
    )(buf, w)


# ----------------------------------------------------- ring primitive cores
def _ring_gather(x, axis_name, size):
    """All-gather along the leading dim via ring steps — pure data
    movement, bitwise equal to ``lax.all_gather(..., tiled=True)``."""
    t = x.shape[0]
    r = jax.lax.axis_index(axis_name)
    out = jnp.zeros((t * size,) + x.shape[1:], x.dtype)
    buf = x
    for step in range(size):
        src = jax.lax.rem(r - step + size, size)
        nxt = _ppermute_step(buf, axis_name, size) if step < size - 1 \
            else None
        out = jax.lax.dynamic_update_slice_in_dim(out, buf, src * t, axis=0)
        if nxt is not None:
            buf = nxt
    return out


def _agmm_impl(x, w, axis_name, size, chunks, quant_mode, use_pallas):
    """Ring all-gather-matmul forward: rank r multiplies block (r-step)
    at step ``step`` while shifting its buffer one hop, so every permute
    rides inside a GEMM. Output holds ALL token blocks (gathered) against
    this rank's weight columns."""
    t = x.shape[0]
    _note_ring_geometry("agmm", x, w, size)
    r = jax.lax.axis_index(axis_name)
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    out = jnp.zeros((t * size,) + x.shape[1:-1] + (w.shape[-1],), out_dtype)
    buf = x
    for step in range(size):
        src = jax.lax.rem(r - step + size, size)
        if use_pallas and step < size - 1:
            part, nxt = _pallas_mm_step(buf, w, axis_name, size)
        else:
            nxt = _ppermute_step(buf, axis_name, size) if step < size - 1 \
                else None
            part = _chunked_rows_mm(buf, w, chunks, quant_mode)
        out = jax.lax.dynamic_update_slice_in_dim(
            out, part.astype(out_dtype), src * t, axis=0)
        if nxt is not None:
            buf = nxt
    return out


def _mmrs_impl(x, w, axis_name, size, chunks, quant_mode, use_pallas):
    """Ring matmul-reduce-scatter forward: the accumulator rides the ring
    while each rank computes the partial product for the block the
    accumulator will need next — per-block sums add the same operands in
    the same rank order as ``psum_scatter(matmul(x, w))``."""
    big_t = x.shape[0]
    t = big_t // size
    _note_ring_geometry("mmrs", x, w, size)
    r = jax.lax.axis_index(axis_name)

    def partial(block_idx):
        rows = jax.lax.dynamic_slice_in_dim(x, block_idx * t, t, axis=0)
        if use_pallas:
            # the fused kernel computes rows @ w; the permute rides on the
            # accumulator below, so only the GEMM goes through Pallas here
            return _chunked_rows_mm(rows, w, 1, quant_mode)
        return _chunked_rows_mm(rows, w, chunks, quant_mode)

    acc = partial(jax.lax.rem(r + size - 1, size))
    for step in range(1, size):
        acc = _ppermute_step(acc, axis_name, size)
        acc = acc + partial(jax.lax.rem(r - step + size - 1, size))
    return acc


# ------------------------------------------------------- public primitives
def all_gather_matmul(x, w, *, axis_name=None, axis_size=1, chunks=None,
                      quant_mode="off"):
    """Decomposed ``matmul(all_gather(x, tiled), w)`` (column-parallel
    forward / row-parallel backward).

    ``x``: this rank's token block ``[t, ..., k]``; ``w``: this rank's
    weight columns ``[k, n_local]``; returns ``[t*size, ..., n_local]``.
    Must be called inside a ``shard_map`` body mapped over ``axis_name``
    (or with ``axis_size <= 1``, where it degenerates to the row-chunked
    local matmul — the single-device form the bitwise tests pin down).

    The custom VJP reproduces the monolithic gradient DAG: the transpose
    of all-gather is reduce-scatter, so dx runs the dual decomposed
    :func:`matmul_reduce_scatter` ring on ``g @ w.T``; dw regathers the
    activations over the ring (pure data movement) and contracts in one
    2D dot. Gradients are straight-through full precision under quant.
    """
    chunks = default_chunks() if chunks is None else max(1, int(chunks))  # ptlint: disable=jit-purity (static chunk count)
    _note_chunks(chunks)
    use_pallas = impl() == "pallas" and quant_mode == "off"

    if axis_name is None or axis_size <= 1:
        @jax.custom_vjp
        def local(x, w):
            return _chunked_rows_mm(x, w, chunks, quant_mode)

        def local_fwd(x, w):
            return local(x, w), (x, w)

        def local_bwd(res, g):
            x, w = res
            g = g.astype(x.dtype)
            return _chunked_dx(g, w, chunks), _flat_dw(x, g).astype(w.dtype)

        local.defvjp(local_fwd, local_bwd)
        return local(x, w)

    size = int(axis_size)  # ptlint: disable=jit-purity (static mesh-axis size)

    @jax.custom_vjp
    def agmm(x, w):
        return _agmm_impl(x, w, axis_name, size, chunks, quant_mode,
                          use_pallas)

    def agmm_fwd(x, w):
        return agmm(x, w), (x, w)

    def agmm_bwd(res, g):
        x, w = res
        g = g.astype(x.dtype)
        # dx: transpose of all-gather is reduce-scatter -> dual ring
        dx = _mmrs_impl(g, jnp.swapaxes(w, -1, -2), axis_name, size,
                        chunks, "off", False)
        # dw: regather the activations (bitwise == lax.all_gather), one dot
        dw = _flat_dw(_ring_gather(x, axis_name, size), g).astype(w.dtype)
        return dx, dw

    agmm.defvjp(agmm_fwd, agmm_bwd)
    return agmm(x, w)


def matmul_reduce_scatter(x, w, *, axis_name=None, axis_size=1, chunks=None,
                          quant_mode="off"):
    """Decomposed ``psum_scatter(matmul(x, w), tiled)`` (row-parallel
    forward / column-parallel backward).

    ``x``: all token blocks against this rank's contraction slice
    ``[T, ..., k_local]``; ``w``: this rank's weight rows ``[k_local, n]``;
    returns this rank's token block ``[T/size, ..., n]``. Must run inside
    ``shard_map`` over ``axis_name`` (``axis_size <= 1`` degenerates to
    the row-chunked local matmul).

    VJP: the transpose of reduce-scatter is all-gather, so dx runs the
    dual decomposed :func:`all_gather_matmul` ring on ``g @ w.T`` and dw
    contracts the local activations against the ring-gathered output
    cotangent in one 2D dot.
    """
    chunks = default_chunks() if chunks is None else max(1, int(chunks))  # ptlint: disable=jit-purity (static chunk count)
    _note_chunks(chunks)
    use_pallas = impl() == "pallas" and quant_mode == "off"

    if axis_name is None or axis_size <= 1:
        return all_gather_matmul(x, w, axis_name=None, axis_size=1,
                                 chunks=chunks, quant_mode=quant_mode)

    size = int(axis_size)  # ptlint: disable=jit-purity (static mesh-axis size)

    @jax.custom_vjp
    def mmrs(x, w):
        return _mmrs_impl(x, w, axis_name, size, chunks, quant_mode,
                          use_pallas)

    def mmrs_fwd(x, w):
        return mmrs(x, w), (x, w)

    def mmrs_bwd(res, g):
        x, w = res
        g = g.astype(x.dtype)
        # dx: transpose of reduce-scatter is all-gather -> dual ring
        dx = _agmm_impl(g, jnp.swapaxes(w, -1, -2), axis_name, size,
                        chunks, "off", False)
        dw = _flat_dw(x, _ring_gather(g, axis_name, size)).astype(w.dtype)
        return dx, dw

    mmrs.defvjp(mmrs_fwd, mmrs_bwd)
    return mmrs(x, w)


# --------------------------------------------------- shard_map conveniences
def _shard_map(fn, mesh, in_specs, out_specs):
    from jax.experimental.shard_map import shard_map

    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=False)


def sharded_all_gather_matmul(x, w, *, mesh, axis_name="mp", chunks=None,
                              quant_mode="off"):
    """Global-array wrapper: ``x`` sharded on its leading (token) dim,
    ``w`` on its last dim; output gathered on tokens, sharded on columns."""
    from jax.sharding import PartitionSpec as P

    size = int(mesh.shape[axis_name])  # ptlint: disable=jit-purity (static mesh-axis size)
    x_spec = P(axis_name, *([None] * (x.ndim - 1)))
    w_spec = P(*([None] * (w.ndim - 1)), axis_name)
    out_spec = P(*([None] * (x.ndim - 1)), axis_name)

    def body(xl, wl):
        return all_gather_matmul(xl, wl, axis_name=axis_name,
                                 axis_size=size, chunks=chunks,
                                 quant_mode=quant_mode)

    return _shard_map(body, mesh, (x_spec, w_spec), out_spec)(x, w)


def sharded_matmul_reduce_scatter(x, w, *, mesh, axis_name="mp",
                                  chunks=None, quant_mode="off"):
    """Global-array wrapper: ``x`` sharded on its last (contraction) dim,
    ``w`` on its first dim; output sharded on the leading (token) dim."""
    from jax.sharding import PartitionSpec as P

    size = int(mesh.shape[axis_name])  # ptlint: disable=jit-purity (static mesh-axis size)
    x_spec = P(*([None] * (x.ndim - 1)), axis_name)
    w_spec = P(axis_name, *([None] * (w.ndim - 1)))
    out_spec = P(axis_name, *([None] * (x.ndim - 1)))

    def body(xl, wl):
        return matmul_reduce_scatter(xl, wl, axis_name=axis_name,
                                     axis_size=size, chunks=chunks,
                                     quant_mode=quant_mode)

    return _shard_map(body, mesh, (x_spec, w_spec), out_spec)(x, w)


# ------------------------------------------------- GSPMD model-level path
def chunked_mm(a, w, chunks=None, quant_mode="off"):
    """Raw-array decomposed matmul for jit/GSPMD call sites.

    Flattens leading dims to tokens and splits both the forward GEMM and
    the backward dx GEMM into ``chunks`` independent row blocks, so when
    ``w`` carries an mp sharding GSPMD emits one small collective per
    chunk riding inside the next chunk's GEMM instead of one big serial
    collective after the matmul. dw stays a single 2D dot (chunking the
    contraction would change the reduction order). Bitwise equal to
    ``jnp.matmul`` / ``qmm`` fwd and bwd — asserted by
    tests/test_tp_overlap.py.
    """
    chunks = default_chunks() if chunks is None else max(1, int(chunks))  # ptlint: disable=jit-purity (static chunk count)
    _note_chunks(chunks)
    lead = a.shape[:-1]
    k, n = a.shape[-1], w.shape[-1]

    @jax.custom_vjp
    def cmm(a, w):
        flat = _chunked_rows_mm(a.reshape(-1, k), w, chunks, quant_mode)
        return flat.reshape(lead + (n,))

    def cmm_fwd(a, w):
        return cmm(a, w), (a, w)

    def cmm_bwd(res, g):
        a, w = res
        g = g.astype(a.dtype)
        dx = _chunked_dx(g.reshape(-1, n), w, chunks).reshape(lead + (k,))
        return dx, _flat_dw(a, g).astype(w.dtype)

    cmm.defvjp(cmm_fwd, cmm_bwd)
    return cmm(a, w)


def _mesh_engaged() -> bool:
    from ..distributed.auto_parallel.constraint import _active_jax_mesh

    mesh = _active_jax_mesh()
    return (mesh is not None and "mp" in mesh.axis_names
            and mesh.shape["mp"] > 1)


def region_mm(a, w, quant_mode="off", op="fused_region"):
    """Overlap-aware matmul for fused epilogue regions (raw arrays).

    Inside ``fusion.linear_gelu`` / ``fusion.swiglu_linear`` the producing
    GEMM is the serial-collective hazard; when overlap routing engages this
    swaps in the decomposed :func:`chunked_mm` (bitwise equal), otherwise
    the plain ``jnp.matmul`` / ``qmm`` the region always used.

    The GSPMD rewrite engages only on an EXPLICIT opt-in — forced chunks
    (:func:`override`) or mode "on"/"pallas" with an active mp mesh —
    never under the default "auto": reshaping the GEMM changes how GSPMD
    partitions the surrounding trace, so default compiled programs must
    stay byte-identical to pre-overlap builds. (The eager fleet layers,
    whose collectives are real calls rather than compiler-placed, do
    overlap under "auto" — see distributed/tp_overlap.py.)
    """
    if enabled() and (_forced.get()[1] is not None or
                      (_raw_mode() != "auto" and _mesh_engaged())) \
            and route(op):
        return chunked_mm(a, w, None, quant_mode)
    return _mm(a, w, quant_mode)


def overlap_linear(x, weight, bias=None, *, op, quant_mode="off"):
    """Tensor-level decomposed linear for the model call sites.

    Returns the chunked-overlap ``x @ W (+ b)`` when overlap routing says
    so — an explicit mode ("on"/"pallas") with an active mp mesh of
    size > 1, or a forced chunk count from :func:`override` (how
    single-device tests engage the path) — else ``None`` so the caller
    runs its verbatim serial composition. Like :func:`region_mm`, the
    default "auto" never rewrites compiled model traces.
    """
    if not enabled():
        return None
    if _forced.get()[1] is None and \
            not (_raw_mode() != "auto" and _mesh_engaged()):
        return None
    if not route(op):
        return None
    from ..core.autograd import run_op
    from ..ops._helpers import as_tensor

    chunks = default_chunks()
    ts = [as_tensor(x), as_tensor(weight)]
    if bias is not None:
        ts.append(as_tensor(bias))
        return run_op(lambda a, w, b: chunked_mm(a, w, chunks, quant_mode)
                      + b,
                      ts, name="tp_overlap_linear",
                      attrs={"op": op, "chunks": chunks, "quant": quant_mode})
    return run_op(lambda a, w: chunked_mm(a, w, chunks, quant_mode), ts,
                  name="tp_overlap_linear",
                  attrs={"op": op, "chunks": chunks, "quant": quant_mode})
