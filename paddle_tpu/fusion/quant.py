"""Quantized matmul hot path for the MLP blocks (PADDLE_TPU_MM_QUANT).

int8: symmetric per-token activation scales (absmax over the contraction
dim) and per-output-channel weight scales, int8 x int8 -> int32 MXU
accumulation via ``lax.dot_general(preferred_element_type=int32)``, one
fused rescale epilogue. fp8 (where ``jnp.float8_e4m3fn`` exists): same
scale scheme mapped to the e4m3 range with fp32 accumulation.

Gradients are straight-through: the backward of ``qmm`` is the vjp of the
full-precision matmul (the same STE scheme as quantization/functional.py
``fake_quant_dequant``), so training sees quantization error only in the
forward values — the loss-drift bound in tests/test_fusion.py is enforced
against this contract.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.autograd import run_op
from ..ops._helpers import as_tensor

__all__ = ["qmm", "quantized_linear", "fp8_supported",
           "int8_matmul", "fp8_matmul"]

_FP8 = getattr(jnp, "float8_e4m3fn", None)
_FP8_MAX = 448.0  # e4m3fn finite max


def fp8_supported() -> bool:
    return _FP8 is not None


def _row_scale(a, limit):
    amax = jnp.max(jnp.abs(a), axis=-1, keepdims=True)
    return jnp.maximum(amax, 1e-8).astype(jnp.float32) / limit


def _col_scale(w, limit):
    amax = jnp.max(jnp.abs(w), axis=0, keepdims=True)
    return jnp.maximum(amax, 1e-8).astype(jnp.float32) / limit


def int8_matmul(a, w):
    """[..., K] @ [K, N] with dynamic per-token / per-channel int8 scales."""
    sa = _row_scale(a, 127.0)
    sw = _col_scale(w, 127.0)
    qa = jnp.clip(jnp.round(a.astype(jnp.float32) / sa), -127, 127) \
        .astype(jnp.int8)
    qw = jnp.clip(jnp.round(w.astype(jnp.float32) / sw), -127, 127) \
        .astype(jnp.int8)
    acc = jax.lax.dot_general(
        qa, qw, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return (acc.astype(jnp.float32) * (sa * sw)).astype(a.dtype)


def fp8_matmul(a, w):
    """[..., K] @ [K, N] through e4m3 with per-token / per-channel scales."""
    sa = _row_scale(a, _FP8_MAX)
    sw = _col_scale(w, _FP8_MAX)
    qa = (a.astype(jnp.float32) / sa).astype(_FP8)
    qw = (w.astype(jnp.float32) / sw).astype(_FP8)
    acc = jax.lax.dot_general(
        qa, qw, (((qa.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (acc * (sa * sw)).astype(a.dtype)


@jax.custom_vjp
def _qmm_int8(a, w):
    return int8_matmul(a, w)


@jax.custom_vjp
def _qmm_fp8(a, w):
    return fp8_matmul(a, w)


def _qmm_fwd_int8(a, w):
    return int8_matmul(a, w), (a, w)


def _qmm_fwd_fp8(a, w):
    return fp8_matmul(a, w), (a, w)


def _qmm_bwd(res, g):
    # straight-through: gradients of the full-precision matmul
    a, w = res
    _, vjp = jax.vjp(lambda x, y: jnp.matmul(x, y), a, w)
    return vjp(g.astype(a.dtype))


_qmm_int8.defvjp(_qmm_fwd_int8, _qmm_bwd)
_qmm_fp8.defvjp(_qmm_fwd_fp8, _qmm_bwd)


def qmm(a, w, mode="int8"):
    """Raw-array quantized matmul dispatch (usable inside fused regions)."""
    if mode == "fp8":
        if not fp8_supported():
            raise RuntimeError("fp8 dtypes unavailable in this jax build")
        return _qmm_fp8(a, w)
    if mode == "int8":
        return _qmm_int8(a, w)
    raise ValueError(f"unknown quantized-matmul mode {mode!r}")


def quantized_linear(x, weight, bias=None, mode="int8"):
    """Tensor-level y = qmm(x, W) (+ b). Weight layout [in, out].

    The GEMM goes through the overlap-aware dispatch
    (:func:`..overlap_mm.region_mm`): per-token/per-channel scales are
    chunk-independent, so the decomposed int8/fp8 matmul is bitwise equal
    to the monolithic one while its collectives ride the chunk loop."""
    from .overlap_mm import region_mm

    ts = [as_tensor(x), as_tensor(weight)]
    if bias is not None:
        ts.append(as_tensor(bias))
        return run_op(lambda a, w, b: region_mm(a, w, mode,
                                                op="quant_linear") + b,
                      ts, name="quant_linear", attrs={"mode": mode})
    return run_op(lambda a, w: region_mm(a, w, mode, op="quant_linear"),
                  ts, name="quant_linear", attrs={"mode": mode})
